"""swshard (DESIGN.md §20): sharding -> sharding redistribution.

The acceptance contract (ISSUE 12): ``redistribute()`` moves an array
between two different NamedShardings across process/rank boundaries with
the result bit-identical to the utils/checkpoint.py restore oracle, peak
staging stays O(shard) per host (asserted via the live
``reshard_staging_peak`` gauge), schedule tags live in the reserved
namespace (collision-checked leases), the schedule survives a
mid-transfer connection kill under ``STARWAY_SESSION=1``, and the
fabric's wire is unchanged (HELLO parity before/after reshard use).

Planner properties (rounds, budget, determinism, coverage) are pinned
white-box -- the planner is pure data, no jax, no sockets.
"""

import asyncio
import json
import multiprocessing as mp
import socket

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from starway_tpu import Client, Server
from starway_tpu.core import frames
from starway_tpu.reshard import (
    ArrayRef,
    Block,
    ShardSpec,
    build_plan,
    executor,
    lease,
    redistribute,
    tags,
)
from starway_tpu.testing.faults import FaultProxy
from starway_tpu.utils.checkpoint import restore_pytree, save_pytree

pytestmark = pytest.mark.asyncio

ADDR = "127.0.0.1"
MASK = (1 << 64) - 1


# ---------------------------------------------------------------- planner


def test_plan_transpose_rounds_and_bound():
    """8-rank row->column retile: one transfer per pair, 7 rounds (the
    optimal all-to-all decomposition), per-rank staging <= 2 x budget,
    and exactly the off-diagonal volume on the wire."""
    n = 64
    src = ShardSpec((n, n), 4, [Block(r, ((r * 8, (r + 1) * 8), (0, n)))
                                for r in range(8)])
    dst = ShardSpec((n, n), 4, [Block(r, ((0, n), (r * 8, (r + 1) * 8)))
                                for r in range(8)])
    plan = build_plan(src, dst)
    assert plan.rounds == 7 and len(plan.transfers) == 56
    assert plan.total_wire_nbytes() == n * n * 4 * 7 // 8
    for r in range(8):
        assert plan.peak_staging(r) <= 2 * plan.budget
        assert len(plan.local_pieces.get(r, [])) == 1  # the diagonal
    for rnd in range(plan.rounds):
        tx = [t.src for t in plan.transfers if t.round == rnd]
        rx = [t.dst for t in plan.transfers if t.round == rnd]
        assert len(tx) == len(set(tx)), "two sends from one rank in a round"
        assert len(rx) == len(set(rx)), "two recvs into one rank in a round"


def test_plan_replication_and_determinism():
    n = 64
    repl = ShardSpec((n,), 1, [Block(r, ((0, n),)) for r in range(4)])
    shard = ShardSpec((n,), 1, [Block(r, ((r * 16, (r + 1) * 16),))
                                for r in range(4)])
    # replicated -> sharded: every rank already holds its slice.
    assert build_plan(repl, shard).transfers == []
    # sharded -> replicated: each rank fetches the 3 remote quarters.
    plan = build_plan(shard, repl)
    assert plan.total_wire_nbytes() == 3 * n
    again = build_plan(shard, repl)
    assert [(t.src, t.dst, t.tag_off, t.round) for t in plan.transfers] == \
        [(t.src, t.dst, t.tag_off, t.round) for t in again.transfers]
    # a source that does not cover the destination is an error, not a
    # silent partial schedule.
    with pytest.raises(ValueError, match="does not cover"):
        build_plan(ShardSpec((n,), 1, [Block(0, ((0, 32),))]), repl)


def test_plan_budget_splits_transfers():
    """A pair's pieces pack into <=budget messages: 8 source rows to one
    destination rank split at one-shard granularity."""
    src = ShardSpec((8, 1024), 1, [Block(0, ((r, r + 1), (0, 1024)))
                                   for r in range(8)])
    dst = ShardSpec((8, 1024), 1, [Block(1, ((0, 8), (0, 1024)))])
    plan = build_plan(src, dst)  # budget = dst shard = whole array
    assert plan.budget == 8 * 1024
    small = build_plan(src, dst, budget=1024)
    assert len(small.transfers) == 8 and small.rounds == 8
    assert all(t.nbytes <= 1024 for t in small.transfers)


# -------------------------------------------------------------- tag leases


def test_tag_lease_reserved_and_collision():
    assert tags.is_reshard_tag(tags.RESHARD_TAG_BASE)
    assert not tags.is_reshard_tag(0x2B40)  # bench tags stay user-space
    with lease(5) as a:
        assert tags.is_reshard_tag(a.ctl_tag(0))
        assert tags.is_reshard_tag(a.data_tag(0))
        assert a.data_tag(0) != a.ctl_tag(0)
        # Same slot while live: the collision this registry exists for.
        with pytest.raises(RuntimeError, match="already live"):
            lease(5)
        # Distinct slots never overlap tag ranges.
        with lease(6) as b:
            span_a = {a.ctl_tag(0), a.data_tag(tags.SLOT_SPAN
                                               - tags.CTL_TAGS - 1)}
            assert all(not (b.base <= t < b.base + tags.SLOT_SPAN)
                       for t in span_a)
    # Released: the slot is reusable.
    lease(5).release()
    # Out-of-range indices fail loudly instead of spilling.
    with lease(7) as c:
        with pytest.raises(ValueError):
            c.data_tag(tags.SLOT_SPAN)
        with pytest.raises(ValueError):
            c.ctl_tag(tags.CTL_TAGS)


# ------------------------------------------------- local retile vs oracle

# (src spec, dst spec) PartitionSpec pairs over an 8-device 1-axis mesh:
# replicated->sharded, sharded->replicated, transposed ownership, and a
# partial-replication reshard over a 2x4 mesh.
LOCAL_PAIRS = [
    (P(None, None), P("x", None)),
    (P("x", None), P(None, None)),
    (P("x", None), P(None, "x")),
    (P(None, "x"), P("x", None)),
]


@pytest.mark.parametrize("src_spec,dst_spec", LOCAL_PAIRS)
async def test_local_retile_matches_checkpoint_oracle(tmp_path, src_spec,
                                                      dst_spec):
    """Single-process retile over the virtual 8-device mesh: the
    redistributed array is bit-identical to saving under the source
    sharding and restoring onto the destination sharding
    (utils/checkpoint.py, the ISSUE-12 correctness oracle)."""
    mesh = Mesh(np.array(jax.devices()).reshape(8), ("x",))
    src_sh = NamedSharding(mesh, src_spec)
    dst_sh = NamedSharding(mesh, dst_spec)
    x = jnp.arange(16 * 64, dtype=jnp.float32).reshape(16, 64)
    xs = jax.device_put(x, src_sh)

    save_pytree(str(tmp_path / "ck"), {"w": xs})
    res = await redistribute(xs, dst_sh)
    out = res.array
    assert out.sharding.is_equivalent_to(dst_sh, out.ndim)

    like = {"w": jax.device_put(jnp.zeros_like(x), dst_sh)}
    oracle = restore_pytree(str(tmp_path / "ck"), like)["w"]
    np.testing.assert_array_equal(np.asarray(out), np.asarray(oracle))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


async def test_partial_replication_retile():
    """2x4 mesh, P('x') -> P(None, 'y'): partially replicated source
    blocks pick one holder per piece and the result is exact."""
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("x", "y"))
    src_sh = NamedSharding(mesh, P("x"))        # replicated over y
    dst_sh = NamedSharding(mesh, P(None, "y"))  # replicated over x
    x = jnp.arange(8 * 12, dtype=jnp.int32).reshape(8, 12)
    res = await redistribute(jax.device_put(x, src_sh), dst_sh)
    np.testing.assert_array_equal(np.asarray(res.array), np.asarray(x))


# ------------------------------------- cross-rank over the fabric (1 proc)

ENGINE_PAIRS = ["py-py", "native-native", "py-native", "native-py"]


def _need_native(*engines):
    if "native" in engines:
        from starway_tpu.core import native

        if not native.available():
            pytest.skip("native engine unavailable (no toolchain)")


def _split_rank_of(dev):
    """Simulated 2-rank ownership of the 8-device mesh: devices 0-3 are
    rank 0, devices 4-7 rank 1."""
    return 0 if dev.id < 4 else 1


def _two_rank_shardings():
    devs = jax.devices()
    mesh0 = Mesh(np.array(devs[:4]), ("x",))
    mesh1 = Mesh(np.array(devs[4:]), ("x",))
    return (NamedSharding(mesh0, P("x", None)),
            NamedSharding(mesh1, P(None, "x")))


class _SinkPort:
    def __init__(self, server, endpoint=None):
        self._s = server
        self._ep = endpoint or next(iter(server.list_clients()))

    def asend(self, buf, tag):
        return self._s.asend(self._ep, buf, tag)

    def arecv(self, buf, tag, mask=MASK):
        return self._s.arecv(buf, tag, mask)

    def aflush(self):
        return self._s.aflush_ep(self._ep)


@pytest.mark.parametrize("pairing", ENGINE_PAIRS)
async def test_cross_rank_redistribute_all_pairings(pairing, port,
                                                    monkeypatch):
    """Two simulated ranks exchanging over a real TCP conn, all four
    engine pairings (the mixed py<->native interop pin): source rows on
    rank 0's devices land column-sharded on rank 1's devices,
    bit-exact, with peak transfer staging inside the §20 bound."""
    s_eng, c_eng = pairing.split("-")
    _need_native(s_eng, c_eng)
    monkeypatch.setenv("STARWAY_TLS", "tcp")
    src_sh, dst_sh = _two_rank_shardings()
    shape = (16, 4096)
    x = jnp.arange(np.prod(shape), dtype=jnp.float32).reshape(shape)
    xs = jax.device_put(x, src_sh)

    monkeypatch.setenv("STARWAY_NATIVE", "1" if s_eng == "native" else "0")
    server = Server()
    server.listen(ADDR, port)
    monkeypatch.setenv("STARWAY_NATIVE", "1" if c_eng == "native" else "0")
    client = Client()
    try:
        await asyncio.wait_for(client.aconnect(ADDR, port), 30)
        executor.reset_staging_peak()
        with lease() as L:
            res0, res1 = await asyncio.gather(
                redistribute(xs, None, {1: client}, rank=0,
                             rank_of=_split_rank_of, lease=L),
                redistribute(ArrayRef(shape, np.float32), dst_sh,
                             {0: _SinkPort(server)}, rank=1,
                             rank_of=_split_rank_of, lease=L))
        out = res1.array
        np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
        assert out.sharding.is_equivalent_to(dst_sh, out.ndim)
        # §20 memory bound via the live gauge: both simulated ranks run
        # in this one process, so the host bound is 2 x (send + recv).
        peak = executor.staging_snapshot()["peak"]
        assert peak <= 2 * res1.stats["peak_staging_bound"], res1.stats
        assert res1.stats["rx_bytes"] > 0 and res0.stats["tx_bytes"] > 0
    finally:
        try:
            await asyncio.wait_for(client.aclose(), 15)
        finally:
            await asyncio.wait_for(server.aclose(), 15)


async def test_cross_rank_via_device_payloads(port, monkeypatch):
    """via='device': transfers ride device.py's DevicePayload/
    DeviceBuffer protocols instead of host staging buffers."""
    monkeypatch.setenv("STARWAY_TLS", "tcp")
    monkeypatch.setenv("STARWAY_NATIVE", "0")
    src_sh, dst_sh = _two_rank_shardings()
    shape = (8, 1024)
    x = jnp.arange(np.prod(shape), dtype=jnp.bfloat16).reshape(shape)
    xs = jax.device_put(x, src_sh)
    server = Server()
    server.listen(ADDR, port)
    client = Client()
    try:
        await asyncio.wait_for(client.aconnect(ADDR, port), 30)
        with lease() as L:
            _, res1 = await asyncio.gather(
                redistribute(xs, None, {1: client}, rank=0,
                             rank_of=_split_rank_of, lease=L, via="device"),
                redistribute(ArrayRef(shape, jnp.bfloat16), dst_sh,
                             {0: _SinkPort(server)}, rank=1,
                             rank_of=_split_rank_of, lease=L, via="device"))
        np.testing.assert_array_equal(
            np.asarray(res1.array).astype(np.float32),
            np.asarray(x).astype(np.float32))
    finally:
        try:
            await asyncio.wait_for(client.aclose(), 15)
        finally:
            await asyncio.wait_for(server.aclose(), 15)


# ------------------------------------------------ two real processes


def _child_rank1(port, tmpdir, q):
    """Rank 1 in its own process: its 'mesh' is its OWN 8 CPU devices --
    a different process set than the parent's -- and the spec exchange
    over the fabric is the only coordination."""
    import os
    import traceback

    os.environ["STARWAY_TLS"] = "tcp"
    os.environ["STARWAY_NATIVE"] = "0"
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
        import asyncio

        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from starway_tpu import Client
        from starway_tpu.reshard import ArrayRef, executor, redistribute
        from starway_tpu.utils.checkpoint import restore_pytree

        shape = (16, 4096)
        mesh = Mesh(np.array(jax.devices()).reshape(8), ("x",))
        dst_sh = NamedSharding(mesh, P(None, "x"))

        async def run():
            client = Client()
            for _ in range(120):
                try:
                    await client.aconnect(ADDR, port)
                    break
                except Exception:
                    client = Client()
                    await asyncio.sleep(0.25)
            res = await redistribute(
                ArrayRef(shape, np.float32), dst_sh, {0: client},
                rank=1, rank_of=lambda d: 1, lease_slot=3,
                round_timeout=60)
            out = res.array
            # Oracle: the checkpoint the parent saved under the SOURCE
            # sharding, restored onto THIS process's dst sharding.
            like = {"w": jax.device_put(
                jnp.zeros(shape, dtype=jnp.float32), dst_sh)}
            oracle = restore_pytree(os.path.join(tmpdir, "ck"), like)["w"]
            if not np.array_equal(np.asarray(out), np.asarray(oracle)):
                raise AssertionError("redistributed != checkpoint restore")
            peak = executor.staging_snapshot()["peak"]
            bound = res.stats["peak_staging_bound"]
            if peak > bound:
                raise AssertionError(f"staging {peak} > bound {bound}")
            await client.aclose()
            return {"ok": True, "peak": peak, "bound": bound,
                    "rounds": res.stats["rounds"]}

        q.put(asyncio.run(run()))
    except Exception:
        q.put({"ok": False, "error": traceback.format_exc()})


async def test_redistribute_across_two_processes(port, tmp_path,
                                                 monkeypatch):
    """The acceptance scenario: an array moves between two different
    NamedShardings across 2 OS processes over the fabric, bit-identical
    to the checkpoint-restore oracle, with each host's measured peak
    staging inside the O(shard) bound (the child asserts its own
    gauge)."""
    monkeypatch.setenv("STARWAY_TLS", "tcp")
    monkeypatch.setenv("STARWAY_NATIVE", "0")
    shape = (16, 4096)
    mesh = Mesh(np.array(jax.devices()).reshape(8), ("x",))
    src_sh = NamedSharding(mesh, P("x", None))
    x = jnp.arange(np.prod(shape), dtype=jnp.float32).reshape(shape)
    xs = jax.device_put(x, src_sh)
    save_pytree(str(tmp_path / "ck"), {"w": xs})

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    proc = ctx.Process(target=_child_rank1, args=(port, str(tmp_path), q),
                       daemon=True)
    server = Server()
    server.listen(ADDR, port)
    proc.start()
    try:
        for _ in range(600):
            if server.list_clients():
                break
            await asyncio.sleep(0.1)
        assert server.list_clients(), "child never connected"
        executor.reset_staging_peak()
        res0 = await redistribute(
            xs, None, {1: _SinkPort(server)}, rank=0,
            rank_of=lambda d: 0, lease_slot=3, round_timeout=60)
        assert res0.stats["tx_bytes"] == np.prod(shape) * 4
        # This host's own bound (the parent is a pure sender here).
        peak = executor.staging_snapshot()["peak"]
        assert peak <= res0.stats["peak_staging_bound"], res0.stats
        verdict = await asyncio.get_running_loop().run_in_executor(
            None, lambda: q.get(timeout=120))
        assert verdict.get("ok"), verdict.get("error")
        assert verdict["rounds"] > 1  # genuinely round-decomposed
    finally:
        proc.terminate()
        proc.join(10)
        await asyncio.wait_for(server.aclose(), 15)


# ------------------------------------------------ chaos: session resume


async def test_schedule_survives_conn_kill_with_session(port, monkeypatch):
    """STARWAY_SESSION=1 + a mid-transfer connection kill: the §14 layer
    redials and replays, the schedule's rounds complete exactly-once,
    and the retile is still bit-exact (ISSUE-12 chaos acceptance)."""
    monkeypatch.setenv("STARWAY_TLS", "tcp")
    monkeypatch.setenv("STARWAY_NATIVE", "0")
    monkeypatch.setenv("STARWAY_SESSION", "1")
    src_sh, dst_sh = _two_rank_shardings()
    shape = (16, 1 << 20)  # 16 MiB: four rounds of 4 MiB transfers
    x = (jnp.arange(np.prod(shape), dtype=jnp.uint32) % 251).astype(
        jnp.uint8).reshape(shape)
    xs = jax.device_put(x, src_sh)
    server = Server()
    server.listen(ADDR, port)
    proxy = FaultProxy(ADDR, port).start()
    client = Client()
    try:
        await asyncio.wait_for(client.aconnect(ADDR, proxy.port), 30)
        # Land the RST ~2 MiB into the schedule: mid-payload of round 0's
        # 4 MiB transfer, well past the handshake + spec exchange.
        proxy.reset_mid_message(proxy.forwarded_bytes + (2 << 20))
        with lease() as L:
            _, res1 = await asyncio.wait_for(asyncio.gather(
                redistribute(xs, None, {1: client}, rank=0,
                             rank_of=_split_rank_of, lease=L),
                redistribute(ArrayRef(shape, np.uint8), dst_sh,
                             {0: _SinkPort(server)}, rank=1,
                             rank_of=_split_rank_of, lease=L)), 120)
        np.testing.assert_array_equal(np.asarray(res1.array), np.asarray(x))
        assert client._client.counters_snapshot()["sessions_resumed"] >= 1
    finally:
        try:
            await asyncio.wait_for(client.aclose(), 15)
        finally:
            await asyncio.wait_for(server.aclose(), 15)
            proxy.stop()


# ----------------------------------------- observability + wire parity


async def test_counters_and_gauges_surface(port, monkeypatch):
    """reshard_bytes/reshard_rounds ride the shared counter vocabulary
    (both engines' snapshots -- overlaid process-globals, like the
    staging pool) and the staging gauge rides gauges_snapshot."""
    monkeypatch.setenv("STARWAY_TLS", "tcp")
    monkeypatch.setenv("STARWAY_NATIVE", "0")
    from starway_tpu.core import swtrace

    before_b = swtrace.GLOBAL.reshard_bytes
    before_r = swtrace.GLOBAL.reshard_rounds
    src_sh, dst_sh = _two_rank_shardings()
    shape = (8, 512)
    xs = jax.device_put(
        jnp.arange(np.prod(shape), dtype=jnp.float32).reshape(shape), src_sh)
    server = Server()
    server.listen(ADDR, port)
    client = Client()
    try:
        await asyncio.wait_for(client.aconnect(ADDR, port), 30)
        with lease() as L:
            await asyncio.gather(
                redistribute(xs, None, {1: client}, rank=0,
                             rank_of=_split_rank_of, lease=L),
                redistribute(ArrayRef(shape, np.float32), dst_sh,
                             {0: _SinkPort(server)}, rank=1,
                             rank_of=_split_rank_of, lease=L))
        assert swtrace.GLOBAL.reshard_bytes > before_b
        assert swtrace.GLOBAL.reshard_rounds > before_r
        snap = client._client.counters_snapshot()
        assert snap["reshard_bytes"] == swtrace.GLOBAL.reshard_bytes
        gauges = client._client.gauges_snapshot()
        assert gauges["reshard_staging_peak"] >= 0
        assert gauges["reshard_staging_bytes"] == 0  # quiescent: drained
    finally:
        try:
            await asyncio.wait_for(client.aclose(), 15)
        finally:
            await asyncio.wait_for(server.aclose(), 15)


async def _capture_hello(port):
    listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    listener.bind((ADDR, port))
    listener.listen(4)
    client = Client()
    try:
        fut = client.aconnect(ADDR, port)
        conn, _ = listener.accept()
        conn.settimeout(10)
        hdr = b""
        while len(hdr) < frames.HEADER_SIZE:
            hdr += conn.recv(frames.HEADER_SIZE - len(hdr))
        ftype, _a, blen = frames.unpack_header(hdr)
        assert ftype == frames.T_HELLO
        body = b""
        while len(body) < blen:
            body += conn.recv(blen - len(body))
        conn.sendall(frames.pack_hello_ack("seedpeer"))
        await asyncio.wait_for(fut, 30)
        conn.close()
        return json.loads(body.decode())
    finally:
        listener.close()
        try:
            await asyncio.wait_for(client.aclose(), 10)
        except Exception:
            pass


async def test_hello_parity_reshard_is_not_a_wire_feature(port, port2,
                                                          monkeypatch):
    """swshard rides the EXISTING wire: no handshake key, no new frame
    type.  The HELLO a client offers is identical (modulo worker_id)
    before and after the process has imported and run a schedule --
    the seed-parity pattern of §17/§18/§19, inverted: there is nothing
    to negotiate."""
    monkeypatch.setenv("STARWAY_TLS", "tcp")
    monkeypatch.setenv("STARWAY_NATIVE", "0")
    before = await _capture_hello(port)
    # Run a real (local) schedule end to end.
    mesh = Mesh(np.array(jax.devices()).reshape(8), ("x",))
    xs = jax.device_put(jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
                        NamedSharding(mesh, P("x")))
    await redistribute(xs, NamedSharding(mesh, P(None, "x")))
    after = await _capture_hello(port2)
    # worker_id/name are per-worker random ids; every negotiated key and
    # value must match exactly.
    scrub = lambda h: {k: v for k, v in h.items()
                       if k not in ("worker_id", "name")}
    assert scrub(before) == scrub(after)


# ------------------------------------------------------------------ soak


@pytest.mark.slow
async def test_reshard_gib_soak(port, monkeypatch):
    """Multi-GiB redistribution soak: a 1 GiB retile between the two
    simulated ranks completes checksum-exact with staging still inside
    the bound."""
    monkeypatch.setenv("STARWAY_TLS", "tcp")
    monkeypatch.setenv("STARWAY_NATIVE", "0")
    src_sh, dst_sh = _two_rank_shardings()
    shape = (16, 1 << 26)  # 1 GiB of uint8
    x = (np.arange(np.prod(shape), dtype=np.uint64) % 251).astype(np.uint8)
    xs = jax.device_put(jnp.asarray(x).reshape(shape), src_sh)
    want = int(x.astype(np.uint64).sum())
    server = Server()
    server.listen(ADDR, port)
    client = Client()
    try:
        await asyncio.wait_for(client.aconnect(ADDR, port), 30)
        executor.reset_staging_peak()
        with lease() as L:
            _, res1 = await asyncio.gather(
                redistribute(xs, None, {1: client}, rank=0,
                             rank_of=_split_rank_of, lease=L),
                redistribute(ArrayRef(shape, np.uint8), dst_sh,
                             {0: _SinkPort(server)}, rank=1,
                             rank_of=_split_rank_of, lease=L))
        got = np.asarray(res1.array)
        assert int(got.astype(np.uint64).sum()) == want
        peak = executor.staging_snapshot()["peak"]
        assert peak <= 2 * res1.stats["peak_staging_bound"]
    finally:
        try:
            await asyncio.wait_for(client.aclose(), 30)
        finally:
            await asyncio.wait_for(server.aclose(), 30)
