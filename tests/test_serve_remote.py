"""Serving over the transport (models/remote_serving.py): requests arrive
as tagged messages on a Server, SlotServer admits them, tokens stream back
per-request over the connection — and every request's greedy output is
bit-identical to the standalone generate() oracle.

Matrix: the same contract over the in-process fast path, real TCP
sockets, and the C++ native engine (VERDICT r4 #2 "works over inproc,
tcp AND the native engine"), plus a multiprocess test driving concurrent
client processes against one serving process.
"""

import asyncio
import multiprocessing as mp
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from starway_tpu.models import LlamaConfig, SlotServer, init_params
from starway_tpu.models.generate import generate

pytestmark = pytest.mark.asyncio

ADDR = "127.0.0.1"


@pytest.fixture(params=["inproc", "tcp", "native"])
def transport(request, monkeypatch):
    if request.param == "inproc":
        # Ambient env must not silently turn this leg into tcp/native.
        monkeypatch.delenv("STARWAY_TLS", raising=False)
        monkeypatch.delenv("STARWAY_NATIVE", raising=False)
    elif request.param == "tcp":
        monkeypatch.setenv("STARWAY_TLS", "tcp")
        monkeypatch.setenv("STARWAY_NATIVE", "0")
    elif request.param == "native":
        from starway_tpu.core import native

        if not native.available():
            pytest.skip("native engine unavailable (no toolchain)")
        monkeypatch.setenv("STARWAY_TLS", "tcp")
        monkeypatch.setenv("STARWAY_NATIVE", "1")
    return request.param


@pytest.fixture(scope="module")
def cfg():
    return LlamaConfig.preset("debug")


@pytest.fixture(scope="module")
def params(cfg):
    return init_params(jax.random.PRNGKey(0), cfg)


def _oracle(params, cfg, prompt, max_new):
    out = generate(params, cfg, jnp.asarray([prompt], jnp.int32), max_new)
    return np.asarray(out[0, len(prompt):])


async def _serve_and_query(cfg, params, reqs, port, n_sessions=1):
    """One bridge, n_sessions concurrent client sessions, reqs round-robin
    across them; returns the per-request token arrays in reqs order."""
    from starway_tpu.models.remote_serving import (RemoteGenerateSession,
                                                   RemoteSlotServer)

    slot = SlotServer(params, cfg, n_slots=2, max_len=64, chunk=4)
    bridge = RemoteSlotServer(slot)
    bridge.server.listen(ADDR, port)
    serve_task = asyncio.create_task(bridge.serve())

    sessions = [await RemoteGenerateSession.aconnect(ADDR, port)
                for _ in range(n_sessions)]
    try:
        outs = await asyncio.gather(*(
            sessions[i % n_sessions].generate(p, m)
            for i, (p, m) in enumerate(reqs)))
    finally:
        bridge.stop()
        await serve_task
        for s in sessions:
            await s.aclose()
        await bridge.aclose()
    return outs


async def test_remote_matches_generate(cfg, params, transport, port):
    """More requests than slots through one remote session: every greedy
    continuation equals standalone generate()."""
    rng = np.random.default_rng(1)
    reqs = [(list(rng.integers(1, cfg.vocab_size, n)), m)
            for n, m in [(3, 6), (7, 4), (12, 9), (5, 1), (2, 11)]]
    outs = await _serve_and_query(cfg, params, reqs, port)
    for (prompt, max_new), got in zip(reqs, outs):
        np.testing.assert_array_equal(got, _oracle(params, cfg, prompt,
                                                   max_new))


async def test_remote_concurrent_sessions(cfg, params, transport, port):
    """Three sessions (connections) interleaving requests on one bridge:
    tag routing keeps every stream on its own request."""
    rng = np.random.default_rng(2)
    reqs = [(list(rng.integers(1, cfg.vocab_size, n)), m)
            for n, m in [(4, 5), (9, 7), (2, 3), (6, 8), (3, 4), (8, 2)]]
    outs = await _serve_and_query(cfg, params, reqs, port, n_sessions=3)
    for (prompt, max_new), got in zip(reqs, outs):
        np.testing.assert_array_equal(got, _oracle(params, cfg, prompt,
                                                   max_new))


async def test_remote_streaming_chunks(cfg, params, transport, port):
    """The per-chunk callback sees the same tokens, in order, as the
    final result — streaming is not a re-delivery."""
    from starway_tpu.models.remote_serving import (RemoteGenerateSession,
                                                   RemoteSlotServer)

    slot = SlotServer(params, cfg, n_slots=1, max_len=64, chunk=3)
    bridge = RemoteSlotServer(slot)
    bridge.server.listen(ADDR, port)
    serve_task = asyncio.create_task(bridge.serve())
    session = await RemoteGenerateSession.aconnect(ADDR, port)
    try:
        seen: list = []
        out = await session.generate([4, 2, 8, 1], 10,
                                     on_tokens=seen.extend)
        assert seen == list(out)
        assert len(out) == 10
        # chunk=3 means the stream arrived in > 1 message
        np.testing.assert_array_equal(
            out, _oracle(params, cfg, [4, 2, 8, 1], 10))
    finally:
        bridge.stop()
        await serve_task
        await session.aclose()
        await bridge.aclose()


async def test_remote_rejects_oversized(cfg, params, transport, port):
    """A request that exceeds the server's max_len comes back as a
    rejection (empty fatal stream -> ValueError), and the serve loop
    keeps working for the next request."""
    from starway_tpu.models.remote_serving import (RemoteGenerateSession,
                                                   RemoteSlotServer)

    slot = SlotServer(params, cfg, n_slots=1, max_len=32, chunk=4)
    bridge = RemoteSlotServer(slot)
    bridge.server.listen(ADDR, port)
    serve_task = asyncio.create_task(bridge.serve())
    session = await RemoteGenerateSession.aconnect(ADDR, port)
    try:
        with pytest.raises(ValueError, match="rejected"):
            await session.generate(list(range(1, 20)), 100)
        out = await session.generate([4, 2, 8], 5)
        np.testing.assert_array_equal(out, _oracle(params, cfg, [4, 2, 8],
                                                   5))
    finally:
        bridge.stop()
        await serve_task
        await session.aclose()
        await bridge.aclose()


async def test_remote_client_rejects_oversized_prompt_locally(cfg, params,
                                                              port):
    """ASSIGN carries the server's request-size limit; generate() raises
    client-side instead of sending an unanswerable truncated request."""
    from starway_tpu.models.remote_serving import (RemoteGenerateSession,
                                                   RemoteSlotServer)

    slot = SlotServer(params, cfg, n_slots=1, max_len=64, chunk=4)
    bridge = RemoteSlotServer(slot, max_prompt_tokens=16)
    bridge.server.listen(ADDR, port)
    serve_task = asyncio.create_task(bridge.serve())
    session = await RemoteGenerateSession.aconnect(ADDR, port)
    try:
        assert session.server_max_prompt == 16
        with pytest.raises(ValueError, match="request limit"):
            await session.generate(list(range(1, 30)), 4)
    finally:
        bridge.stop()
        await serve_task
        await session.aclose()
        await bridge.aclose()


async def test_remote_intake_survives_truncated_request(cfg, params, port):
    """An oversized request truncates the server's wildcard recv; the
    bridge must re-post and keep serving everyone else (a one-request
    denial must not become a permanent one)."""
    from starway_tpu.models.remote_serving import (TAG_REQUEST,
                                                   RemoteGenerateSession,
                                                   RemoteSlotServer, _wire)

    slot = SlotServer(params, cfg, n_slots=1, max_len=64, chunk=4)
    bridge = RemoteSlotServer(slot, max_prompt_tokens=16)
    bridge.server.listen(ADDR, port)
    serve_task = asyncio.create_task(bridge.serve())
    session = await RemoteGenerateSession.aconnect(ADDR, port)
    try:
        # Raw oversized request (larger than the bridge's recv buffer);
        # sent directly so the test doesn't await a stream that cannot
        # come back (the recv fails before the nonce is parsed).
        big = np.concatenate([np.asarray([0, 4, 64], np.int32),
                              np.ones(64, np.int32)])
        await session.client.asend(_wire(big),
                                   TAG_REQUEST | session.client_id)
        await asyncio.sleep(0.2)
        out = await session.generate([4, 2, 8], 5)
        np.testing.assert_array_equal(out, _oracle(params, cfg, [4, 2, 8],
                                                   5))
    finally:
        bridge.stop()
        await serve_task
        await session.aclose()
        await bridge.aclose()


async def test_remote_malformed_request_is_rejected(cfg, params, port):
    """A length-inconsistent request gets a fatal empty stream back (the
    client errors instead of hanging), and service continues."""
    from starway_tpu.models.remote_serving import (TAG_REQUEST, TAG_TOKENS,
                                                   FULL_MASK,
                                                   RemoteGenerateSession,
                                                   RemoteSlotServer,
                                                   _recv_buf, _wire)

    slot = SlotServer(params, cfg, n_slots=1, max_len=64, chunk=4)
    bridge = RemoteSlotServer(slot)
    bridge.server.listen(ADDR, port)
    serve_task = asyncio.create_task(bridge.serve())
    session = await RemoteGenerateSession.aconnect(ADDR, port)
    try:
        nonce = 7777
        bad = np.asarray([nonce, 4, 99, 1, 2, 3], np.int32)  # n=99, 3 sent
        await session.client.asend(_wire(bad),
                                   TAG_REQUEST | session.client_id)
        buf = _recv_buf(8)
        await session.client.arecv(buf, TAG_TOKENS | nonce, FULL_MASK)
        words = buf.view(np.int32)
        assert int(words[1]) == 2 and int(words[2]) == 0  # aborted, empty
        out = await session.generate([9, 1], 4)
        np.testing.assert_array_equal(out, _oracle(params, cfg, [9, 1], 4))
    finally:
        bridge.stop()
        await serve_task
        await session.aclose()
        await bridge.aclose()


# --------------------------------------------------------- multiprocess
def _server_proc(port, ready, stop):
    os.environ["STARWAY_TLS"] = "tcp"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax as j

    j.config.update("jax_platforms", "cpu")

    from starway_tpu.models import LlamaConfig, SlotServer, init_params
    from starway_tpu.models.remote_serving import RemoteSlotServer

    cfg = LlamaConfig.preset("debug")
    params = init_params(j.random.PRNGKey(0), cfg)

    async def main():
        slot = SlotServer(params, cfg, n_slots=2, max_len=64, chunk=4)
        bridge = RemoteSlotServer(slot)
        bridge.server.listen("127.0.0.1", port)
        ready.set()
        task = asyncio.create_task(bridge.serve())
        while not stop.is_set():
            await asyncio.sleep(0.05)
        bridge.stop()
        await task
        await bridge.aclose()

    asyncio.run(main())


def _client_proc(port, reqs, out_q):
    os.environ["STARWAY_TLS"] = "tcp"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax as j

    j.config.update("jax_platforms", "cpu")

    from starway_tpu.models.remote_serving import RemoteGenerateSession

    async def main():
        session = None
        for _ in range(60):  # clients are connect-once: fresh per attempt
            try:
                session = await RemoteGenerateSession.aconnect(
                    "127.0.0.1", port)
                break
            except Exception:
                await asyncio.sleep(0.25)
        assert session is not None, "could not connect to serving process"
        outs = await asyncio.gather(*(session.generate(p, m)
                                      for p, m in reqs))
        await session.aclose()
        return [np.asarray(o).tolist() for o in outs]

    out_q.put(asyncio.run(main()))


def test_remote_multiprocess(cfg, params, port):
    """One serving process, two concurrent client processes over real TCP:
    every stream matches its oracle computed in THIS process."""
    mp_ctx = mp.get_context("spawn")
    ready, stop = mp_ctx.Event(), mp_ctx.Event()
    srv = mp_ctx.Process(target=_server_proc, args=(port, ready, stop))
    srv.start()
    try:
        assert ready.wait(120), "serving process never came up"
        rng = np.random.default_rng(3)
        all_reqs = [[(list(map(int, rng.integers(1, cfg.vocab_size, n))), m)
                     for n, m in [(3, 6), (8, 4)]]
                    for _ in range(2)]
        qs, clients = [], []
        for reqs in all_reqs:
            q = mp_ctx.Queue()
            c = mp_ctx.Process(target=_client_proc, args=(port, reqs, q))
            c.start()
            qs.append(q)
            clients.append(c)
        results = [q.get(timeout=300) for q in qs]
        for c in clients:
            c.join(timeout=60)
    finally:
        stop.set()
        srv.join(timeout=60)
        if srv.is_alive():
            srv.terminate()
    for reqs, outs in zip(all_reqs, results):
        for (prompt, max_new), got in zip(reqs, outs):
            np.testing.assert_array_equal(
                np.asarray(got, np.int32),
                _oracle(params, cfg, prompt, max_new))


async def test_remote_cancel_frees_slot_and_aborts_stream(cfg, params,
                                                          port):
    """Client-initiated CANCEL: the awaiting generate() raises, the slot
    frees for waiting work, and subsequent requests still match their
    oracle."""
    from starway_tpu.models.remote_serving import (RemoteGenerateSession,
                                                   RemoteSlotServer)

    slot = SlotServer(params, cfg, n_slots=1, max_len=64, chunk=2)
    bridge = RemoteSlotServer(slot)
    bridge.server.listen(ADDR, port)
    serve_task = asyncio.create_task(bridge.serve())
    session = await RemoteGenerateSession.aconnect(ADDR, port)
    try:
        handle = RemoteGenerateSession.Handle()
        first_chunk = asyncio.Event()

        async def doomed():
            with pytest.raises(ValueError, match="rejected or cancelled"):
                await session.generate(
                    [4, 2, 8, 1], 40, handle=handle,
                    on_tokens=lambda c: first_chunk.set())

        task = asyncio.create_task(doomed())
        await asyncio.wait_for(first_chunk.wait(), 120)
        await session.cancel(handle)
        await asyncio.wait_for(task, 120)

        # The only slot must now be free for a fresh request.
        out = await asyncio.wait_for(session.generate([9, 1, 5], 6), 120)
        np.testing.assert_array_equal(out, _oracle(params, cfg, [9, 1, 5],
                                                   6))
    finally:
        bridge.stop()
        await serve_task
        await session.aclose()
        await bridge.aclose()


async def test_remote_cancel_overtaking_request(cfg, params, port):
    """A CANCEL drained before its REQUEST (both queue up during one
    decode step; cancels drain first) must still abort the request —
    the stash rejects it at submit time instead of losing the cancel."""
    from starway_tpu.models.remote_serving import (RemoteGenerateSession,
                                                   RemoteSlotServer)

    slot = SlotServer(params, cfg, n_slots=1, max_len=64, chunk=4)
    bridge = RemoteSlotServer(slot)
    bridge.server.listen(ADDR, port)
    # The session needs a running serve loop to receive its ASSIGN;
    # pause the loop afterwards to stage the overtaking deterministically.
    serve_task = asyncio.create_task(bridge.serve())
    session = await RemoteGenerateSession.aconnect(ADDR, port)
    bridge.stop()
    await serve_task
    bridge._stopping = False  # re-arm (white-box: serve() is re-entrant)
    try:
        # Pre-load BOTH queues while the loop is paused: the drain order
        # processes cancels first — the CANCEL overtakes the REQUEST.
        nonce = 0
        bridge._requests.append((session.client_id, np.asarray(
            [nonce, 30, 4, 4, 2, 8, 1], np.int32)))
        bridge._cancels.append((session.client_id, nonce))
        session._nonce = 1  # nonce 0 is taken by the hand-crafted request
        task = asyncio.create_task(_await_aborted(session, nonce))
        serve_task = asyncio.create_task(bridge.serve())
        status = await asyncio.wait_for(task, 120)
        assert status == 2  # aborted, never decoded
        # Service continues for normal requests.
        out = await asyncio.wait_for(session.generate([9, 1, 5], 6), 120)
        np.testing.assert_array_equal(out, _oracle(params, cfg, [9, 1, 5],
                                                   6))
    finally:
        bridge.stop()
        await serve_task
        await session.aclose()
        await bridge.aclose()


async def _await_aborted(session, nonce):
    from starway_tpu.models.remote_serving import (FULL_MASK, TAG_TOKENS,
                                                   _recv_buf)

    buf = _recv_buf(8)
    await session.client.arecv(buf, TAG_TOKENS | nonce, FULL_MASK)
    return int(buf.view(np.int32)[1])
