"""swcost runtime twin (DESIGN.md §23): the dynamic shadow of the static
cost ledger.

The ``cost`` gate leg pins per-path syscall/copy/alloc/lock SITE counts
for both engines in analysis/cost_budgets.txt; these tests pin the other
half of the conformance loop: driving a canonical eager op sequence over
all four engine pairings and checking the ``io_syscalls``/``hot_copies``
counter deltas against the extractor's own site vectors.  The bounds are
DERIVED from the extraction at runtime, so the coupling cuts both ways:
extraction going stale (zero sites while the counters move) fails here,
and instrumentation going dark (sites present, counters frozen) fails
here -- neither can pass vacuously.

Seed darkness: the twin is a pair of unconditional counter increments at
sites that already maintain ``bytes_tx``/``bytes_rx`` -- no new branch,
no wire bytes, no handshake key (HELLO parity pinned below).
"""

import asyncio
import json
import socket
from pathlib import Path

import numpy as np
import pytest

from starway_tpu import Client, Server
from starway_tpu.core import frames, swtrace

pytestmark = pytest.mark.asyncio

REPO = Path(__file__).resolve().parents[1]
ADDR = "127.0.0.1"
MASK = (1 << 64) - 1
ENGINES = ["python", "native"]

#: Canonical op sequence: K eager sends of NBYTES each, plus one flush.
K, NBYTES = 8, 4096

#: Dynamic ceiling per extracted syscall site: the pumps loop (a recv
#: drains until EAGAIN, a gather retries on partial writes), so one
#: static site executes a small multiple of times per op.  Generous on
#: purpose -- the *static* budget is the precise ratchet; this bound
#: only has to catch an instrumentation/extraction split, not a
#: one-syscall drift.
EXECS_PER_SITE = 8
BASE_SLACK = 64  # handshake, doorbells, keepalive, the flush frame


def _native_available() -> bool:
    from starway_tpu.core import native

    return native.available()


def _static_vectors():
    from starway_tpu.analysis import clear_caches, cost

    clear_caches()
    vectors, vacuity = cost.extract(REPO)
    assert vacuity == [], [f.render() for f in vacuity]
    return vectors


def _sites(vectors, engine: str, metric: str, paths=None) -> int:
    return sum(v for (e, p, m), v in vectors.items()
               if e == engine and m == metric
               and (paths is None or p in paths))


def _env(monkeypatch):
    monkeypatch.setenv("STARWAY_TLS", "tcp")
    monkeypatch.setenv("STARWAY_DEVPULL", "0")
    monkeypatch.delenv("STARWAY_TRACE", raising=False)
    monkeypatch.delenv("STARWAY_FLIGHT_DIR", raising=False)
    swtrace.reset()


async def _drive(server, client):
    sinks = [np.empty(NBYTES, dtype=np.uint8) for _ in range(K)]
    futs = [server.arecv(b, 0x600 + i, MASK) for i, b in enumerate(sinks)]
    await asyncio.sleep(0.05)
    await asyncio.gather(
        *(client.asend(np.full(NBYTES, i + 1, dtype=np.uint8), 0x600 + i)
          for i in range(K)))
    await asyncio.gather(*futs)
    await client.aflush()


@pytest.mark.parametrize("server_engine", ENGINES)
@pytest.mark.parametrize("client_engine", ENGINES)
async def test_counter_twin_matches_static_ledger(port, monkeypatch,
                                                  client_engine,
                                                  server_engine):
    """All four pairings: the canonical eager sequence moves io_syscalls
    within the extraction-derived envelope, keeps hot_copies at the
    ledger's tcp prediction (zero -- the tcp data path is copy-free), and
    populates the §25 swpulse histograms without adding a ledger site."""
    if "native" in (client_engine, server_engine) and not _native_available():
        pytest.skip("native engine unavailable")
    vectors = _static_vectors()
    ce = "cpp" if client_engine == "native" else "py"
    se = "cpp" if server_engine == "native" else "py"

    _env(monkeypatch)
    monkeypatch.setenv("STARWAY_NATIVE",
                       "1" if server_engine == "native" else "0")
    server = Server()
    server.listen(ADDR, port)
    monkeypatch.setenv("STARWAY_NATIVE",
                       "1" if client_engine == "native" else "0")
    client = Client()
    await client.aconnect(ADDR, port)
    try:
        await _drive(server, client)
        cs = client._client.counters_snapshot()
        ss = server._server.counters_snapshot()
        ch = client._client.hists_snapshot()
        sh = server._server.hists_snapshot()
    finally:
        await client.aclose()
        await server.aclose()

    # The twin rides the shared vocabulary on both engines.
    for snap in (cs, ss):
        assert "io_syscalls" in snap and "hot_copies" in snap

    # swpulse (DESIGN.md §25) rides the SAME certified hot path without
    # moving the §23 ledger: the gate's cost leg pins zero new sites, so
    # conformance here is "the histograms populated anyway" -- on all
    # four pairings, in the one shared shape.
    for snap in (ch, sh):
        assert sorted(snap) == sorted(swtrace.HIST_NAMES)
        assert all(len(row) == swtrace.HIST_BUCKETS for row in snap.values())
    assert sum(ch["send_local_us"]) >= K, ch
    assert sum(ch["msg_bytes"]) >= K, ch
    assert sum(ch["flush_us"]) >= 1, ch
    assert sum(sh["recv_wait_us"]) >= K, sh

    for engine, snap, role in ((ce, cs, "client"), (se, ss, "server")):
        sites = _sites(vectors, engine, "syscalls")
        got = snap["io_syscalls"]
        if sites == 0:
            # Extraction sees no syscall sites: the counters must agree,
            # or the site table went stale (the non-vacuity direction).
            assert got == 0, (
                f"{role} ({engine}): io_syscalls moved to {got} but the "
                "static extraction finds zero syscall sites -- "
                "analysis/cost.py's tables are stale")
        else:
            assert got >= 1, (
                f"{role} ({engine}): {sites} static syscall sites but "
                "io_syscalls never moved -- the §23 runtime twin is dark")
            bound = K * sites * EXECS_PER_SITE + BASE_SLACK
            assert got <= bound, (
                f"{role} ({engine}): io_syscalls={got} exceeds the "
                f"extraction-derived envelope {bound} (K={K} ops x "
                f"{sites} sites x {EXECS_PER_SITE} execs + {BASE_SLACK})")
        # tcp transport: the ledger pins zero copy sites on the eager
        # tcp path, so the dynamic twin must not move either.
        tcp_copy_sites = _sites(vectors, engine, "copies",
                                paths=("eager_tx", "eager_rx", "dispatch"))
        assert tcp_copy_sites == 0, (
            f"{engine}: the eager tcp path grew a copy site -- the "
            "cost gate should have caught this in cost_budgets.txt")
        assert snap["hot_copies"] == 0, (
            f"{role} ({engine}): hot_copies={snap['hot_copies']} on a "
            "pure-tcp run -- the tcp data path is pinned copy-free")


@pytest.mark.parametrize("engine", ENGINES)
async def test_counter_twin_sm_copies(port, monkeypatch, engine):
    """Over the sm ring the same sequence pays exactly the ledger's
    copy asymmetry: hot_copies moves on both ends (ring put/take are
    real byte copies), matching the nonzero sm_enqueue/sm_dequeue copy
    rows that the tcp paths do not have."""
    import platform

    if platform.machine() not in ("x86_64", "AMD64"):
        pytest.skip("python sm transport requires x86-64")
    if engine == "native" and not _native_available():
        pytest.skip("native engine unavailable")
    vectors = _static_vectors()
    e = "cpp" if engine == "native" else "py"
    assert _sites(vectors, e, "copies", paths=("sm_enqueue",)) > 0
    assert _sites(vectors, e, "copies", paths=("sm_dequeue",)) > 0

    _env(monkeypatch)
    monkeypatch.setenv("STARWAY_TLS", "tcp,sm")
    monkeypatch.setenv("STARWAY_NATIVE", "1" if engine == "native" else "0")
    server = Server()
    server.listen(ADDR, port)
    client = Client()
    await client.aconnect(ADDR, port)
    try:
        await _drive(server, client)
        cs = client._client.counters_snapshot()
        ss = server._server.counters_snapshot()
    finally:
        await client.aclose()
        await server.aclose()

    assert cs["hot_copies"] >= 1, (
        "sender on sm: ring put never counted -- the §23 copy twin is "
        f"dark ({cs})")
    assert ss["hot_copies"] >= 1, (
        "receiver on sm: ring take never counted -- the §23 copy twin "
        f"is dark ({ss})")


async def test_seed_path_stays_dark(port):
    """The runtime twin adds NO wire surface: the HELLO carries no new
    key (counters are not negotiated -- both engines always count), and
    the counter names land in the one shared vocabulary instead of a
    side channel."""
    assert "io_syscalls" in swtrace.COUNTER_NAMES
    assert "hot_copies" in swtrace.COUNTER_NAMES

    listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    listener.bind((ADDR, port))
    listener.listen(4)
    client = Client()
    try:
        fut = client.aconnect(ADDR, port)
        conn, _ = listener.accept()
        conn.settimeout(10)
        hdr = b""
        while len(hdr) < frames.HEADER_SIZE:
            hdr += conn.recv(frames.HEADER_SIZE - len(hdr))
        ftype, _a, blen = frames.unpack_header(hdr)
        assert ftype == frames.T_HELLO
        body = b""
        while len(body) < blen:
            body += conn.recv(blen - len(body))
        conn.sendall(frames.pack_hello_ack("seedpeer"))
        await asyncio.wait_for(fut, 30)
        conn.close()
        hello = json.loads(body.decode())
    finally:
        listener.close()
        try:
            await asyncio.wait_for(client.aclose(), 10)
        except Exception:
            pass
    assert not any("cost" in k or "syscall" in k or "copies" in k
                   for k in hello), hello
