"""Unit tests for the host tag-matching engine (starway_tpu/core/matching.py).

The reference has no unit tier (UCX does its matching); this engine is ours,
so it gets direct coverage: match rules, FIFO order, wildcard masks,
unexpected-queue behaviour, truncation, claim-in-flight, cancellation.
"""

import numpy as np

from starway_tpu.core.matching import TagMatcher, tags_match


def run(fires):
    for f in fires:
        f()


def test_tags_match_rules():
    assert tags_match(0x1234, 0x0, 0x0)  # mask 0 = wildcard
    assert tags_match(0x1234, 0x1234, (1 << 64) - 1)
    assert not tags_match(0x1234, 0x1235, (1 << 64) - 1)
    assert tags_match(0xAB12, 0xCD12, 0xFF)  # low-byte-only match


def test_deliver_to_posted_recv():
    m = TagMatcher()
    buf = np.zeros(4, dtype=np.uint8)
    got = []
    run(m.post_recv(memoryview(buf), 7, (1 << 64) - 1, lambda t, n: got.append((t, n)), lambda e: got.append(e)))
    run(m.deliver(7, memoryview(np.array([1, 2, 3, 4], dtype=np.uint8))))
    assert got == [(7, 4)]
    np.testing.assert_array_equal(buf, [1, 2, 3, 4])


def test_unexpected_then_post():
    m = TagMatcher()
    run(m.deliver(9, memoryview(np.array([5, 6], dtype=np.uint8))))
    buf = np.zeros(2, dtype=np.uint8)
    got = []
    run(m.post_recv(memoryview(buf), 0, 0, lambda t, n: got.append((t, n)), lambda e: got.append(e)))
    assert got == [(9, 2)]
    np.testing.assert_array_equal(buf, [5, 6])


def test_fifo_order_of_unexpected():
    m = TagMatcher()
    run(m.deliver(1, memoryview(np.array([1], dtype=np.uint8))))
    run(m.deliver(2, memoryview(np.array([2], dtype=np.uint8))))
    buf = np.zeros(1, dtype=np.uint8)
    got = []
    run(m.post_recv(memoryview(buf), 0, 0, lambda t, n: got.append(t), lambda e: got.append(e)))
    assert got == [1]
    run(m.post_recv(memoryview(buf), 0, 0, lambda t, n: got.append(t), lambda e: got.append(e)))
    assert got == [1, 2]


def test_fifo_order_of_posted():
    m = TagMatcher()
    b1 = np.zeros(1, dtype=np.uint8)
    b2 = np.zeros(1, dtype=np.uint8)
    got = []
    run(m.post_recv(memoryview(b1), 0, 0, lambda t, n: got.append("first"), lambda e: None))
    run(m.post_recv(memoryview(b2), 0, 0, lambda t, n: got.append("second"), lambda e: None))
    run(m.deliver(5, memoryview(np.array([9], dtype=np.uint8))))
    assert got == ["first"]


def test_mask_selects_specific_recv():
    m = TagMatcher()
    b1 = np.zeros(1, dtype=np.uint8)
    b2 = np.zeros(1, dtype=np.uint8)
    got = []
    full = (1 << 64) - 1
    run(m.post_recv(memoryview(b1), 100, full, lambda t, n: got.append(100), lambda e: None))
    run(m.post_recv(memoryview(b2), 200, full, lambda t, n: got.append(200), lambda e: None))
    run(m.deliver(200, memoryview(np.array([1], dtype=np.uint8))))
    assert got == [200]


def test_truncation_fails_recv():
    m = TagMatcher()
    buf = np.zeros(2, dtype=np.uint8)
    got = []
    run(m.post_recv(memoryview(buf), 0, 0, lambda t, n: got.append("done"), lambda e: got.append(e)))
    run(m.deliver(1, memoryview(np.zeros(10, dtype=np.uint8))))
    assert len(got) == 1 and "truncated" in got[0].lower()


def test_streaming_message_start_complete():
    m = TagMatcher()
    buf = np.zeros(8, dtype=np.uint8)
    got = []
    run(m.post_recv(memoryview(buf), 3, (1 << 64) - 1, lambda t, n: got.append((t, n)), lambda e: got.append(e)))
    msg, fires = m.on_message_start(3, 8)
    run(fires)
    assert msg.sink is not None and not got
    msg.sink[:8] = bytes(range(8))
    msg.received = 8
    run(m.on_message_complete(msg))
    assert got == [(3, 8)]
    np.testing.assert_array_equal(buf, np.arange(8, dtype=np.uint8))


def test_claim_inflight_spill():
    m = TagMatcher()
    msg, fires = m.on_message_start(4, 4)  # no posted recv: spills
    run(fires)
    buf = np.zeros(4, dtype=np.uint8)
    got = []
    run(m.post_recv(memoryview(buf), 4, (1 << 64) - 1, lambda t, n: got.append((t, n)), lambda e: got.append(e)))
    assert not got  # claimed but still in flight
    msg.sink[:4] = b"\x01\x02\x03\x04"
    msg.received = 4
    run(m.on_message_complete(msg))
    assert got == [(4, 4)]
    np.testing.assert_array_equal(buf, [1, 2, 3, 4])


def test_cancel_all_fails_everything():
    m = TagMatcher()
    buf = np.zeros(1, dtype=np.uint8)
    got = []
    run(m.post_recv(memoryview(buf), 50, (1 << 64) - 1, lambda t, n: got.append("done"), lambda e: got.append(e)))
    msg, fires = m.on_message_start(1, 100)  # in-flight spill, unclaimed
    run(fires)
    run(m.cancel_all())
    assert len(got) == 1 and "cancel" in got[0].lower()
    assert not m.posted and not m.unexpected and not m.inflight


def test_probe_tag_discarded():
    """Messages on the reserved PROBE_TAG never enter the unexpected queue
    and never match a receive -- even a wildcard posted first."""
    from starway_tpu.core.matching import PROBE_TAG

    m = TagMatcher()
    buf = np.zeros(16, dtype=np.uint8)
    got = []
    run(m.post_recv(memoryview(buf), 0, 0, lambda t, n: got.append((t, n)),
                    lambda e: got.append(e)))  # wildcard
    msg, fires = m.on_message_start(PROBE_TAG, 8)
    run(fires)
    assert msg.discard and not m.unexpected and not got
    run(m.on_message_complete(msg))
    assert not got and len(m.posted) == 1  # wildcard still armed

    # The inproc fast path drops probes too.
    run(m.deliver(PROBE_TAG, memoryview(b"\x00" * 8)))
    assert not got and not m.unexpected
