"""All-to-all shuffle composed from tagged P2P -- the host-API counterpart of
parallel/all_to_all.py's single jitted collective.

BASELINE config 4 pattern ("1GB jax.Array all-to-all shuffle, KV-cache
disaggregation"): N logical ranks, each holding N chunks, redistribute so
rank j ends up with chunk j from every rank.  Each rank runs a Server
(worker-address bootstrap, no TCP listener semantics needed by callers) and
connects a Client to every peer; chunks are routed purely by tag
(tag = source_rank), the reference's multi-client fan-in pattern.

Run:  python examples/all_to_all_p2p.py [--ranks 4] [--chunk 1M]
"""

import argparse
import asyncio
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from starway_tpu import Client, Server  # noqa: E402

MASK = (1 << 64) - 1


async def main(n_ranks: int, chunk_bytes: int) -> None:
    # Bootstrap: every rank listens and publishes its worker address.
    servers = [Server() for _ in range(n_ranks)]
    addresses = [s.listen_address() for s in servers]

    # Full-mesh clients: clients[i][j] = rank i's connection to rank j.
    clients: list[dict[int, Client]] = [dict() for _ in range(n_ranks)]

    async def connect_all(i: int) -> None:
        for j in range(n_ranks):
            if j == i:
                continue
            c = Client()
            await c.aconnect_address(addresses[j])
            clients[i][j] = c

    await asyncio.gather(*(connect_all(i) for i in range(n_ranks)))

    # Source data: rank i's chunk destined for rank j is filled with i*16+j.
    data = [
        np.stack([np.full(chunk_bytes, (i * 16 + j) % 251, dtype=np.uint8)
                  for j in range(n_ranks)])
        for i in range(n_ranks)
    ]
    out = [np.zeros((n_ranks, chunk_bytes), dtype=np.uint8) for _ in range(n_ranks)]

    import time

    t0 = time.perf_counter()

    async def exchange(i: int) -> None:
        recvs = [
            servers[i].arecv(out[i][src], src, MASK)
            for src in range(n_ranks) if src != i
        ]
        sends = [
            clients[i][j].asend(data[i][j], i)  # tag = source rank
            for j in range(n_ranks) if j != i
        ]
        out[i][i] = data[i][i]  # local chunk stays
        await asyncio.gather(*sends, *recvs)
        await asyncio.gather(*(clients[i][j].aflush() for j in clients[i]))

    await asyncio.gather(*(exchange(i) for i in range(n_ranks)))
    dt = time.perf_counter() - t0

    # Verify: rank j's row from src i must carry pattern i*16+j.
    for j in range(n_ranks):
        for i in range(n_ranks):
            assert (out[j][i] == (i * 16 + j) % 251).all(), (i, j)

    moved = n_ranks * (n_ranks - 1) * chunk_bytes
    print(f"all-to-all ok: {n_ranks} ranks x {chunk_bytes} B chunks, "
          f"{moved / 1e6:.1f} MB moved in {dt * 1e3:.1f} ms "
          f"({moved / dt / 1e9:.2f} GB/s aggregate)")

    for i in range(n_ranks):
        for c in clients[i].values():
            await c.aclose()
    for s in servers:
        await s.aclose()


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--ranks", type=int, default=4)
    ap.add_argument("--chunk", default="1M")
    args = ap.parse_args()
    from starway_tpu.bench import parse_size

    asyncio.run(main(args.ranks, parse_size(args.chunk)))
