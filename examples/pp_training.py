"""Pipeline-parallel Llama training: plain 1F1B and interleaved chunks.

Trains the same tiny model two ways on a virtual pp (x dp) mesh and shows
the schedules agree with each other (same math, different fill cost):

* plain 1F1B  — one stage per device (parallel/pipeline.py)
* interleaved — 2 virtual chunks per device (parallel/interleaved.py);
  fill shrinks (V-1)(S-2) ticks, worth it at small microbatch counts

Runs on the virtual CPU mesh anywhere: no TPU needed.

Usage:  python examples/pp_training.py [--steps 4] [--dp]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--dp", action="store_true",
                    help="compose with data parallelism (pp2 x dp2 mesh)")
    args = ap.parse_args()

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")  # virtual mesh demo

    import jax.numpy as jnp
    import numpy as np
    import optax

    from starway_tpu.models import (LlamaConfig, init_params,
                                    make_pp_llama_train, pp_split_params,
                                    ppv_split_params, shard_pp_params,
                                    shard_ppv_params)
    from starway_tpu.parallel import make_mesh

    cfg = LlamaConfig.preset("debug", n_layers=4, d_model=64, n_heads=4,
                             n_kv_heads=2, d_ff=96, vocab_size=256)
    params = init_params(jax.random.PRNGKey(0), cfg)
    axes = {"pp": 2, "dp": 2} if args.dp else {"pp": 2}
    mesh = make_mesh(axes)
    dp_axis = "dp" if args.dp else None
    batch = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (8, 17), dtype=np.int32))

    loss_by_schedule = {}
    for name, n_chunks in (("plain 1F1B", 1), ("interleaved x2", 2)):
        if n_chunks == 1:
            pp = shard_pp_params(pp_split_params(params, 2), mesh)
        else:
            pp = shard_ppv_params(ppv_split_params(params, 2, 2), mesh)
        step = make_pp_llama_train(mesh, cfg, n_micro=4, n_chunks=n_chunks,
                                   dp_axis=dp_axis)
        tx = optax.adamw(3e-3)
        opt = tx.init(pp)
        losses = []
        for _ in range(args.steps):
            loss, grads = step(pp, batch)
            updates, opt = tx.update(grads, opt, pp)
            pp = optax.apply_updates(pp, updates)
            losses.append(float(loss))
        print(f"{name:15s} mesh={axes}: losses "
              f"{[round(l, 4) for l in losses]}")
        assert all(np.isfinite(losses))
        if args.steps >= 2:
            assert losses[-1] < losses[0]
        loss_by_schedule[name] = losses

    a, b = loss_by_schedule.values()
    np.testing.assert_allclose(a, b, rtol=1e-5)  # same math, pinned
    print("both schedules train with matching losses = same math:")
    print("  (fill-cost difference shows on real hardware, not the "
          "virtual mesh)")


if __name__ == "__main__":
    main()
