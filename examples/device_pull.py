"""Cross-process device transfer over the PJRT pull path (devpull).

Two processes, each with its own JAX runtime: the child sends a jax.Array,
the parent receives it into a DeviceBuffer.  The payload moves
device-to-device over the PJRT transfer socket -- the framework never
stages the bytes through the host (sink.last_transport proves which path
ran).  The reference's closest analogue is its zero-copy RDMA into the
receiver's buffer; this is the TPU-native equivalent
(DESIGN.md section 7, tests/test_devpull.py).

Run:  python examples/device_pull.py  [--size 16M]
"""

from __future__ import annotations

import argparse
import asyncio
import multiprocessing
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MASK = (1 << 64) - 1
TAG = 0x9D


def parse_size(text: str) -> int:
    mult = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30}.get(text[-1].lower(), 1)
    return int(text[:-1] if mult > 1 else text) * mult


def child(port: int, nbytes: int) -> None:
    os.environ.setdefault("STARWAY_TLS", "tcp")
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from starway_tpu import Client

    jax.devices()  # devpull is advertised once the backend is up

    async def run() -> None:
        client = Client()
        for _ in range(100):
            try:
                await client.aconnect("127.0.0.1", port)
                break
            except Exception:
                client = Client()
                await asyncio.sleep(0.1)
        payload = jax.device_put(jnp.arange(nbytes, dtype=jnp.uint8))
        await client.asend(payload, TAG)
        await client.aflush()  # barrier: payload resident at the receiver
        await client.aclose()

    asyncio.run(run())


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", default="16M")
    args = ap.parse_args()
    nbytes = parse_size(args.size)

    os.environ.setdefault("STARWAY_TLS", "tcp")
    import time

    import numpy as np

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from starway_tpu import DeviceBuffer, Server

    jax.devices()

    async def run() -> None:
        server = Server()
        server.listen("127.0.0.1", 0)
        import json

        port = json.loads(server.get_worker_address())["port"]
        ctx = multiprocessing.get_context("spawn")
        proc = ctx.Process(target=child, args=(port, nbytes), daemon=True)
        proc.start()

        sink = DeviceBuffer((nbytes,), jnp.uint8)
        t0 = time.perf_counter()
        tag, length = await asyncio.wait_for(server.arecv(sink, TAG, MASK), 60)
        dt = time.perf_counter() - t0
        assert (tag, length) == (TAG, nbytes)
        ok = bool((np.asarray(sink.array) == np.arange(nbytes, dtype=np.uint8)).all())
        print(f"received {nbytes} bytes via {sink.last_transport!r} "
              f"in {dt:.3f}s (includes peer startup) content_ok={ok}")
        proc.join(10)
        await server.aclose()

    asyncio.run(run())


if __name__ == "__main__":
    main()
