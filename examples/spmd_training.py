"""SPMD training tour: ZeRO/FSDP and pipeline-parallel Llama on one host.

Runs on the virtual CPU mesh (no TPU needed) — the same code shards over
real chips when a TPU mesh is present.  Three parts:

  1. Trainer in ZeRO mode: params + Adam state sharded 1/N over "fsdp",
     XLA inserting the all-gather/reduce-scatter schedule.
  2. The same ZeRO step assembled from the low-level pieces
     (parallel/fsdp.py) for custom training loops.
  3. End-to-end pipeline-parallel Llama (models/pp_llama.py): embed +
     collective 1F1B over "pp" + head, every parameter receiving grads.

Usage:  python examples/spmd_training.py [--devices 8] [--steps 4]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--platform", choices=("cpu", "auto"), default="cpu",
                    help="cpu (default): virtual host mesh, runs anywhere; "
                         "auto: whatever backend jax picks (real chips)")
    args = ap.parse_args()

    # Virtual device mesh when demoing on CPU (must precede the first jax
    # backend use; see tests/conftest.py for the same dance).
    flags = os.environ.get("XLA_FLAGS", "")
    if args.platform == "cpu" and "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={args.devices}".strip())
    import jax

    if args.platform == "cpu":
        # Unconditional: an interpreter hook may have pre-selected a device
        # backend, and the env var alone is too late once jax is imported.
        jax.config.update("jax_platforms", "cpu")

    if len(jax.devices()) < args.devices:
        raise SystemExit(f"need {args.devices} devices, have {len(jax.devices())}")

    import numpy as np
    import jax.numpy as jnp
    import optax

    from starway_tpu.models import (LlamaConfig, init_params,
                                    make_pp_llama_train, make_train_step,
                                    pp_split_params, shard_pp_params)
    from starway_tpu.models.trainer import Trainer
    from starway_tpu.parallel import (fsdp_specs, make_fsdp_train_step,
                                      make_mesh, shard_tree)

    cfg = LlamaConfig.preset("debug", d_model=64, n_heads=4, n_kv_heads=4,
                             d_ff=128, vocab_size=256, n_layers=4)
    rng = np.random.default_rng(0)
    batch = lambda: jnp.asarray(
        rng.integers(0, cfg.vocab_size, (args.devices, 33), dtype=np.int32))

    # -- 1. High-level: Trainer in ZeRO mode ------------------------------
    mesh = make_mesh({"fsdp": args.devices})
    trainer = Trainer(cfg, optax.adamw(3e-3),
                      init_params(jax.random.PRNGKey(0), cfg),
                      mesh=mesh, fsdp_axis="fsdp")
    for _ in range(args.steps):
        loss = trainer.step_sync(batch())
    emb = trainer.state.params["embed"]
    print(f"[fsdp/Trainer] {args.steps} steps, loss={loss:.4f}, "
          f"embed shard {emb.addressable_shards[0].data.shape} of {emb.shape}")

    # -- 2. Low-level: the same ZeRO step from parts ----------------------
    params = init_params(jax.random.PRNGKey(1), cfg)
    tx = optax.adamw(3e-3)
    pspecs = fsdp_specs(params, mesh)
    ospecs = fsdp_specs(jax.eval_shape(tx.init, params), mesh)
    p = shard_tree(params, mesh, pspecs)
    o = shard_tree(tx.init(params), mesh, ospecs)
    step = make_fsdp_train_step(make_train_step(cfg, tx), mesh, pspecs, ospecs)
    for _ in range(args.steps):
        p, o, loss = step(p, o, batch())
    print(f"[fsdp/manual]  {args.steps} steps, loss={float(loss):.4f}")

    # -- 3. Pipeline-parallel Llama (1F1B, all grads) ---------------------
    # Stage count must divide n_layers; microbatch count must divide the
    # batch — derive both from the device budget instead of assuming 4/8.
    pp_n = max(d for d in (4, 2, 1) if d <= args.devices and cfg.n_layers % d == 0)
    n_micro, bsz = 4, 8
    mesh_pp = make_mesh({"pp": pp_n})
    pp_params = shard_pp_params(
        pp_split_params(init_params(jax.random.PRNGKey(2), cfg), pp_n), mesh_pp)
    pp_step = make_pp_llama_train(mesh_pp, cfg, n_micro=n_micro)
    tx_pp = optax.adamw(3e-3)
    opt_pp = tx_pp.init(pp_params)
    fixed = jnp.asarray(
        rng.integers(0, cfg.vocab_size, (bsz, 33), dtype=np.int32))
    for _ in range(args.steps):
        loss, grads = pp_step(pp_params, fixed)
        updates, opt_pp = tx_pp.update(grads, opt_pp, pp_params)
        pp_params = optax.apply_updates(pp_params, updates)
    print(f"[pp-llama]     {pp_n} stages x {cfg.n_layers // pp_n} layers, "
          f"{args.steps} steps on one batch, loss={float(loss):.4f}")


if __name__ == "__main__":
    main()
