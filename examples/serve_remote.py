"""Demo: a served model BEHIND the transport — the repo's two halves meet.

One process runs a SlotServer (continuous batching) bridged onto a
starway Server; requests arrive as tagged messages, admission interleaves
them into the running batch, and each request's tokens stream back
per decode chunk over its own connection (models/remote_serving.py).
Three client sessions submit concurrently, print their streams as chunks
arrive, and every greedy result is cross-checked against standalone
``generate()``.

Run:  python examples/serve_remote.py            (in-process fast path)
      STARWAY_TLS=tcp python examples/serve_remote.py   (real sockets)
      STARWAY_NATIVE=1 STARWAY_TLS=tcp python examples/serve_remote.py
"""

import asyncio
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # demo runs anywhere; see CLAUDE.md

import jax.numpy as jnp  # noqa: E402

from starway_tpu.models import LlamaConfig, SlotServer, init_params  # noqa: E402
from starway_tpu.models.generate import generate  # noqa: E402
from starway_tpu.models.remote_serving import (  # noqa: E402
    RemoteGenerateSession, RemoteSlotServer)

PORT = 23981


async def main() -> None:
    cfg = LlamaConfig.preset("debug")
    params = init_params(jax.random.PRNGKey(0), cfg)

    slot = SlotServer(params, cfg, n_slots=2, max_len=64, chunk=4)
    bridge = RemoteSlotServer(slot)
    bridge.server.listen("127.0.0.1", PORT)
    serve_task = asyncio.create_task(bridge.serve())

    rng = np.random.default_rng(0)
    reqs = [(list(map(int, rng.integers(1, cfg.vocab_size, n))), m)
            for n, m in [(5, 12), (9, 6), (3, 9), (7, 4), (4, 10)]]

    sessions = [await RemoteGenerateSession.aconnect("127.0.0.1", PORT)
                for _ in range(3)]
    print(f"3 sessions connected (client ids "
          f"{[s.client_id for s in sessions]}); "
          f"{len(reqs)} requests over 2 slots")

    async def one(i, prompt, max_new):
        chunks = []
        out = await sessions[i % 3].generate(
            prompt, max_new, on_tokens=lambda c: chunks.append(list(c)))
        print(f"  req {i}: {len(out)} tokens in {len(chunks)} stream "
              f"chunks {chunks}")
        return out

    outs = await asyncio.gather(*(one(i, p, m)
                                  for i, (p, m) in enumerate(reqs)))

    bridge.stop()
    await serve_task
    for s in sessions:
        await s.aclose()
    await bridge.aclose()

    for i, ((prompt, max_new), got) in enumerate(zip(reqs, outs)):
        want = np.asarray(generate(params, cfg,
                                   jnp.asarray([prompt], jnp.int32),
                                   max_new)[0, len(prompt):])
        assert np.array_equal(got, want), f"request {i} diverged"
    print(f"all {len(reqs)} streams cross-checked against standalone "
          f"generate(): OK")


if __name__ == "__main__":
    asyncio.run(main())
