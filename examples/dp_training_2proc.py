"""Two-process data-parallel training over the async P2P fabric.

BASELINE config 5 as a living loop: two ranks (separate processes, i.e. the
DP boundary between TPU hosts) each run the flagship Llama model on their own
batch shard and average gradients every step by exchanging pytrees through
``asend``/``arecv`` + ``aflush`` -- the pattern a reference user would build
by hand, here via parallel/dp_exchange.py.

Rank 0 serves (worker-address bootstrap written to a handoff file); rank 1
connects.  Both apply identical averaged updates, so parameters stay
bit-identical across ranks -- asserted at the end.

Run:  python examples/dp_training_2proc.py [--steps 3]
"""

import argparse
import asyncio
import multiprocessing as mp
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

GRAD_TAG = 0x6000
STEPS_DEFAULT = 3


def _setup_jax():
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


def _build(step_count: int):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from starway_tpu.models import LlamaConfig, init_params, loss_fn

    cfg = LlamaConfig.preset("debug")
    params = init_params(jax.random.PRNGKey(0), cfg)  # same seed on both ranks
    tx = optax.adamw(1e-3)
    opt_state = tx.init(params)
    grad_fn = jax.jit(lambda p, b: jax.value_and_grad(loss_fn)(p, b, cfg))
    return cfg, params, tx, opt_state, grad_fn


async def _train(rank: int, port_file: str, steps: int) -> bytes:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from starway_tpu import Client, Server
    from starway_tpu.parallel import ClientPort, ServerPort, recv_pytree, send_pytree

    cfg, params, tx, opt_state, grad_fn = _build(steps)

    if rank == 0:
        server = Server()
        blob = server.listen_address()
        with open(port_file, "wb") as f:
            f.write(blob)
        while not server.list_clients():
            await asyncio.sleep(0.05)
        port = ServerPort(server)
        endpoint = server
    else:
        for _ in range(100):
            if os.path.exists(port_file) and os.path.getsize(port_file):
                break
            await asyncio.sleep(0.1)
        blob = open(port_file, "rb").read()
        client = Client()
        for i in range(40):
            try:
                await client.aconnect_address(blob)
                break
            except Exception:
                client = Client()
                await asyncio.sleep(0.25)
        port = ClientPort(client)
        endpoint = client

    rng = np.random.default_rng(100 + rank)  # different data per rank
    for step in range(steps):
        batch = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 33), dtype=np.int32))
        loss, grads = grad_fn(params, batch)

        # DP boundary: exchange gradient pytrees and average.
        base = GRAD_TAG + step * 256
        send_task = asyncio.ensure_future(send_pytree(port, grads, base_tag=base))
        peer_grads = await recv_pytree(port, like=grads, base_tag=base)
        await send_task
        grads = jax.tree_util.tree_map(lambda a, b: (a + b) / 2, grads, peer_grads)

        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        print(f"[rank {rank}] step {step}: loss={float(loss):.4f}", flush=True)

    digest = np.concatenate(
        [np.asarray(x, dtype=np.float32).ravel()[:8] for x in jax.tree_util.tree_leaves(params)]
    ).tobytes()
    if rank == 0:
        await endpoint.aclose()
    else:
        await endpoint.aclose()
    return digest


def _rank_main(rank: int, port_file: str, steps: int, out_q) -> None:
    _setup_jax()
    digest = asyncio.run(_train(rank, port_file, steps))
    out_q.put((rank, digest))


def main(steps: int) -> None:
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    with tempfile.TemporaryDirectory() as td:
        pf = os.path.join(td, "addr.bin")
        ps = [ctx.Process(target=_rank_main, args=(r, pf, steps, q), daemon=True) for r in (0, 1)]
        for p in ps:
            p.start()
        digests = dict(q.get(timeout=600) for _ in range(2))
        for p in ps:
            p.join()
    assert digests[0] == digests[1], "ranks diverged after averaged updates!"
    print(f"OK: {steps} DP steps, parameters identical across ranks")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=STEPS_DEFAULT)
    args = ap.parse_args()
    main(args.steps)
