"""Manual perf: concurrent large sends (reference: test.py:18-56).

Fires 5 x 1 GiB sends concurrently from client to server and reports
aggregate throughput.

Run:  python examples/throughput.py [--tls tcp] [--count 5] [--size 1g]
"""

import argparse
import asyncio
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from starway_tpu import Client, Server  # noqa: E402

PORT = 23753


async def main(count: int, size: int) -> None:
    server = Server()
    server.listen("127.0.0.1", PORT)
    client = Client()
    await client.aconnect("127.0.0.1", PORT)

    payloads = [np.full(size, i, dtype=np.uint8) for i in range(count)]
    sinks = [np.empty(size, dtype=np.uint8) for _ in range(count)]

    t0 = time.perf_counter()
    recvs = [server.arecv(s, 0, 0) for s in sinks]
    sends = [client.asend(p, i) for i, p in enumerate(payloads)]
    await asyncio.gather(*sends, *recvs)
    dt = time.perf_counter() - t0

    total = count * size
    print(f"{count} x {size} bytes in {dt:.3f}s -> {total / dt / 1e9:.2f} GB/s aggregate")

    await client.aclose()
    await server.aclose()


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--tls")
    ap.add_argument("--count", type=int, default=5)
    ap.add_argument("--size", default="1g")
    args = ap.parse_args()
    if args.tls:
        os.environ["STARWAY_TLS"] = args.tls
    from starway_tpu.bench import parse_size

    asyncio.run(main(args.count, parse_size(args.size)))
