"""Serve a HuggingFace Llama through the framework's decode path.

End-to-end serving demo: convert a transformers ``LlamaForCausalLM`` into
the framework's parameter tree, then answer a RAGGED batch of prompts
(different lengths, one compiled dispatch) with greedy or sampled decoding
and eos-fill — and cross-check one row against transformers' own
``generate``.

Uses a tiny random model so it runs anywhere; point ``--model`` at a local
HF checkpoint directory to serve real weights.  ``--arch llama31`` swaps
the demo model for a Llama-3.1-style config — decoupled ``head_dim`` and
``llama3`` rope scaling — exercising the modern-checkpoint conversion path
end to end (hf_convert.py; VERDICT r3 #6).

Usage:  python examples/serve_hf.py [--model DIR] [--max-new 12]
        [--arch llama\|llama31\|qwen2\|qwen25\|mixtral\|gemma\|phi3\|phi35]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default=None,
                    help="local HF checkpoint dir (default: tiny random model)")
    ap.add_argument("--max-new", type=int, default=12)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--quantize", choices=["none", "int8"], default="none",
                    help="int8 = W8A16 weight-only serving tree "
                         "(half the weight HBM; see ops/quantize.py)")
    ap.add_argument("--arch",
                    choices=["llama", "llama31", "qwen2", "qwen25",
                             "mixtral", "gemma", "phi3", "phi35"],
                    default="llama",
                    help="demo-model flavour: llama31 = decoupled head_dim "
                         "+ llama3 rope scaling; qwen2 = q/k/v projection "
                         "biases; mixtral = SwiGLU top-2 MoE experts; "
                         "gemma = GeGLU + (1+w) norms + scaled embeddings; "
                         "phi3 = fused qkv/gate_up projections, "
                         "qwen25 = Qwen2 biases + YaRN rope, "
                         "phi35 = Phi-3 projections + LongRoPE")
    args = ap.parse_args()

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu" or args.model is None:
        # The demo model is tiny; run on CPU unless real weights are given.
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import torch
    import transformers

    from starway_tpu.models import config_from_hf, params_from_hf
    from starway_tpu.models.generate import generate

    if args.model:
        # Auto class: real checkpoints of every served family (Llama,
        # Mistral, Qwen2, Mixtral, Gemma, Phi-3) load through their own
        # architecture.
        hf = transformers.AutoModelForCausalLM.from_pretrained(args.model)
    else:
        torch.manual_seed(0)
        dims = dict(
            vocab_size=512, hidden_size=128, intermediate_size=256,
            num_hidden_layers=2, num_attention_heads=8, num_key_value_heads=4,
            max_position_embeddings=256, attn_implementation="eager")
        if args.arch == "qwen2":
            # Qwen2-style: q/k/v projection biases.
            hf = transformers.Qwen2ForCausalLM(
                transformers.Qwen2Config(**dims))
        elif args.arch == "qwen25":
            # Qwen2.5-long style: Qwen2 biases + YaRN rope scaling
            # (seventh served family).
            hf = transformers.Qwen2ForCausalLM(transformers.Qwen2Config(
                **dims, rope_scaling={
                    "rope_type": "yarn", "factor": 4.0,
                    "original_max_position_embeddings": 64}))
        elif args.arch == "mixtral":
            # Mixtral-style: SwiGLU top-2 MoE FFN (dropless conversion).
            hf = transformers.MixtralForCausalLM(transformers.MixtralConfig(
                **dims, num_local_experts=4, num_experts_per_tok=2))
        elif args.arch == "gemma":
            # Gemma-style: GeGLU, (1+w) norms, sqrt(d)-scaled embeddings.
            hf = transformers.GemmaForCausalLM(transformers.GemmaConfig(
                **dims, head_dim=32))
        elif args.arch == "phi3":
            # Phi-3-style: fused qkv_proj + gate_up_proj, split at
            # conversion.  (Phi3Config's default pad_token_id needs
            # vocab > 32000.)
            hf = transformers.Phi3ForCausalLM(transformers.Phi3Config(
                **{**dims, "vocab_size": 33000}))
        elif args.arch == "phi35":
            # Phi-3.5/128k style: Phi-3 projections + LongRoPE per-dim
            # short/long factor lists (eighth served family).
            half = (dims["hidden_size"] // dims["num_attention_heads"]) // 2
            hf = transformers.Phi3ForCausalLM(transformers.Phi3Config(
                **{**dims, "vocab_size": 33000},
                original_max_position_embeddings=64,
                rope_scaling={
                    "type": "longrope",
                    "short_factor": [1.0 + 0.05 * i for i in range(half)],
                    "long_factor": [2.0 + 0.1 * i for i in range(half)]}))
        else:
            extra = {}
            if args.arch == "llama31":
                # Llama-3.1-style: head_dim pinned independently of
                # hidden_size // n_heads, banded llama3 rope scaling.
                extra = dict(head_dim=32, rope_scaling={
                    "rope_type": "llama3", "factor": 4.0,
                    "low_freq_factor": 1.0, "high_freq_factor": 2.0,
                    "original_max_position_embeddings": 64})
            hf = transformers.LlamaForCausalLM(transformers.LlamaConfig(
                **dims, **extra))
    hf.eval()

    cfg = config_from_hf(hf.config, dtype="float32" if args.model is None
                         else "bfloat16")
    params = params_from_hf(hf, cfg, quantize=args.quantize)
    extras = "".join(
        [f" hd={cfg.head_dim}(override)" if cfg.head_dim_override else "",
         f" rope_scaling={cfg.rope_scaling[0]}" if cfg.rope_scaling else "",
         " (W8A16 int8 weights)" if args.quantize == "int8" else ""])
    print(f"converted: {cfg.n_layers}L d={cfg.d_model} "
          f"Hq={cfg.n_heads}/Hkv={cfg.n_kv_heads} V={cfg.vocab_size}"
          f"{extras}")

    # A ragged batch: three "requests" of different lengths, one dispatch.
    rows = [[11, 3, 9, 1, 4, 2, 8], [7, 5], [2, 6, 1, 9]]
    P = max(map(len, rows))
    padded = jnp.asarray([r + [0] * (P - len(r)) for r in rows], jnp.int32)
    lengths = jnp.asarray([len(r) for r in rows], jnp.int32)
    new = generate(params, cfg, padded, args.max_new,
                   prompt_lengths=lengths, temperature=args.temperature,
                   key=jax.random.PRNGKey(0))
    for b, r in enumerate(rows):
        print(f"request {b} ({len(r)} tokens) -> {list(map(int, new[b]))}")

    # eos-fill demo: force the first continuation token as the terminator —
    # that row comes back all-eos while the others are untouched.  Same
    # sampling settings as the run above, so the first token recurs.
    eos = int(new[0][0])
    filled = generate(params, cfg, padded, args.max_new,
                      prompt_lengths=lengths, eos_id=eos,
                      temperature=args.temperature, key=jax.random.PRNGKey(0))
    print(f"with eos_id={eos}: request 0 -> {list(map(int, filled[0]))}")

    # Token-exact cross-check only in the controlled configuration: greedy
    # + the f32 demo model + full-precision weights.  (A real --model runs
    # bf16 here vs f32 in transformers, quantized weights are a slightly
    # different model by design, and transformers may stop early at its
    # eos — tokens can legitimately differ.)
    if (args.temperature == 0.0 and args.model is None
            and args.quantize == "none"):
        with torch.no_grad():
            ref = hf.generate(torch.tensor([rows[0]]), max_new_tokens=args.max_new,
                              do_sample=False, pad_token_id=0).numpy()
        match = list(map(int, new[0])) == list(ref[0, len(rows[0]):])
        print("row 0 matches transformers.generate:", match)
        if not match:
            raise SystemExit(1)


if __name__ == "__main__":
    main()
