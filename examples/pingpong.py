"""Manual perf sweep: evaluate_perf estimate vs measured throughput.

Analogue of the reference's pingpong.py (reference: pingpong.py:11-47):
sweeps message sizes 1 B .. 1 GiB over a loopback Server/Client pair,
printing the link-model estimate next to the measured number.

Run:  python examples/pingpong.py [--tls tcp] [--max-size 1g] [--uvloop]

``--uvloop`` swaps in uvloop's event loop when the package is available
(the reference's perf script runs under uvloop, reference pingpong.py:6,47
— the asyncio scheduling overhead it removes is exactly the remaining gap
BASELINE.md names on the pingpong headline).  Falls back to stock asyncio
with a warning when uvloop isn't installed (it is not in this sandbox).
"""

import argparse
import asyncio
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from starway_tpu import Client, Server  # noqa: E402

PORT = 23751
TAG = 0x77


async def main(max_size: int) -> None:
    server = Server()
    server.listen("127.0.0.1", PORT)
    client = Client()
    await client.aconnect("127.0.0.1", PORT)
    ep = server.list_clients().pop()

    print(f"{'size':>12} {'est (s)':>12} {'measured (s)':>12} {'GB/s':>8}")
    size = 1
    while size <= max_size:
        buf = np.full(size, 0xA5, dtype=np.uint8)
        sink = np.empty(size, dtype=np.uint8)
        est = client.evaluate_perf(size)

        iters = 3 if size >= (1 << 28) else 10
        t0 = time.perf_counter()
        for _ in range(iters):
            recv_fut = server.arecv(sink, TAG, (1 << 64) - 1)
            await client.asend(buf, TAG)
            await recv_fut
        dt = (time.perf_counter() - t0) / iters
        gbps = size / dt / 1e9 if dt > 0 else float("inf")
        print(f"{size:>12} {est:>12.3e} {dt:>12.3e} {gbps:>8.2f}")
        size *= 16

    await client.aclose()
    await server.aclose()


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--tls", help="STARWAY_TLS override (e.g. tcp)")
    ap.add_argument("--max-size", default="1g")
    ap.add_argument("--uvloop", action="store_true",
                    help="run under uvloop (reference pingpong.py parity)")
    args = ap.parse_args()
    if args.tls:
        os.environ["STARWAY_TLS"] = args.tls
    if args.uvloop:
        try:
            import uvloop
            asyncio.set_event_loop_policy(uvloop.EventLoopPolicy())
        except ImportError:
            print("uvloop not installed; running under stock asyncio",
                  file=sys.stderr)
    from starway_tpu.bench import parse_size

    asyncio.run(main(parse_size(args.max_size)))
