"""Demo: pending ops are cancelled when the endpoint closes.

Analogue of the reference's cb.py (reference: cb.py:12-40): posts a receive
that nothing will ever match, closes the client, and shows the fail callback
firing with a cancellation reason.

Run:  python examples/cancel_on_close.py
"""

import asyncio
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from starway_tpu import Client, Server  # noqa: E402

PORT = 23755


async def main() -> None:
    server = Server()
    server.listen("127.0.0.1", PORT)
    client = Client()
    await client.aconnect("127.0.0.1", PORT)

    sink = np.empty(1024, dtype=np.uint8)

    async def doomed_recv():
        try:
            await client.arecv(sink, tag=999, tag_mask=(1 << 64) - 1)
            print("recv completed (unexpected!)")
        except Exception as e:
            print(f"recv failed as expected: {e}")

    task = asyncio.create_task(doomed_recv())
    await asyncio.sleep(0.05)
    print("closing client with recv in flight...")
    await client.aclose()
    await task
    await server.aclose()
    print("done")


if __name__ == "__main__":
    asyncio.run(main())
