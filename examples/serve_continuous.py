"""Continuous-batching demo: a request stream through a fixed slot set.

Requests arrive over time (here: submitted between decode chunks), cohabit
the slot batch, finish at different lengths, and free their slot for the
next arrival immediately — no waiting for the batch to drain.  Greedy
outputs are bit-identical to one-at-a-time ``generate()`` calls; this demo
cross-checks one request against that oracle.

Uses the tiny debug model so it runs anywhere (CPU included); swap in
converted HF weights (examples/serve_hf.py shows the conversion) to serve
a real checkpoint.

Usage:  python examples/serve_continuous.py [--requests 12] [--slots 3]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=12)
    ap.add_argument("--slots", type=int, default=3)
    ap.add_argument("--chunk", type=int, default=8)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--device", action="store_true",
                    help="use the configured accelerator instead of CPU")
    args = ap.parse_args()

    import jax

    if not args.device:
        # Env vars alone do not switch platforms here (a TPU backend may be
        # pre-registered at interpreter start); the config call does.
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from starway_tpu.models import LlamaConfig, SlotServer, init_params
    from starway_tpu.models.generate import generate

    cfg = LlamaConfig.preset("debug")
    params = init_params(jax.random.PRNGKey(0), cfg)
    srv = SlotServer(params, cfg, n_slots=args.slots, max_len=96,
                     chunk=args.chunk, temperature=args.temperature, seed=7)

    rng = np.random.default_rng(0)
    reqs = {}
    t0 = time.time()
    done = {}
    # Arrivals interleave with decode chunks — the continuous part.
    for i in range(args.requests):
        prompt = list(rng.integers(1, cfg.vocab_size,
                                   int(rng.integers(2, 16))))
        max_new = int(rng.integers(4, 12))
        reqs[srv.submit(prompt, max_new)] = (prompt, max_new)
        done.update(srv.step())
    done.update(srv.run())
    dt = time.time() - t0

    total = sum(len(v) for v in done.values())
    print(f"served {len(done)} requests / {total} tokens in {dt:.2f}s "
          f"({total / dt:.1f} tok/s wall) through {args.slots} slots")
    for rid in sorted(done)[:4]:
        print(f"  req {rid}: +{len(done[rid])} tokens {done[rid].tolist()}")

    if args.temperature == 0.0 and done:
        rid0 = sorted(done)[0]
        prompt, max_new = reqs[rid0]
        solo = generate(params, cfg,
                        jax.numpy.asarray([prompt], jax.numpy.int32), max_new)
        want = np.asarray(solo[0, len(prompt):])
        assert (done[rid0] == want).all(), "continuous != standalone greedy!"
        print(f"  req {rid0} cross-checked against standalone generate(): OK")

    # Prefix caching: a shared "system prompt" prefilled ONCE; requests
    # submit only their suffix and still generate exactly what
    # generate(prefix + suffix) would.
    if args.temperature == 0.0:
        system = list(rng.integers(1, cfg.vocab_size, 11))
        pid = srv.register_prefix(system)
        suffixes = [list(rng.integers(1, cfg.vocab_size, n))
                    for n in (3, 5, 2)]
        prids = [srv.submit(s, 6, prefix=pid) for s in suffixes]
        pdone = srv.run()
        for prid, suffix in zip(prids, suffixes):
            solo = generate(
                params, cfg,
                jax.numpy.asarray([system + suffix], jax.numpy.int32), 6)
            want = np.asarray(solo[0, len(system) + len(suffix):])
            assert (pdone[prid] == want).all(), "prefix != full-prompt!"
        print(f"  prefix caching: {len(prids)} suffix-only requests over "
              f"one {len(system)}-token cached prefix, all match "
              f"generate(prefix + suffix)")


if __name__ == "__main__":
    main()
