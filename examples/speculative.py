"""Speculative decoding demo: draft-proposed tokens, target-verified.

A cheap draft model proposes ``gamma - 1`` tokens; the target model checks
the whole chunk in ONE forward and keeps the accepted prefix (plus one
corrected/bonus token) — the target's KV cache streams once per accepted
run instead of once per token, which is the whole speedup on a
bandwidth-bound decode.  Greedy output matches plain ``generate()``
token for token (up to bf16 argmax near-ties between the chunk and
stepwise forwards): the draft changes how fast tokens appear.

Uses the tiny debug model so it runs anywhere (CPU included).  With
random weights a shallow draft rarely agrees with the target, so the demo
also runs a self-draft (acceptance ~1) to show the mechanism at both ends;
a real deployment pairs a trained target with a distilled draft
(examples/serve_hf.py shows how checkpoints convert in).

Usage:  python examples/speculative.py [--gamma 4] [--max-new 24]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--gamma", type=int, default=4)
    ap.add_argument("--max-new", type=int, default=24)
    ap.add_argument("--device", action="store_true",
                    help="run on the default (TPU) backend instead of CPU")
    args = ap.parse_args()

    import jax

    if not args.device:
        # Env vars alone do not switch platforms here (a TPU backend may be
        # pre-registered at interpreter start); the config call does —
        # and probing jax.default_backend() first would INITIALISE the
        # tunneled TPU, hanging when it is unreachable.
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from starway_tpu.models import LlamaConfig, init_params
    from starway_tpu.models.generate import generate
    from starway_tpu.models.speculative import generate_speculative

    cfg = LlamaConfig.preset("debug")
    dcfg = LlamaConfig.preset("debug", n_layers=1)
    params = init_params(jax.random.PRNGKey(0), cfg)
    dparams = init_params(jax.random.PRNGKey(1), dcfg)
    prompt = jnp.asarray(np.random.default_rng(0).integers(
        1, cfg.vocab_size, (2, 8), dtype=np.int32))

    ref = generate(params, cfg, prompt, args.max_new)

    from starway_tpu.models.speculative import (draft_from_truncation,
                                                generate_lookup)

    def report(name, out, stats):
        same = bool((out == ref).all())
        steps = np.asarray(stats["macro_steps"], np.float64)
        acc = np.asarray(stats["accepted"], np.float64)
        rate = acc.sum() / max(steps.sum() * (args.gamma - 1), 1)
        amort = (acc.sum() + steps.sum()) / max(steps.sum(), 1)
        print(f"{name}: bit-identical to generate(): {same}; "
              f"acceptance {rate:.0%}, {amort:.2f} tokens/target-pass "
              f"(gamma={args.gamma})")
        assert same, "greedy speculative output diverged from generate()"

    # A FREE draft: the target's own first layer (no second checkpoint).
    tparams, tcfg = draft_from_truncation(params, cfg, 1)
    for name, dp, dc in (("shallow draft (1L, random)", dparams, dcfg),
                         ("truncation draft (target[:1])", tparams, tcfg),
                         ("self-draft (acceptance ~1)", params, cfg)):
        out, stats = generate_speculative(
            params, cfg, dp, dc, prompt, args.max_new, gamma=args.gamma,
            return_stats=True)
        report(name, out, stats)
    # Prompt-lookup: no draft model at all — proposals copy the latest
    # matching n-gram continuation from the sequence's own history.
    out, stats = generate_lookup(params, cfg, prompt, args.max_new,
                                 gamma=args.gamma, ngram=2,
                                 return_stats=True)
    report("prompt-lookup (ngram=2, draft-free)", out, stats)
    print("ok")


if __name__ == "__main__":
    main()
