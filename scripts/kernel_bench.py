"""On-chip kernel benchmarks: tunnel-immune TFLOP/s for the hot kernels.

Every benchmark jits a ``lax.fori_loop`` of N dependent kernel invocations so
the whole measurement is ONE dispatch — the sandbox tunnel's ~100 ms RTT is
amortized away and the number reflects on-device compute only.  The loop body
perturbs the input with the previous output (``q + o*0``-style chaining would
be folded; we add a tiny carry-dependent epsilon) so XLA cannot CSE the calls.

Reference hook: /root/reference/benchmark.md defines transfer scenarios only;
compute-efficiency benchmarks are the TPU build's own north star (VERDICT r1
next-round #1/#4).

Usage:  python scripts/kernel_bench.py [--iters 8] [--which all|matmul|flash|...]
Emits one JSON line per benchmark row.
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax


def _timeit(fn, *args, iters: int, reps: int = 4):
    """Per-call seconds for `fn`'s kernel, tunnel-immune.

    On this sandbox the device sits behind a tunnel with ~70-100 ms dispatch
    RTT and `block_until_ready` returns before execution finishes, so we (a)
    force a scalar device->host read to synchronize and (b) time the SAME
    compiled loop at `iters` and at 1 iteration, using the difference to
    cancel the constant tunnel/dispatch/readback cost.
    """

    def run(n):
        c = jax.jit(functools.partial(fn, iters=n)).lower(*args).compile()
        float(c(*args))  # warmup (compile transfer etc.)
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            float(c(*args))
            best = min(best, time.perf_counter() - t0)
        return best

    # Difference two LONG runs: a tunnel hiccup in a short baseline run
    # deflates the subtracted constant and wildly inflates the rate.  With
    # both runs >> RTT the constant cancels and hiccups only shrink the
    # reported rate slightly (best-of-reps already dampens them).
    iters = max(iters, 2)  # the difference needs two distinct loop counts
    mid = max(iters // 2, 1)
    t_hi, t_mid = run(iters), run(mid)
    return max(t_hi - t_mid, 1e-9) / (iters - mid)


def _chain(kernel, q, *rest, iters):
    """fori_loop of `iters` dependent kernel calls; returns a sync scalar."""

    def body(_, carry):
        # carry-dependent zero-ish perturbation defeats CSE without changing
        # the math measurably.
        qq = q + carry[(0,) * carry.ndim].astype(q.dtype) * jnp.asarray(
            1e-30, q.dtype
        )
        return kernel(qq, *rest)

    out0 = kernel(q, *rest)
    out = lax.fori_loop(0, iters - 1, body, out0)
    return out[(0,) * out.ndim].astype(jnp.float32)


def bench_matmul(n: int = 8192, iters: int = 8):
    """bf16 n^3 matmul + tanh — the chip's demonstrated compute ceiling."""
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (n, n), jnp.bfloat16)
    b = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.bfloat16)

    def k(a, b):
        return jnp.tanh(jnp.dot(a, b, preferred_element_type=jnp.float32)).astype(
            jnp.bfloat16
        )

    dt = _timeit(lambda a, b, iters: _chain(k, a, b, iters=iters), a, b, iters=iters)
    tflops = 2 * n**3 / dt / 1e12
    return {"metric": "matmul_ceiling_tflops", "value": round(tflops, 2),
            "unit": "TFLOP/s", "detail": f"bf16 {n}^3, {dt*1e3:.2f} ms/iter"}


def _attn_flops(b, hq, s, d, causal):
    f = 4 * b * hq * s * s * d
    return f // 2 if causal else f


def bench_flash_fwd(b=1, hq=8, hkv=2, s=8192, d=128, causal=True, iters: int = 8,
                    impl="ours"):
    from starway_tpu.ops.pallas_attention import flash_attention

    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (b, hq, s, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, hkv, s, d), jnp.bfloat16)
    v = jax.random.normal(kv, (b, hkv, s, d), jnp.bfloat16)

    if impl == "ours":
        kern = functools.partial(flash_attention, causal=causal)
    elif impl == "stock":
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as stock,
        )

        # Stock kernel wants hq == hkv; expand grouped kv like repeat_kv.
        def kern(q, k, v):
            n_rep = hq // hkv
            ke = jnp.repeat(k, n_rep, axis=1)
            ve = jnp.repeat(v, n_rep, axis=1)
            return stock(q, ke, ve, causal=causal,
                         sm_scale=1.0 / d**0.5)
    else:
        raise ValueError(impl)

    dt = _timeit(lambda q, k, v, iters: _chain(kern, q, k, v, iters=iters),
                 q, k, v, iters=iters)
    tflops = _attn_flops(b, hq, s, d, causal) / dt / 1e12
    return {"metric": f"flash_fwd_{impl}_tflops", "value": round(tflops, 2),
            "unit": "TFLOP/s",
            "detail": f"B={b} Hq={hq} Hkv={hkv} S={s} D={d} causal={causal} "
                      f"bf16, {dt*1e3:.2f} ms/iter"}


def bench_flash_bwd(b=1, hq=8, hkv=2, s=8192, d=128, causal=True, iters: int = 4,
                    impl="ours"):
    from starway_tpu.ops.pallas_attention import flash_attention

    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (b, hq, s, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, hkv, s, d), jnp.bfloat16)
    v = jax.random.normal(kv, (b, hkv, s, d), jnp.bfloat16)

    if impl == "ours":
        base = functools.partial(flash_attention, causal=causal)
    elif impl == "stock":
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as stock,
        )

        def base(q, k, v):
            n_rep = hq // hkv
            return stock(q, jnp.repeat(k, n_rep, axis=1),
                         jnp.repeat(v, n_rep, axis=1), causal=causal,
                         sm_scale=1.0 / d**0.5)
    else:
        raise ValueError(impl)

    def kern(q, k, v):
        loss = lambda q, k, v: base(q, k, v).astype(jnp.float32).sum()
        dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        return dq + 0 * dk.sum() + 0 * dv.sum()

    dt = _timeit(lambda q, k, v, iters: _chain(kern, q, k, v, iters=iters),
                 q, k, v, iters=iters)
    # fwd (recomputed) + bwd ≈ 3.5x fwd flops (2 fwd matmuls + 5 bwd matmuls)
    tflops = _attn_flops(b, hq, s, d, causal) * 3.5 / dt / 1e12
    return {"metric": f"flash_fwdbwd_{impl}_tflops", "value": round(tflops, 2),
            "unit": "TFLOP/s",
            "detail": f"B={b} Hq={hq} Hkv={hkv} S={s} D={d} causal={causal} "
                      f"bf16, {dt*1e3:.2f} ms/iter (fwd+bwd)"}


BENCHES = {
    "matmul": bench_matmul,
    "flash": bench_flash_fwd,
    "flash_stock": functools.partial(bench_flash_fwd, impl="stock"),
    "flash_bwd": bench_flash_bwd,
    "flash_bwd_stock": functools.partial(bench_flash_bwd, impl="stock"),
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--which", default="all")
    ap.add_argument("--iters", type=int, default=None)
    args = ap.parse_args()
    names = list(BENCHES) if args.which == "all" else args.which.split(",")
    for name in names:
        fn = BENCHES[name]
        kw = {"iters": args.iters} if args.iters else {}
        try:
            row = fn(**kw)
        except Exception as e:  # keep going; report the failure as a row
            row = {"metric": name, "error": f"{type(e).__name__}: {e}"[:300]}
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
