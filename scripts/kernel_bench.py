"""On-chip kernel benchmarks: tunnel-immune TFLOP/s for the hot kernels.

Every benchmark jits a ``lax.fori_loop`` of N dependent kernel invocations so
the whole measurement is ONE dispatch — the sandbox tunnel's ~100 ms RTT is
amortized away and the number reflects on-device compute only.  The loop body
perturbs the input with the previous output (``q + o*0``-style chaining would
be folded; we add a tiny carry-dependent epsilon) so XLA cannot CSE the calls.

Reference hook: /root/reference/benchmark.md defines transfer scenarios only;
compute-efficiency benchmarks are the TPU build's own north star (VERDICT r1
next-round #1/#4).

Usage:  python scripts/kernel_bench.py [--iters 8] [--which all|matmul|flash|...]
Emits one JSON line per benchmark row.
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax


def _timeit(fn, *args, iters: int, reps: int = 5, target_s: float = 0.4):
    """Per-call seconds for `fn`'s kernel, tunnel-immune.

    On this sandbox the device sits behind a tunnel with ~70-200 ms dispatch
    RTT *and tens-of-ms jitter between runs*, so (a) a scalar device->host
    read forces synchronization, and (b) the SAME compiled loop is timed at
    two counts and differenced to cancel the constant tunnel/readback cost.

    The difference only means anything when it dwarfs the jitter: the gap
    between the two loop counts is auto-scaled (from a pilot difference)
    until the extra device time is >= `target_s`, and the two runs are timed
    interleaved (hi, lo, hi, lo, ...) so slow drift in tunnel state hits both
    minima equally.  `iters` seeds the pilot gap; the final count is chosen
    here.
    """

    def compile_n(n):
        c = jax.jit(functools.partial(fn, iters=n)).lower(*args).compile()
        float(c(*args))  # warmup (transfer caches, first dispatch)
        return c

    def time_min(c, n=2):
        best = float("inf")
        for _ in range(n):
            t0 = time.perf_counter()
            float(c(*args))
            best = min(best, time.perf_counter() - t0)
        return best

    n_lo = max(iters // 2, 1)
    c_lo = compile_n(n_lo)
    t_lo = time_min(c_lo)

    # Grow the gap until the differenced device time clears target_s.  Each
    # attempt extrapolates a per-iter estimate from the observed difference;
    # a noise-negative difference just multiplies the gap by 8 and retries.
    gap = max(iters - n_lo, 1)
    c_hi = None
    used_gap = gap  # the gap c_hi was actually compiled with
    for _ in range(6):
        used_gap = gap
        c_hi = compile_n(n_lo + used_gap)
        t_hi = time_min(c_hi)
        diff = t_hi - t_lo
        if diff >= target_s or used_gap >= (1 << 17):
            break
        per_iter = diff / used_gap if diff > 0 else 0.0
        if per_iter > 0:
            gap = min(max(int(target_s / per_iter * 1.3) + 1, used_gap * 2),
                      1 << 17)
        else:
            gap = min(used_gap * 8, 1 << 17)

    his, los = [], []
    for _ in range(reps):
        his.append(time_min(c_hi, n=1))
        los.append(time_min(c_lo, n=1))
    dt = (min(his) - min(los)) / used_gap
    if dt <= 0:  # jitter still won; medians are the robust fallback
        import statistics

        dt = (statistics.median(his) - statistics.median(los)) / used_gap
    return max(dt, 1e-9)


def _chain(kernel, q, *rest, iters):
    """fori_loop of `iters` dependent kernel calls; returns a sync scalar."""

    def body(_, carry):
        # carry-dependent zero-ish perturbation defeats CSE without changing
        # the math measurably.
        qq = q + carry[(0,) * carry.ndim].astype(q.dtype) * jnp.asarray(
            1e-30, q.dtype
        )
        return kernel(qq, *rest)

    out0 = kernel(q, *rest)
    out = lax.fori_loop(0, iters - 1, body, out0)
    return out[(0,) * out.ndim].astype(jnp.float32)


def bench_matmul(n: int = 8192, iters: int = 8):
    """bf16 n^3 matmul + tanh — the chip's demonstrated compute ceiling."""
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (n, n), jnp.bfloat16)
    b = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.bfloat16)

    def k(a, b):
        return jnp.tanh(jnp.dot(a, b, preferred_element_type=jnp.float32)).astype(
            jnp.bfloat16
        )

    dt = _timeit(lambda a, b, iters: _chain(k, a, b, iters=iters), a, b, iters=iters)
    tflops = 2 * n**3 / dt / 1e12
    return {"metric": "matmul_ceiling_tflops", "value": round(tflops, 2),
            "unit": "TFLOP/s", "detail": f"bf16 {n}^3, {dt*1e3:.2f} ms/iter"}


def _attn_flops(b, hq, s, d, causal):
    f = 4 * b * hq * s * s * d
    return f // 2 if causal else f


def bench_flash_fwd(b=1, hq=8, hkv=2, s=8192, d=128, causal=True, iters: int = 8,
                    impl="ours"):
    from starway_tpu.ops.pallas_attention import flash_attention

    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (b, hq, s, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, hkv, s, d), jnp.bfloat16)
    v = jax.random.normal(kv, (b, hkv, s, d), jnp.bfloat16)

    if impl == "ours":
        kern = functools.partial(flash_attention, causal=causal)
    elif impl == "stock":
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as stock,
        )

        # Stock kernel wants hq == hkv; expand grouped kv like repeat_kv.
        def kern(q, k, v):
            n_rep = hq // hkv
            ke = jnp.repeat(k, n_rep, axis=1)
            ve = jnp.repeat(v, n_rep, axis=1)
            return stock(q, ke, ve, causal=causal,
                         sm_scale=1.0 / d**0.5)
    else:
        raise ValueError(impl)

    dt = _timeit(lambda q, k, v, iters: _chain(kern, q, k, v, iters=iters),
                 q, k, v, iters=iters)
    tflops = _attn_flops(b, hq, s, d, causal) / dt / 1e12
    return {"metric": f"flash_fwd_{impl}_tflops", "value": round(tflops, 2),
            "unit": "TFLOP/s",
            "detail": f"B={b} Hq={hq} Hkv={hkv} S={s} D={d} causal={causal} "
                      f"bf16, {dt*1e3:.2f} ms/iter"}


def bench_flash_window(b=1, hq=8, hkv=2, s=8192, d=128, window=1024,
                       iters: int = 8):
    """Windowed flash fwd: the DMA band means compute AND bandwidth scale
    with S*window, not S^2 — compare against the causal row to see it."""
    from starway_tpu.ops.pallas_attention import flash_attention

    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (b, hq, s, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, hkv, s, d), jnp.bfloat16)
    v = jax.random.normal(kv, (b, hkv, s, d), jnp.bfloat16)
    kern = functools.partial(flash_attention, causal=True, window=window)
    dt = _timeit(lambda q, k, v, iters: _chain(kern, q, k, v, iters=iters),
                 q, k, v, iters=iters)
    # Useful flops: ~4*b*hq*s*window*d (each query attends ~window keys).
    flops = 4 * b * hq * s * min(window, s) * d
    return {"metric": "flash_window_tflops", "value": round(flops / dt / 1e12, 2),
            "unit": "TFLOP/s",
            "detail": f"B={b} Hq={hq} Hkv={hkv} S={s} D={d} window={window} "
                      f"bf16, {dt*1e3:.2f} ms/iter (banded-useful flops)"}


def bench_flash_bwd(b=1, hq=8, hkv=2, s=8192, d=128, causal=True, iters: int = 4,
                    impl="ours"):
    from starway_tpu.ops.pallas_attention import flash_attention

    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (b, hq, s, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, hkv, s, d), jnp.bfloat16)
    v = jax.random.normal(kv, (b, hkv, s, d), jnp.bfloat16)

    if impl == "ours":
        base = functools.partial(flash_attention, causal=causal)
    elif impl == "stock":
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as stock,
        )

        def base(q, k, v):
            n_rep = hq // hkv
            return stock(q, jnp.repeat(k, n_rep, axis=1),
                         jnp.repeat(v, n_rep, axis=1), causal=causal,
                         sm_scale=1.0 / d**0.5)
    else:
        raise ValueError(impl)

    def kern(q, k, v):
        loss = lambda q, k, v: base(q, k, v).astype(jnp.float32).sum()
        dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        return dq + 0 * dk.sum() + 0 * dv.sum()

    dt = _timeit(lambda q, k, v, iters: _chain(kern, q, k, v, iters=iters),
                 q, k, v, iters=iters)
    # fwd (recomputed) + bwd ≈ 3.5x fwd flops (2 fwd matmuls + 5 bwd matmuls)
    tflops = _attn_flops(b, hq, s, d, causal) * 3.5 / dt / 1e12
    return {"metric": f"flash_fwdbwd_{impl}_tflops", "value": round(tflops, 2),
            "unit": "TFLOP/s",
            "detail": f"B={b} Hq={hq} Hkv={hkv} S={s} D={d} causal={causal} "
                      f"bf16, {dt*1e3:.2f} ms/iter (fwd+bwd)"}


def _decode_inputs(b, hq, hkv, t, d):
    """Shared decode-bench workload: bf16 single query + grouped cache at
    full position, plus the grouped-cache byte count (k + v)."""
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (b, hq, 1, d), jnp.bfloat16)
    kc = jax.random.normal(kk, (b, hkv, t, d), jnp.bfloat16)
    vc = jax.random.normal(kv, (b, hkv, t, d), jnp.bfloat16)
    pos = jnp.asarray(t - 1, jnp.int32)
    return q, kc, vc, pos, 2 * b * hkv * t * d * 2


def bench_decode(b=1, hq=8, hkv=2, t=8192, d=128, iters: int = 64, impl="ours"):
    """Cached single-token decode attention: us/token + effective HBM GB/s
    (decode is bandwidth-bound: the kernel's job is streaming the grouped
    cache exactly once).  ``impl="int8"``: the quantized-cache path — half
    the bytes stream, dequant folded into the kernel (ops/quantize.py)."""
    from starway_tpu.models.generate import _attend_cached

    q, kc, vc, pos, cache_bytes = _decode_inputs(b, hq, hkv, t, d)

    if impl == "int8":
        from starway_tpu.ops.pallas_decode import decode_attention
        from starway_tpu.ops.quantize import quantize_kv

        kc, ks = quantize_kv(kc)
        vc, vs = quantize_kv(vc)
        # int8 cache + f32 scales: (1 + 4/D) bytes per former bf16 2 bytes.
        cache_bytes = cache_bytes // 2 + 2 * b * hkv * t * 4

        def kern(q, kc, vc):
            return decode_attention(q, kc, vc, pos, k_scale=ks, v_scale=vs)
    else:
        use_pallas = impl == "ours"

        def kern(q, kc, vc):
            return _attend_cached(q, kc, vc, pos, hq // hkv,
                                  use_pallas=use_pallas)

    dt = _timeit(lambda q, kc, vc, iters: _chain(kern, q, kc, vc, iters=iters),
                 q, kc, vc, iters=iters)
    return {"metric": f"decode_{impl}_us_per_token", "value": round(dt * 1e6, 2),
            "unit": "us",
            "detail": f"B={b} Hq={hq} Hkv={hkv} T={t} D={d} "
                      f"{'int8 cache' if impl == 'int8' else 'bf16'}, "
                      f"streamed bytes {cache_bytes / 1e6:.1f} MB -> "
                      f"{cache_bytes / dt / 1e9:.0f} GB/s effective"}


V5E_PEAK = 197e12  # v5e bf16 peak FLOP/s


def _train_mfu_row(metric: str, cfg_kw: dict, B: int, S: int, iters: int,
                   compile_only: bool = False):
    """Train-step MFU on one chip: model flops from config, time from an
    on-device fori_loop of full optimizer steps.

    ``compile_only``: AOT-lower + compile the EXACT config/shapes from
    ShapeDtypeStructs and report the compile seconds instead of timing —
    the chip-independent rehearsal half of the row (VERDICT r4 #1/#3: a
    shape bug must die here, on CPU, not in the one live tunnel window)."""
    import numpy as np
    import optax

    from starway_tpu.models import LlamaConfig, init_params, make_train_step

    cfg = LlamaConfig.preset("debug", **cfg_kw)
    tx = optax.adamw(1e-3)
    step = make_train_step(cfg, tx)

    def loop(params, opt, batch, iters):
        def body(_, carry):
            p, o = carry
            p, o, loss = step(p, o, batch)
            return (p, o)

        p, o = lax.fori_loop(0, iters, body, (params, opt))
        return jax.tree_util.tree_leaves(p)[0][(0, 0)].astype(jnp.float32)

    if compile_only:
        p_avals = jax.eval_shape(
            lambda: init_params(jax.random.PRNGKey(0), cfg))
        o_avals = jax.eval_shape(
            lambda: tx.init(init_params(jax.random.PRNGKey(0), cfg)))
        b_aval = jax.ShapeDtypeStruct((B, S + 1), jnp.int32)
        t0 = time.perf_counter()
        jax.jit(functools.partial(loop, iters=iters)).lower(
            p_avals, o_avals, b_aval).compile()
        # The CPU compile above traces the blockwise-attention branch
        # (default_attn keys off the backend), so it cannot catch a
        # mosaic tiling bug at the row's real geometry.  Cross-lower the
        # SAME config for the TPU platform with the flash kernel forced,
        # which runs the full mosaic kernel pipeline host-side.
        from starway_tpu.ops.pallas_attention import flash_attention

        def _flash_attn(q, k, v):
            return flash_attention(q, k, v, causal=True, interpret=False)

        step_tpu = make_train_step(cfg, tx, _flash_attn)

        def loop_tpu(params, opt, batch, iters):
            def body(_, carry):
                p, o = carry
                p, o, loss = step_tpu(p, o, batch)
                return (p, o)

            p, o = lax.fori_loop(0, iters, body, (params, opt))
            return jax.tree_util.tree_leaves(p)[0][(0, 0)].astype(
                jnp.float32)

        n_kernels = (jax.jit(functools.partial(loop_tpu, iters=iters))
                     .trace(p_avals, o_avals, b_aval)
                     .lower(lowering_platforms=("tpu",))
                     .as_text().count("tpu_custom_call"))
        dt = time.perf_counter() - t0
        return {"metric": f"{metric}_rehearsal_compile",
                "value": round(dt, 1), "unit": "s",
                "detail": f"AOT compile of the exact row config "
                          f"(B={B} S={S} {cfg.n_layers}L d{cfg.d_model} "
                          f"remat={cfg.remat}/{cfg.remat_policy}) on "
                          f"{jax.default_backend()} + TPU cross-lowering "
                          f"with the flash kernel "
                          f"({n_kernels} pallas call sites)"}

    params = init_params(jax.random.PRNGKey(0), cfg)
    opt = tx.init(params)
    batch = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (B, S + 1), dtype=np.int32))

    dt = _timeit(loop, params, opt, batch, iters=iters)

    # 6ND counts matmul flops only: the embedding table is a gather/scatter,
    # not a matmul, so it is excluded (lm_head is a real matmul and stays).
    n_params = sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(params))
    n_matmul = n_params - params["embed"].size
    tokens = B * S
    # 6ND for fwd+bwd matmul flops + attention term (12 * L * H * S^2 * Dh,
    # halved for causality).
    attn = 6 * cfg.n_layers * cfg.n_heads * S * S * cfg.head_dim * B
    flops = 6 * n_matmul * tokens + attn
    tflops = flops / dt / 1e12
    return {"metric": metric, "value": round(tflops / (V5E_PEAK / 1e12), 4),
            "unit": "frac_of_197T",
            "detail": f"{tflops:.1f} TFLOP/s, {n_params/1e6:.1f}M params "
                      f"({n_matmul/1e6:.1f}M matmul), "
                      f"B={B} S={S} remat={cfg.remat}, {dt*1e3:.1f} ms/step"}


def bench_decode_paged(b=1, hq=8, hkv=2, t=8192, d=128, page=512,
                       iters: int = 64):
    """Paged vs dense decode at the headline shape: the page-table
    indirection must cost ~nothing (same bytes, same stream structure —
    ops/pallas_paged.py) while buying pool-granularity memory.  Emits the
    paged us/token row; compare against the adjacent decode_ours row."""
    import numpy as np

    from starway_tpu.ops.pallas_paged import paged_decode_attention

    rng = np.random.default_rng(0)
    max_pages = t // page
    n_pages = b * max_pages + 1
    kp = jnp.asarray(rng.standard_normal((n_pages, hkv, page, d)),
                     jnp.bfloat16)
    vp = jnp.asarray(rng.standard_normal((n_pages, hkv, page, d)),
                     jnp.bfloat16)
    table = jnp.asarray(
        rng.permutation(np.arange(1, n_pages))[:b * max_pages].reshape(
            b, max_pages), jnp.int32)
    q = jnp.asarray(rng.standard_normal((b, hq, 1, d)), jnp.bfloat16)
    pos = jnp.full((b,), t - 1, jnp.int32)
    cache_bytes = 2 * b * hkv * t * d * kp.dtype.itemsize

    def kern(q, kp, vp):
        return paged_decode_attention(q, kp, vp, table, pos)

    dt = _timeit(lambda q, kp, vp, iters: _chain(kern, q, kp, vp,
                                                 iters=iters),
                 q, kp, vp, iters=iters)
    return {"metric": "decode_paged_us_per_token",
            "value": round(dt * 1e6, 2), "unit": "us",
            "detail": f"B={b} Hq={hq} Hkv={hkv} T={t} page={page} bf16 "
                      f"scrambled tables, streamed {cache_bytes / 1e6:.1f} "
                      f"MB -> {cache_bytes / dt / 1e9:.0f} GB/s effective "
                      f"(compare decode_ours_us_per_token)"}


def bench_decode_shapes(iters: int = 64, shapes=None):
    """Ours-vs-lax decode at the VERDICT r2 acceptance shapes: besides the
    headline (B=1, Hkv=2, T=8192 — measured by the adjacent
    ``decode``/``decode_lax`` rows, not repeated here), the kernel must
    also beat the lax path at three more (B, Hkv, T) points.  Emits one
    ours/lax pair per shape plus a summary row counting wins."""
    if shapes is None:
        shapes = [  # (B, Hq, Hkv, T)
            (8, 8, 2, 4096),   # serving batch
            (1, 32, 8, 8192),  # more kv heads (smaller GQA ratio)
            (4, 8, 1, 16384),  # long cache, extreme grouping
        ]
    wins = 0
    for b, hq, hkv, t in shapes:
        pair = {}
        for impl in ("ours", "lax"):
            row = bench_decode(b=b, hq=hq, hkv=hkv, t=t, iters=iters,
                               impl=impl)
            row["metric"] = f"decode_{impl}_b{b}_hkv{hkv}_t{t}_us"
            pair[impl] = row["value"]
            print(json.dumps(row), flush=True)
        if pair["ours"] < pair["lax"]:
            wins += 1
    return {"metric": "decode_shape_wins", "value": wins,
            "unit": f"of_{len(shapes)}",
            "detail": "shapes (B,Hq,Hkv,T): " + "; ".join(
                f"({b},{hq},{hkv},{t})" for b, hq, hkv, t in shapes)}


def bench_train_mfu(iters: int = 4, B: int = 8, S: int = 1024,
                    compile_only: bool = False):
    """Tiny-Llama MFU (the r2 row; kept for continuity of the table)."""
    return _train_mfu_row(
        "train_step_mfu",
        dict(d_model=512, n_layers=4, n_heads=8, n_kv_heads=8, d_ff=1536,
             vocab_size=8192, dtype="bfloat16"),
        B=B, S=S, iters=iters, compile_only=compile_only)


def bench_train_mfu_large(iters: int = 2, compile_only: bool = False):
    """Model-scale MFU (VERDICT r2 next #3): a 672M-param GQA Llama at
    S=8192 with remat + the pallas flash kernel, as large as one v5e-1
    comfortably fits with the fori_loop's undonated params+opt carries
    (~4 GB weights+moments live twice during timing, plus the [B, S, V]
    f32 logits in the loss).  Target >= 0.40 of the 197T peak; the toy
    train_step_mfu row stays for drift comparison."""
    return _train_mfu_row(
        "train_step_mfu_large",
        dict(d_model=2048, n_layers=12, n_heads=16, n_kv_heads=4,
             d_ff=5632, vocab_size=32000, dtype="bfloat16", remat=True,
             # Chunked "dots" remat (llama.py:decoder_layer): backward
             # replays only norms/rope/silu — no matmul recompute, no
             # flash-forward re-run (pinned chip-independently by
             # tests/test_remat_policy.py), so the 6ND MFU isn't capped
             # at ~0.75x like full-layer remat.
             remat_policy="dots"),
        B=1, S=8192, iters=iters, compile_only=compile_only)


def check_numerics():
    """On-chip numerics: pin the pallas kernels against the lax oracles on
    the REAL backend (the pytest suite pins them in CPU interpret mode; this
    is the hardware half of that contract -- VERDICT r1 #8)."""
    from starway_tpu.models.generate import _attend_cached
    from starway_tpu.ops.attention import attention_reference, repeat_kv
    from starway_tpu.ops.pallas_attention import flash_attention

    b, hq, hkv, s, d = 1, 8, 2, 512, 128
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(kq, (b, hq, s, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, hkv, s, d), jnp.bfloat16)
    v = jax.random.normal(kv, (b, hkv, s, d), jnp.bfloat16)
    rows = []

    def rel_err(a, r):
        a = a.astype(jnp.float32)
        r = r.astype(jnp.float32)
        return float(jnp.max(jnp.abs(a - r)) / (jnp.max(jnp.abs(r)) + 1e-9))

    ref = attention_reference(q.astype(jnp.float32),
                              repeat_kv(k, hq // hkv).astype(jnp.float32),
                              repeat_kv(v, hq // hkv).astype(jnp.float32),
                              causal=True)
    err = rel_err(flash_attention(q, k, v, causal=True), ref)
    rows.append({"metric": "check_flash_fwd_onchip", "value": err,
                 "unit": "max_rel_err", "ok": bool(err < 2e-2)})

    def loss(fn):
        return lambda q, k, v: fn(q, k, v).astype(jnp.float32).sum()

    g_ours = jax.grad(loss(functools.partial(flash_attention, causal=True)),
                      argnums=(0, 1, 2))(q, k, v)
    oracle = lambda q, k, v: attention_reference(
        q, repeat_kv(k, hq // hkv), repeat_kv(v, hq // hkv), causal=True)
    g_ref = jax.grad(loss(oracle), argnums=(0, 1, 2))(q, k, v)
    # Relative: dk/dv accumulate over S rows, so bf16 noise scales with the
    # magnitude (measured ~0.8% at S=1024 on-chip).
    gerr = max(rel_err(a, r) for a, r in zip(g_ours, g_ref))
    rows.append({"metric": "check_flash_bwd_onchip", "value": gerr,
                 "unit": "max_rel_err", "ok": bool(gerr < 2e-2)})

    t = 1024
    qd = jax.random.normal(kq, (b, hq, 1, d), jnp.bfloat16)
    kc = jax.random.normal(kk, (b, hkv, t, d), jnp.bfloat16)
    vc = jax.random.normal(kv, (b, hkv, t, d), jnp.bfloat16)
    pos = jnp.asarray(t // 2, jnp.int32)
    dk = _attend_cached(qd, kc, vc, pos, hq // hkv, use_pallas=True)
    dr = _attend_cached(qd, kc, vc, pos, hq // hkv, use_pallas=False)
    derr = float(jnp.max(jnp.abs(dk.astype(jnp.float32) - dr.astype(jnp.float32))))
    rows.append({"metric": "check_decode_onchip", "value": derr,
                 "unit": "max_abs_err", "ok": bool(derr < 2e-2)})

    # Windowed kernels (VERDICT r2 weak #7: the suite pins these in CPU
    # interpret mode; this is the hardware half).  Window straddles block
    # boundaries on purpose.
    win = 192
    wref = attention_reference(q.astype(jnp.float32),
                               repeat_kv(k, hq // hkv).astype(jnp.float32),
                               repeat_kv(v, hq // hkv).astype(jnp.float32),
                               causal=True, window=win)
    werr = rel_err(flash_attention(q, k, v, causal=True, window=win), wref)
    rows.append({"metric": "check_flash_window_fwd_onchip", "value": werr,
                 "unit": "max_rel_err", "ok": bool(werr < 2e-2)})

    gw_ours = jax.grad(
        loss(functools.partial(flash_attention, causal=True, window=win)),
        argnums=(0, 1, 2))(q, k, v)
    w_oracle = lambda q, k, v: attention_reference(
        q, repeat_kv(k, hq // hkv), repeat_kv(v, hq // hkv), causal=True,
        window=win)
    gw_ref = jax.grad(loss(w_oracle), argnums=(0, 1, 2))(q, k, v)
    gwerr = max(rel_err(a, r) for a, r in zip(gw_ours, gw_ref))
    rows.append({"metric": "check_flash_window_bwd_onchip", "value": gwerr,
                 "unit": "max_rel_err", "ok": bool(gwerr < 2e-2)})

    dwk = _attend_cached(qd, kc, vc, pos, hq // hkv, use_pallas=True,
                         window=win)
    dwr = _attend_cached(qd, kc, vc, pos, hq // hkv, use_pallas=False,
                         window=win)
    dwerr = float(jnp.max(jnp.abs(dwk.astype(jnp.float32)
                                  - dwr.astype(jnp.float32))))
    rows.append({"metric": "check_decode_window_onchip", "value": dwerr,
                 "unit": "max_abs_err", "ok": bool(dwerr < 2e-2)})

    # Round-3 kernel paths: int8 cache (dequant folded into the stream)
    # and multi-query decode (the speculative chunk verify).
    from starway_tpu.ops.quantize import quantize_kv

    kc8, ks = quantize_kv(kc)
    vc8, vs = quantize_kv(vc)
    q8k = _attend_cached(qd, kc8, vc8, pos, hq // hkv, use_pallas=True,
                         k_scale=ks, v_scale=vs)
    q8r = _attend_cached(qd, kc8, vc8, pos, hq // hkv, use_pallas=False,
                         k_scale=ks, v_scale=vs)
    q8err = float(jnp.max(jnp.abs(q8k.astype(jnp.float32)
                                  - q8r.astype(jnp.float32))))
    rows.append({"metric": "check_decode_int8_onchip", "value": q8err,
                 "unit": "max_abs_err", "ok": bool(q8err < 2e-2)})

    C = 5
    qc = jax.random.normal(kq, (b, hq, C, d), jnp.bfloat16)
    posv = jnp.asarray([t // 2 - 3], jnp.int32)  # chunk straddles blocks
    mqk = _attend_cached(qc, kc, vc, posv, hq // hkv, use_pallas=True)
    mqr = _attend_cached(qc, kc, vc, posv, hq // hkv, use_pallas=False)
    mqerr = float(jnp.max(jnp.abs(mqk.astype(jnp.float32)
                                  - mqr.astype(jnp.float32))))
    rows.append({"metric": "check_decode_multiquery_onchip", "value": mqerr,
                 "unit": "max_abs_err", "ok": bool(mqerr < 2e-2)})

    # Speculative chunk verify vs stepwise decode ON HARDWARE (ADVICE r3):
    # the two compute the same logits through different summation orders,
    # which is exactly what lets bf16 argmax near-ties diverge.  Pin the
    # LOGITS teacher-forced (same token sequence through both paths) — an
    # end-to-end greedy-output comparison would cascade from a single
    # benign near-tie and flap; the logit gap is the claim itself.
    from starway_tpu.models import LlamaConfig, init_params
    from starway_tpu.models.generate import decode_step, init_cache
    from starway_tpu.models.llama import rope_tables
    from starway_tpu.models.speculative import chunk_decode_step

    # bfloat16 override: the debug preset is f32 (where summation order is
    # invisible at 1e-7); the claim under test is about the bf16 decode
    # dtype real configs run in.
    cfg = LlamaConfig.preset("debug", dtype="bfloat16")
    p = init_params(jax.random.PRNGKey(0), cfg)
    B, warm, C, T = 4, 8, 6, 32
    toks = jax.random.randint(jax.random.PRNGKey(3), (B, warm + C), 1,
                              cfg.vocab_size, jnp.int32)
    rope = rope_tables(T, cfg.head_dim, cfg.rope_theta)
    c_step = init_cache(cfg, B, T)
    c_chunk = c_step
    step_logits = []
    for i in range(warm + C):
        l, c_step = decode_step(p, c_step, toks[:, i], i, cfg, rope)
        if i >= warm:
            step_logits.append(l)
        if i == warm - 1:
            # Warm the chunk path's cache identically through the prefix
            # (jax arrays are immutable; later steps rebind, not mutate).
            c_chunk = c_step
    chunk_logits, _ = chunk_decode_step(
        p, c_chunk, toks[:, warm:], jnp.full((B,), warm, jnp.int32), cfg,
        rope)
    serr = rel_err(chunk_logits, jnp.stack(step_logits, axis=1))
    rows.append({"metric": "check_spec_chunk_onchip", "value": serr,
                 "unit": "max_rel_err", "ok": bool(serr < 2e-2)})
    return rows


def bench_decode_tune(b=1, hq=8, hkv=2, t=8192, d=128, iters: int = 64):
    """Sweep the STREAM decode kernel's block_k on-chip (plus two grid
    sentinel points for drift); emits one row per (variant, block) and a
    summary row with the winner.  The r2
    re-measurement showed the grid kernel's 128 default losing to the lax
    path (BASELINE.md): ~0.4 us fixed cost x 64 grid cells.  The stream
    variant (r3) removes the per-block cell cost entirely — b*hkv cells,
    double-buffered manual DMA — so its block size only tunes DMA
    granularity vs VMEM footprint."""
    from starway_tpu.ops.pallas_decode import decode_attention

    q, kc, vc, pos, cache_bytes = _decode_inputs(b, hq, hkv, t, d)

    candidates = [bk for bk in (128, 256, 512, 1024, 2048) if bk <= t]
    if not candidates:
        raise ValueError(f"t={t} is smaller than every candidate block size")
    # The grid variant already lost to stream at its best setting (r3,
    # BASELINE.md); keep two sentinel points for drift instead of a full
    # sweep so the row fits its queue slot on a slow tunnel (r3's sweep
    # hit the 2400 s row timeout mid-run).
    grid_candidates = [bk for bk in (128, 512) if bk <= t]
    best = None
    for stream in (True, False):
        variant = "stream" if stream else "grid"
        for bk in (candidates if stream else grid_candidates):
            kern = functools.partial(decode_attention, block_k=bk,
                                     stream=stream)

            def run(q, kc, vc, iters, _kern=kern):
                return _chain(lambda q, kc, vc: _kern(q, kc, vc, pos),
                              q, kc, vc, iters=iters)

            dt = _timeit(run, q, kc, vc, iters=iters)
            print(json.dumps(
                {"metric": f"decode_{variant}_block{bk}_us",
                 "value": round(dt * 1e6, 2), "unit": "us",
                 "detail": f"{cache_bytes / dt / 1e9:.0f} GB/s effective"}),
                flush=True)
            if best is None or dt < best[2]:
                best = (variant, bk, dt)
    return {"metric": "decode_best_config", "value": best[1],
            "unit": "block_k", "variant": best[0],
            "detail": f"{best[2] * 1e6:.2f} us with {best[0]} kernel at "
                      f"block_k={best[1]} "
                      f"({cache_bytes / best[2] / 1e9:.0f} GB/s)"}


def bench_serve(batch=1, model="llama", ragged=False, prompt_len=512,
                m_lo=32, m_hi=1056, reps=4, iters=None, kv_quant="none",
                weights="none"):
    """End-to-end serving throughput: tokens/s for the REAL ``generate()``
    surface (flash prefill + cached decode scan + top-k/top-p sampling; the
    Mistral variant decodes through the O(window) rolling cache).

    The whole generation is one dispatch, so timing the same workload at
    two ``max_new`` counts and differencing cancels the tunnel RTT, the
    prefill, and the host/dispatch overhead — the headline is pure
    per-decode-token device time.  The lo-run wall clock is kept in the
    detail so the overhead share (prefill + dispatch + host) stays visible
    next to the kernel-level us/token rows (VERDICT r2 next #4; metric
    discipline per /root/reference/benchmark.md:63-77).

    ``iters`` is accepted for CLI uniformity and ignored (the decode scan
    length IS the iteration count).
    """
    import numpy as np

    from starway_tpu.models import LlamaConfig, init_params
    from starway_tpu.models.generate import generate

    kw = dict(d_model=1024, n_layers=8, n_heads=8, n_kv_heads=2, d_ff=2816,
              vocab_size=32000, dtype="bfloat16", kv_quant=kv_quant)
    if model == "mistral":
        # Window < max_len: the aligned path decodes through the rolling
        # O(window) cache (bit-identical to full-cache, pinned by tests).
        kw["sliding_window"] = prompt_len
    elif model == "mixtral":
        # Dropless top-2 SwiGLU MoE (the Mixtral conversion shape): the
        # per-token weight stream is the experts', so MoE decode tok/s is
        # its own bandwidth regime.
        kw.update(n_experts=8, moe_top_k=2, moe_swiglu=True,
                  moe_capacity_factor=8.0, d_ff=1408)
    cfg = LlamaConfig.preset("debug", **kw)
    params = init_params(jax.random.PRNGKey(0), cfg)
    if weights == "int8":
        from starway_tpu.ops.quantize import quantize_params

        params = quantize_params(params)
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(
        rng.integers(1, cfg.vocab_size, (batch, prompt_len), dtype=np.int32))
    lengths = None
    if ragged:
        # Mixed prompt sizes in one right-padded batch: the ragged path's
        # per-row cursors are the serving-realistic decode shape.
        lengths = jnp.asarray(
            rng.integers(prompt_len // 4, prompt_len + 1, batch,
                         dtype=np.int32))
    key = jax.random.PRNGKey(1)

    def run(m, max_len):
        out = generate(params, cfg, prompt, m, temperature=0.8, top_k=64,
                       top_p=0.9, key=key, max_len=max_len,
                       prompt_lengths=lengths)
        jax.block_until_ready(out)

    name = (f"serve_{model}{'_ragged' if ragged else ''}"
            f"{'_int8' if kv_quant == 'int8' else ''}"
            f"{'_w8' if weights == 'int8' else ''}_b{batch}")
    # Jitter guard (same concern _timeit documents: tens-of-ms tunnel
    # jitter): grow the hi/lo gap until the differenced time comfortably
    # clears it, and REFUSE to report a number when it never does — a
    # clamped near-zero difference would print an absurd tok/s headline
    # that reads like a measurement.
    gap = m_hi - m_lo
    diff = float("-inf")
    for _ in range(3):
        m_hi_eff = m_lo + gap
        max_len = prompt_len + m_hi_eff
        run(m_lo, max_len)  # compile both signatures before timing
        run(m_hi_eff, max_len)
        t_lo = t_hi = float("inf")
        for _ in range(reps):  # interleaved minima, like _timeit
            t0 = time.perf_counter()
            run(m_hi_eff, max_len)
            t_hi = min(t_hi, time.perf_counter() - t0)
            t0 = time.perf_counter()
            run(m_lo, max_len)
            t_lo = min(t_lo, time.perf_counter() - t0)
        diff = t_hi - t_lo
        if diff >= 0.2 or gap >= 4096:
            break
        gap = min(gap * 4, 4096)
    if diff < 0.2:
        # Below the confidence threshold even at the gap cap: a
        # jitter-level difference would print an absurd tok/s headline
        # that reads like a measurement — refuse instead.
        return {"metric": f"{name}_tokens_per_s",
                "error": f"jitter swamped the differenced timing "
                         f"(diff={diff * 1e3:.1f} ms < 200 ms at gap={gap} "
                         f"tokens); rerun on a quieter link"}
    dt_tok = diff / gap  # s per decode step
    tok_s = batch / dt_tok
    wall_tok_s = batch * m_lo / t_lo
    overhead_ms = (t_lo - m_lo * dt_tok) * 1e3  # prefill + dispatch + host
    return {"metric": f"{name}_tokens_per_s", "value": round(tok_s, 1),
            "unit": "tok/s",
            "detail": f"{dt_tok * 1e6 / batch:.1f} us/token device-only, "
                      f"wall {wall_tok_s:.1f} tok/s at max_new={m_lo} "
                      f"(P={prompt_len}, overhead {overhead_ms:.1f} ms/call "
                      f"= prefill+dispatch+host), sampling top_k=64 "
                      f"top_p=0.9, {cfg.n_layers}L d{cfg.d_model} GQA "
                      f"{cfg.n_heads}/{cfg.n_kv_heads} "
                      f"{'W8' if weights == 'int8' else 'bf16'}"
                      f"{'+KV8' if kv_quant == 'int8' else ''}"}


def bench_gemv_int8(m=1, d=4096, f=14336, iters: int = 32):
    """W8A16 weight-stream bandwidth: x [m, d] @ int8 W [d, f] (pallas
    gemv, scale folded post-matmul) vs the same matmul on bf16 weights —
    small-batch decode is weight-bound, so the int8 stream's ceiling is
    ~2x.  Shape defaults to a Llama-8B MLP projection."""
    from starway_tpu.ops.pallas_gemv import int8_matmul
    from starway_tpu.ops.quantize import quantize_weight

    kx, kw = jax.random.split(jax.random.PRNGKey(0), 2)
    x = jax.random.normal(kx, (m, d), jnp.bfloat16)
    w = jax.random.normal(kw, (d, f), jnp.bfloat16)
    qw = quantize_weight(w)
    wq, s = qw["q"], qw["s"]

    def k_int8(x, wq, s):
        return int8_matmul(x, wq, s)

    def k_bf16(x, w):
        return jnp.dot(x, w, preferred_element_type=jnp.float32).astype(
            jnp.bfloat16)

    dt_q = _timeit(lambda x, wq, s, iters: _chain(k_int8, x, wq, s,
                                                  iters=iters),
                   x, wq, s, iters=iters)
    dt_b = _timeit(lambda x, w, iters: _chain(k_bf16, x, w, iters=iters),
                   x, w, iters=iters)
    by_q, by_b = d * f, 2 * d * f
    return {"metric": "gemv_int8_speedup", "value": round(dt_b / dt_q, 2),
            "unit": "x_vs_bf16",
            "detail": f"m={m} d={d} f={f}: int8 {dt_q * 1e6:.1f} us "
                      f"({by_q / dt_q / 1e9:.0f} GB/s) vs bf16 "
                      f"{dt_b * 1e6:.1f} us ({by_b / dt_b / 1e9:.0f} GB/s)"}


def bench_spec_verify(gamma=8, t=4096, iters: int = 16):
    """The mechanical core of speculative decoding's speedup: one
    ``gamma``-wide chunk verify (models/speculative.py:chunk_decode_step)
    vs ``gamma`` sequential decode steps on the same serve-shaped model.
    Both stream the same cache bytes; the chunk does it ONCE — the row's
    ratio is the per-macro-step amortisation an accepting draft realises
    (end-to-end speedup = this ratio discounted by the acceptance rate
    and the draft's own cost, which are model-quality-dependent and so
    not benchmarkable with random weights)."""
    import numpy as np

    from starway_tpu.models import LlamaConfig, chunk_decode_step, init_params
    from starway_tpu.models.generate import decode_step, init_cache
    from starway_tpu.models.llama import rope_tables

    cfg = LlamaConfig.preset(
        "debug", d_model=1024, n_layers=8, n_heads=8, n_kv_heads=2,
        d_ff=2816, vocab_size=32000, dtype="bfloat16")
    params = init_params(jax.random.PRNGKey(0), cfg)
    cache = init_cache(cfg, 1, t)
    rope = rope_tables(t, cfg.head_dim, cfg.rope_theta)
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(1, cfg.vocab_size, (1, gamma),
                                    dtype=np.int32))
    pos = jnp.asarray(t - gamma - 1, jnp.int32)

    # _chain's carry-epsilon trick is float-only (an int epsilon is 0 and
    # XLA would hoist the loop body); chain through the TOKENS instead —
    # each iteration's argmax feeds the next iteration's input.  params and
    # cache are jit ARGUMENTS (a closure would embed ~200 MB of constants
    # into the program).
    def chunk_loop(params, cache, toks, iters):
        def body(_, tk):
            logits, _cache = chunk_decode_step(params, cache, tk, pos, cfg,
                                               rope)
            return jnp.argmax(logits, -1).astype(jnp.int32)  # [1, gamma]

        out = lax.fori_loop(0, iters, body, toks)
        return out[0, 0].astype(jnp.float32)

    def steps_loop(params, cache, toks, iters):
        def body(_, tk):
            def inner(j, carry):
                tok, c = carry
                logits, c = decode_step(params, c, tok, pos + j, cfg, rope)
                return jnp.argmax(logits, -1).astype(jnp.int32), c

            tok, _c = lax.fori_loop(0, gamma, inner, (tk[:, 0], cache))
            return jnp.tile(tok[:, None], (1, gamma))

        out = lax.fori_loop(0, iters, body, toks)
        return out[0, 0].astype(jnp.float32)

    dt_c = _timeit(chunk_loop, params, cache, toks, iters=iters)
    dt_s = _timeit(steps_loop, params, cache, toks, iters=iters)
    return {"metric": "spec_verify_amortisation", "value": round(dt_s / dt_c, 2),
            "unit": f"x_per_{gamma}tok",
            "detail": f"chunk verify {dt_c * 1e6:.0f} us vs {gamma} decode "
                      f"steps {dt_s * 1e6:.0f} us (T={t}, 8L d1024 GQA 8/2 "
                      f"bf16); end-to-end speedup = this x acceptance rate "
                      f"- draft cost"}


def bench_serve_prefix(prompt_len=480, suffix_len=32, iters=8):
    """Prefix-caching admission speedup: full prefill of (prefix+suffix)
    vs suffix-only chunk ingest against a cached prefix (SlotServer's
    register_prefix/submit(prefix=) path, measured at the compiled-program
    level).  Flops fall from O((P+S) * model) + O((P+S)^2) attention to
    O(S * model) + O(S * (P+S)) — the whole point of the feature; this
    row makes the claim a number."""
    import numpy as np

    from starway_tpu.models import LlamaConfig, init_params
    from starway_tpu.models.generate import prefill
    from starway_tpu.models.llama import cfg_rope_tables
    from starway_tpu.models.speculative import chunk_decode_step

    cfg = LlamaConfig.preset(
        "debug", d_model=1024, n_layers=8, n_heads=8, n_kv_heads=2,
        d_ff=2816, vocab_size=32000, dtype="bfloat16")
    params = init_params(jax.random.PRNGKey(0), cfg)
    P, S = prompt_len, suffix_len
    T = P + S
    rng = np.random.default_rng(0)
    full = jnp.asarray(rng.integers(1, cfg.vocab_size, (1, T),
                                    dtype=np.int32))
    suffix = full[:, P:]
    rope = cfg_rope_tables(cfg, T)
    # The cached prefix: built once, outside the timed region (that is
    # the feature's premise — it amortises over every prefixed request).
    _, pre_cache = prefill(params, cfg, full[:, :P], T)

    def k_full(fn_norm):
        p2 = {**params, "final_norm": fn_norm}
        logits, _ = prefill(params=p2, cfg=cfg, prompt=full, max_len=T,
                            logit_positions=jnp.asarray([T - 1]))
        return logits

    def k_prefix(fn_norm):
        p2 = {**params, "final_norm": fn_norm}
        logits, _ = chunk_decode_step(p2, pre_cache, suffix,
                                      jnp.full((1,), P, jnp.int32), cfg,
                                      rope)
        return logits[:, -1]

    dt_full = _timeit(
        lambda fn, iters: _chain(k_full, fn, iters=iters),
        params["final_norm"], iters=iters)
    dt_pre = _timeit(
        lambda fn, iters: _chain(k_prefix, fn, iters=iters),
        params["final_norm"], iters=iters * 4)
    return {"metric": "serve_prefix_admit_speedup",
            "value": round(dt_full / dt_pre, 2), "unit": "x",
            "detail": f"P={P} S={S}: full prefill {dt_full*1e3:.2f} ms vs "
                      f"suffix ingest {dt_pre*1e3:.2f} ms"}


def bench_serve_continuous(n_slots=8, chunk=16, n_requests=32,
                           prompt_len=192, max_new=96, iters=None):
    """Aggregate tokens/s of the continuous-batching SlotServer under a
    request stream (models/serving.py).  Unlike the differenced serve
    rows, this is WALL-CLOCK end to end — per-chunk dispatch and host
    scheduling are part of the product being measured (bigger ``chunk``
    amortises the tunnel RTT; the detail records the configuration so the
    number is interpretable).  ``iters`` accepted for CLI uniformity and
    ignored."""
    import numpy as np

    from starway_tpu.models import LlamaConfig, SlotServer, init_params

    cfg = LlamaConfig.preset(
        "debug", d_model=1024, n_layers=8, n_heads=8, n_kv_heads=2,
        d_ff=2816, vocab_size=32000, dtype="bfloat16")
    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    max_len = prompt_len + max_new + 8

    def workload(srv, n):
        rids = [srv.submit(
            list(rng.integers(1, cfg.vocab_size, prompt_len)), max_new)
            for _ in range(n)]
        done = srv.run()
        return sum(len(done[r]) for r in rids)

    def fresh():
        return SlotServer(params, cfg, n_slots=n_slots, max_len=max_len,
                          chunk=chunk, temperature=0.8, top_k=64, seed=1)

    workload(fresh(), max(2, n_slots // 2))  # compile admit + chunk programs
    srv = fresh()
    t0 = time.perf_counter()
    total = workload(srv, n_requests)
    dt = time.perf_counter() - t0
    return {"metric": "serve_continuous_tokens_per_s",
            "value": round(total / dt, 1), "unit": "tok/s",
            "detail": f"{n_requests} reqs (P={prompt_len} N={max_new}) "
                      f"through {n_slots} slots, chunk={chunk}, sampled "
                      f"top_k=64, {total} tokens in {dt:.2f}s wall "
                      f"(dispatch+host included), 8L d1024 GQA 8/2 bf16"}


# Scaled-down kwargs per bench for STARWAY_BENCH_REHEARSAL=1 (VERDICT r4
# #3): every queue row's exact command path runs on CPU with a budget that
# finishes in seconds-to-minutes, so a shape/API bug dies here instead of
# zeroing a live tunnel window (decode_tune burned the only window of
# rounds 3-4 with rc=124).  Only SIZES shrink — identity-defining kwargs
# (batch, model, kv_quant, ragged) come from the BENCHES entry unchanged.
# train_mfu_large instead AOT-compiles its EXACT config (compile_only).
_REHEARSAL_SERVE = dict(prompt_len=64, m_lo=8, m_hi=24, reps=2)
REHEARSAL_KW = {
    "matmul": dict(n=256, iters=2),
    "flash": dict(s=256, iters=2),
    "flash_stock": dict(s=256, iters=2),
    "flash_window": dict(s=512, window=128, iters=2),
    "flash_bwd": dict(s=256, iters=2),
    "flash_bwd_stock": dict(s=256, iters=2),
    "decode": dict(t=512, iters=2),
    "decode_lax": dict(t=512, iters=2),
    "decode_int8": dict(t=512, iters=2),
    "decode_tune": dict(t=512, iters=2),
    "decode_paged": dict(t=512, page=128, iters=2),
    "decode_shapes": dict(
        iters=2, shapes=[(2, 8, 2, 256), (1, 8, 4, 256), (2, 8, 1, 512)]),
    "train_mfu": dict(iters=2, B=2, S=128),
    "train_mfu_large": dict(compile_only=True),
    "serve": _REHEARSAL_SERVE,
    "serve_b8": _REHEARSAL_SERVE,
    "serve_int8_b8": _REHEARSAL_SERVE,
    "serve_w8_b1": _REHEARSAL_SERVE,
    "gemv_int8": dict(d=256, f=512, iters=2),
    "serve_ragged_b8": _REHEARSAL_SERVE,
    "serve_mistral": _REHEARSAL_SERVE,
    "serve_mixtral": _REHEARSAL_SERVE,
    "serve_continuous": dict(n_slots=2, chunk=4, n_requests=4),
    "serve_prefix": dict(prompt_len=64, suffix_len=8, iters=2),
    "spec_verify": dict(t=256, iters=2),
}

BENCHES = {
    "matmul": bench_matmul,
    "flash": bench_flash_fwd,
    "flash_stock": functools.partial(bench_flash_fwd, impl="stock"),
    "flash_window": bench_flash_window,
    "flash_bwd": bench_flash_bwd,
    "flash_bwd_stock": functools.partial(bench_flash_bwd, impl="stock"),
    "decode": bench_decode,
    "decode_lax": functools.partial(bench_decode, impl="lax"),
    "decode_int8": functools.partial(bench_decode, impl="int8"),
    "decode_tune": bench_decode_tune,
    "decode_paged": bench_decode_paged,
    "decode_shapes": bench_decode_shapes,
    "train_mfu": bench_train_mfu,
    "train_mfu_large": bench_train_mfu_large,
    "serve": bench_serve,
    "serve_b8": functools.partial(bench_serve, batch=8),
    "serve_int8_b8": functools.partial(bench_serve, batch=8,
                                       kv_quant="int8"),
    "serve_w8_b1": functools.partial(bench_serve, kv_quant="int8",
                                     weights="int8"),
    "gemv_int8": bench_gemv_int8,
    "serve_ragged_b8": functools.partial(bench_serve, batch=8, ragged=True),
    "serve_mistral": functools.partial(bench_serve, model="mistral"),
    "serve_mixtral": functools.partial(bench_serve, model="mixtral"),
    "serve_continuous": bench_serve_continuous,
    "serve_prefix": bench_serve_prefix,
    "spec_verify": bench_spec_verify,
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--which", default="all",
                    help="comma list of benches, 'all', or 'check' "
                         "(on-chip numerics vs the lax oracles)")
    ap.add_argument("--iters", type=int, default=None)
    args = ap.parse_args()
    rehearsal = os.environ.get("STARWAY_BENCH_REHEARSAL") == "1"
    if rehearsal:
        # The sandbox pre-registers the TPU tunnel backend at interpreter
        # start; env JAX_PLATFORMS=cpu alone is too late (CLAUDE.md).
        jax.config.update("jax_platforms", "cpu")
    if args.which == "check":
        ok = True
        for row in check_numerics():
            ok = ok and row["ok"]
            print(json.dumps(row), flush=True)
        raise SystemExit(0 if ok else 1)
    if args.which == "all":
        # Tune sweeps, the end-to-end serve rows, and the model-scale MFU
        # row are opt-in: each compiles big programs / runs long
        # generations, which would grow the documented bare
        # `bench.py --kernels` pass from minutes to an hour behind the
        # tunnel.  onchip_refresh.sh runs them individually.
        heavy = ("serve", "serve_b8", "serve_ragged_b8", "serve_mistral",
                 "serve_int8_b8", "serve_w8_b1", "serve_continuous",
                 "train_mfu_large", "decode_shapes", "spec_verify",
                 "gemv_int8")
        names = [n for n in BENCHES
                 if not n.endswith("_tune") and n not in heavy]
    else:
        names = args.which.split(",")
    exit_code = 0
    for name in names:
        if name == "check":
            for row in check_numerics():
                if not row["ok"]:
                    exit_code = 1
                print(json.dumps(row), flush=True)
            continue
        fn = BENCHES[name]
        kw = {"iters": args.iters} if args.iters else {}
        if rehearsal:
            kw.update(REHEARSAL_KW.get(name, {}))
        try:
            row = fn(**kw)
        except Exception as e:  # keep going; report the failure as a row
            row = {"metric": name, "error": f"{type(e).__name__}: {e}"[:300]}
        print(json.dumps(row), flush=True)
    raise SystemExit(exit_code)


if __name__ == "__main__":
    main()
