#!/bin/bash
# Probe the tunneled TPU every ~90 s; the moment it answers, run the
# resumable on-chip refresh queue (scripts/onchip_refresh.sh).  Repeats
# forever: after a queue run (complete or tunnel-death abort) it goes back
# to probing, so later windows pick up still-pending rows.
#
# Markers (for a human/driver polling progress):
#   /tmp/tpu_alive      — touched each time a probe succeeds
#   /tmp/tpu_refresh_running — exists while onchip_refresh.sh is running
#   /tmp/onchip_rows.json    — the accumulated measured rows
# Log: /tmp/tpu_watchdog.log
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/tpu_watchdog.log
# The running-marker must not outlive the process (a stale marker reads as
# "refresh in flight" forever to anything polling it).
trap 'rm -f /tmp/tpu_refresh_running' EXIT
while true; do
  if timeout 60 python -c "import jax, jax.numpy as j; float((j.ones(4)+1).sum())" \
      >/dev/null 2>&1; then
    date "+%F %T tunnel ALIVE — starting refresh queue" >> "$LOG"
    touch /tmp/tpu_alive /tmp/tpu_refresh_running
    bash scripts/onchip_refresh.sh >> "$LOG" 2>&1
    rm -f /tmp/tpu_refresh_running
    date "+%F %T refresh queue exited" >> "$LOG"
    # If every row is in, stop probing (grep finds no pending sections by
    # re-running in check mode is overkill — just keep looping; the queue
    # skips measured rows in seconds when complete).
    sleep 300
  else
    date "+%F %T tunnel dead" >> "$LOG"
    sleep 90
  fi
done
