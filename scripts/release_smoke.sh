#!/bin/bash
# Execute the release pipeline once, locally (VERDICT r4 #4): build the
# sdist, install it into a fresh venv, build the native engine from the
# sdist's own sources, and run a smoke slice of the shipped test suite
# with the venv interpreter.  The wheels workflow (.github/workflows/
# wheels.yml) can't run in this sandbox; this proves the same artifacts
# assemble and install.
#
# Offline by construction: --no-isolation builds with the system
# setuptools, the venv uses --system-site-packages for numpy/jax/pytest,
# and pip installs the local tarball with --no-deps --no-build-isolation.
#
# Usage: bash scripts/release_smoke.sh [workdir]   (default /tmp/sw_release)
set -euo pipefail
cd "$(dirname "$0")/.."
WORK="${1:-/tmp/sw_release}"
rm -rf "$WORK"
mkdir -p "$WORK"
WORK="$(cd "$WORK" && pwd)"   # later steps cd around; must be absolute

echo "== 1/9 swcheck: cross-engine contract + concurrency lint"
# Nothing ships until the two engines agree on the wire format, shm
# layout, ABI, and reason strings (python -m starway_tpu.analysis,
# DESIGN.md §11).  Runs from the repo tree, before any artifact exists.
python -m starway_tpu.analysis

echo "== 2/9 sdist build (python -m build --sdist --no-isolation)"
python -m build --sdist --no-isolation --outdir "$WORK/dist" . >"$WORK/build.log" 2>&1 \
  || { tail -20 "$WORK/build.log"; exit 1; }
SDIST="$(ls "$WORK"/dist/*.tar.gz)"
echo "   $SDIST"

echo "== 3/9 sdist completeness (native sources + tests ship)"
tar tzf "$SDIST" | sed 's|^[^/]*/||' | sort > "$WORK/filelist"
for f in native/sw_engine.cpp native/sw_engine.h native/CMakeLists.txt \
         tests/test_basic.py tests/conftest.py starway_tpu/api.py \
         starway_tpu/models/llama.py starway_tpu/native_build.py \
         starway_tpu/analysis/__main__.py tests/test_swcheck.py \
         starway_tpu/analysis/wirefuzz_corpus.txt \
         starway_tpu/analysis/refine_corpus.txt \
         tests/test_session.py scripts/session_chaos.py \
         tests/test_integrity.py starway_tpu/testing/faults.py; do
  grep -qx "$f" "$WORK/filelist" || { echo "MISSING from sdist: $f"; exit 1; }
done
if grep -qx "starway_tpu/_sw_native.so" "$WORK/filelist"; then
  echo "sdist ships a prebuilt binary (_sw_native.so) — it must not"; exit 1
fi
echo "   $(wc -l < "$WORK/filelist") files; native sources + tests present, no prebuilt .so"

echo "== 4/9 wheel built FROM the sdist tree; installed into a fresh venv"
mkdir -p "$WORK/src"
tar xzf "$SDIST" -C "$WORK/src" --strip-components=1
# The wheel is built from the unpacked sdist (exactly what cibuildwheel
# does in its container), with the system toolchain (--no-isolation: the
# sandbox has no network for an isolated build env); the fresh venv then
# installs the finished wheel — no build backend needed at install time.
python -m build --wheel --no-isolation --outdir "$WORK/dist" "$WORK/src" \
  >>"$WORK/build.log" 2>&1 || { tail -20 "$WORK/build.log"; exit 1; }
WHEEL="$(ls "$WORK"/dist/*.whl)"
python -m venv --system-site-packages "$WORK/venv"
VPY="$WORK/venv/bin/python"
# --system-site-packages chains to the BASE interpreter; the working
# numpy/jax/pytest live in THIS interpreter's site-packages (the sandbox
# runs from its own venv).  A .pth bridges them — offline, no installs.
HOST_SITE="$(python -c 'import sysconfig; print(sysconfig.get_paths()["purelib"])')"
VENV_SITE="$("$VPY" -c 'import sysconfig; print(sysconfig.get_paths()["purelib"])')"
echo "$HOST_SITE" > "$VENV_SITE/_host_site.pth"
"$VPY" -m pip install --no-deps --quiet "$WHEEL"
# Import check from a NEUTRAL cwd: the repo root on sys.path would shadow
# the installed package and prove nothing.
(cd "$WORK" && SW_WORK="$WORK" "$VPY" - <<'PY'
import os
import starway_tpu
from starway_tpu import Client, Server, check_sys_libs
assert starway_tpu.__file__.startswith(os.environ["SW_WORK"]), starway_tpu.__file__
print("   installed import ok:", starway_tpu.__file__)
PY
)

echo "== 5/9 native engine built from the sdist's own sources"
(cd "$WORK/src" && "$VPY" -m starway_tpu.native_build >"$WORK/native_build.log" 2>&1) \
  || { tail -20 "$WORK/native_build.log"; exit 1; }
ls -la "$WORK/src/starway_tpu/_sw_native.so"

echo "== 6/9 smoke tests from the sdist tree on the venv interpreter"
(cd "$WORK/src" && "$VPY" -m pytest \
    tests/test_matching.py tests/test_protocol.py \
    "tests/test_basic.py::test_client_to_server_send_recv[inproc]" -q)

echo "== 7/9 fault-injection smoke (drop + partition, small payloads)"
# The shipped FaultProxy harness against the shipped engines: a mid-frame
# drop and a partition-driven timeout/liveness slice, small payloads only
# (the long soaks are @slow and excluded).
(cd "$WORK/src" && "$VPY" -m pytest tests/test_faults.py -q -m "not slow" \
    -k "drop or partition or repost")

echo "== 8/9 session-chaos smoke (resets mid-burst, exactly-once oracle)"
# The shipped resilient-session layer (STARWAY_SESSION, DESIGN.md §14)
# through the shipped FaultProxy: periodic connection resets mid-burst,
# swtrace counters prove every op completed exactly once.  Both engines
# (the sdist tree built its own native engine in step 5).
(cd "$WORK/src" && "$VPY" scripts/session_chaos.py --cycles 3)
(cd "$WORK/src" && "$VPY" scripts/session_chaos.py --cycles 3 \
    --server-engine native --client-engine native)
# §18 overload smoke: many clients, mixed fast/slow receivers, periodic
# kills, the credit window as the no-OOM bound (DESIGN.md §18).
(cd "$WORK/src" && "$VPY" scripts/session_chaos.py --overload \
    --clients 8 --cycles 2 --n 8)

echo "== 9/9 integrity smoke (STARWAY_INTEGRITY=1, DESIGN.md §19)"
# The shipped integrity plane end to end: a checksummed basic slice on
# both engines, then the corruption soak (bit-flips on striped chunks +
# eager frames over periodic kills; byte-exact delivery is the oracle).
(cd "$WORK/src" && STARWAY_INTEGRITY=1 "$VPY" -m pytest \
    "tests/test_basic.py::test_client_to_server_send_recv" \
    tests/test_integrity.py -q -m "not slow" \
    -k "not sm_slot_corruption")
(cd "$WORK/src" && "$VPY" scripts/session_chaos.py --corrupt --cycles 3)

echo "RELEASE SMOKE: OK ($SDIST)"
