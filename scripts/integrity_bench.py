"""Paired-run integrity-overhead gate (ISSUE 11; DESIGN.md §19).

The §19 plane is negotiated at handshake, so it cannot be flipped inside
one worker pair the way the striped paired-baseline mode flips its
per-send threshold -- instead this script interleaves WHOLE loopback
bench runs: OFF, ON, OFF, ON, ... (fresh subprocess per run, so every
run handshakes from scratch and the box's throughput drift hits both
arms equally, the PR-3/PR-8 interleaved-pairs discipline).  Each run is
``python -m starway_tpu.bench --role loopback --scenarios
streaming-duplex`` on the native engine; the report is the per-pair
ON/OFF throughput ratio distribution plus the medians.

Gate (BENCHMARK.md): the default --gate 0.70 is the THIS-BOX regression
bar for the tcp config -- the 1-core dev box is compute-saturated, so
the full two-CRC-passes-per-byte cost shows as ~18% p50 throughput loss
there (table in BENCHMARK.md); a ratio below the bar means the checksum
path itself regressed (e.g. the 3-way interleave was lost), not that
the plane got "more expensive".  The ISSUE 11 <5% target describes a
wire-limited host where the CRC fits the idle CPU margin: enforce it
there with --gate 0.95.

    python scripts/integrity_bench.py [--pairs 5] [--stream-bytes 4M]
    python scripts/integrity_bench.py --json out.json

Exit 0 when the gate holds, 1 otherwise (noisy-box override: rerun).
"""

import argparse
import json
import os
import statistics
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _one_run(integrity: bool, args) -> float:
    """One fresh loopback streaming run; returns aggregate_gbps."""
    env = dict(os.environ)
    env["STARWAY_NATIVE"] = "0" if args.engine == "py" else "1"
    env["STARWAY_TLS"] = args.tls
    env["JAX_PLATFORMS"] = "cpu"
    if integrity:
        env["STARWAY_INTEGRITY"] = "1"
    else:
        env.pop("STARWAY_INTEGRITY", None)
    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as f:
        out = f.name
    try:
        cmd = [sys.executable, "-m", "starway_tpu.bench", "--role", "loopback",
               "--scenarios", "streaming-duplex",
               "--stream-bytes", args.stream_bytes,
               "--stream-iterations", str(args.iterations),
               "--stream-warmup", str(args.warmup),
               "--output", out]
        subprocess.run(cmd, check=True, env=env, stdout=subprocess.DEVNULL,
                       stderr=subprocess.DEVNULL, timeout=600)
        with open(out) as fh:
            report = json.load(fh)
        sc = next(s for s in report["scenarios"]
                  if s["name"] == "streaming-duplex")
        return float(sc["metrics"]["aggregate_gbps"])
    finally:
        try:
            os.unlink(out)
        except OSError:
            pass


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--pairs", type=int, default=5,
                    help="interleaved OFF/ON run pairs (default 5)")
    ap.add_argument("--stream-bytes", default="4M")
    ap.add_argument("--iterations", type=int, default=48)
    ap.add_argument("--warmup", type=int, default=6)
    ap.add_argument("--engine", choices=("native", "py"), default="native")
    ap.add_argument("--tls", default="tcp",
                    help="STARWAY_TLS for both runs (default tcp; use "
                         "'tcp,sm' to gate the slotted-ring path)")
    ap.add_argument("--gate", type=float, default=0.70,
                    help="minimum acceptable median ON/OFF ratio (0.70 = "
                         "this-box compute-saturated bar; use 0.95 on a "
                         "wire-limited host -- see BENCHMARK.md)")
    ap.add_argument("--json", help="write the full report here")
    args = ap.parse_args()

    offs, ons, ratios = [], [], []
    for i in range(args.pairs):
        off = _one_run(False, args)
        on = _one_run(True, args)
        offs.append(off)
        ons.append(on)
        ratios.append(on / off if off > 0 else 0.0)
        print(f"[pair {i}] off={off:.3f} GB/s  on={on:.3f} GB/s  "
              f"ratio={ratios[-1]:.3f}", file=sys.stderr, flush=True)
    report = {
        "engine": args.engine,
        "tls": args.tls,
        "stream_bytes": args.stream_bytes,
        "pairs": args.pairs,
        "off_gbps": offs,
        "on_gbps": ons,
        "ratios": [round(r, 4) for r in ratios],
        "off_gbps_p50": round(statistics.median(offs), 4),
        "on_gbps_p50": round(statistics.median(ons), 4),
        "ratio_p50": round(statistics.median(ratios), 4),
        "ratio_min": round(min(ratios), 4),
        "ratio_max": round(max(ratios), 4),
        "gate": args.gate,
    }
    report["ok"] = report["ratio_p50"] >= args.gate
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(report, fh, indent=2)
    print(json.dumps(report))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
