"""Interleaved paired-ratio bench runner (ISSUE 17; the reusable form of
scripts/integrity_bench.py's discipline).

Compares two bench configurations A ("baseline") and B ("candidate") by
interleaving WHOLE fresh-subprocess loopback runs -- A, B, A, B, ... --
so the box's throughput drift hits both arms equally (the PR-3/PR-8
paired discipline).  Each arm is ``python -m starway_tpu.bench --role
loopback`` with that arm's env overlay; the report is the per-pair B/A
metric ratio distribution, its p50, and a two-sided sign test on the
pair directions (stdlib ``math.comb`` -- no scipy), emitted as ONE JSON
line on stdout, integrity_bench-style.

Arms differ only by env (that is how every starway plane is armed:
STARWAY_INTEGRITY, STARWAY_FC_WINDOW, STARWAY_RAILS, STARWAY_NATIVE...),
so A-vs-B is expressed as env overlays::

    # integrity overhead, native engine (the integrity_bench scenario):
    python scripts/paired_bench.py --pairs 5 --gate 0.70 \
        --b-env STARWAY_INTEGRITY=1

    # HEAD-vs-baseline engine comparison on the same checkout:
    python scripts/paired_bench.py --a-env STARWAY_NATIVE=0 \
        --b-env STARWAY_NATIVE=1 --scenario streaming-duplex

    # extra bench flags ride through verbatim (= form: argparse would
    # otherwise eat the leading dashes):
    python scripts/paired_bench.py --b-env STARWAY_FC_WINDOW=1M \
        --bench-arg=--stream-bytes --bench-arg=8M

A ``--a-env``/``--b-env`` of ``KEY=VAL`` sets, bare ``KEY`` unsets (so a
plane armed in the outer environment can be the *baseline* arm).  The
metric is read from the named scenario's report entry (default
``aggregate_gbps``); ``--gate R`` turns the run into a pass/fail check
on ratio p50 (exit 1 below it), otherwise exit 0 -- the nightly CI job
runs ungated and uploads the JSON line as an artifact for trend eyes.

The sign test answers "is B consistently on one side of A?" without a
variance model: under H0 (no difference) each pair's direction is a
fair coin, so ``p_sign`` is the two-sided binomial tail of the observed
split.  With the default 5 pairs the floor is p=0.0625 -- treat small-n
p-values as a smell, not a verdict, and rerun with --pairs 10+ before
believing a regression.
"""

import argparse
import json
import math
import os
import statistics
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _apply_env(base: dict, specs: list) -> dict:
    env = dict(base)
    for spec in specs or ():
        if "=" in spec:
            key, val = spec.split("=", 1)
            env[key] = val
        else:
            env.pop(spec, None)
    return env


def _one_run(env: dict, args) -> float:
    """One fresh loopback bench run; returns the chosen scenario metric."""
    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as f:
        out = f.name
    try:
        cmd = [sys.executable, "-m", "starway_tpu.bench", "--role", "loopback",
               "--scenarios", args.scenario,
               "--output", out] + (args.bench_arg or [])
        subprocess.run(cmd, check=True, env=env, stdout=subprocess.DEVNULL,
                       stderr=subprocess.DEVNULL, timeout=args.run_timeout)
        with open(out) as fh:
            report = json.load(fh)
        sc = next(s for s in report["scenarios"] if s["name"] == args.scenario)
        v = sc["metrics"].get(args.metric)
        if v is None:
            raise SystemExit(
                f"paired_bench: scenario {args.scenario!r} has no metric "
                f"{args.metric!r}; available: {sorted(sc['metrics'])}")
        return float(v)
    finally:
        try:
            os.unlink(out)
        except OSError:
            pass


def _sign_test_p(ratios: list) -> float:
    """Two-sided sign test: P(split at least this lopsided | fair coin),
    ties (ratio exactly 1.0) discarded per the classical test."""
    n = sum(1 for r in ratios if r != 1.0)
    if n == 0:
        return 1.0
    k = sum(1 for r in ratios if r > 1.0)
    tail = min(k, n - k)
    p = 2.0 * sum(math.comb(n, i) for i in range(tail + 1)) / (2.0 ** n)
    return min(1.0, p)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--pairs", type=int, default=5,
                    help="interleaved A/B run pairs (default 5)")
    ap.add_argument("--scenario", default="streaming-duplex",
                    help="bench scenario to run (default streaming-duplex)")
    ap.add_argument("--metric", default="aggregate_gbps",
                    help="scenario metric to ratio (default aggregate_gbps; "
                         "e.g. median_rtt_us for pingpong-flag)")
    ap.add_argument("--higher-is-better", dest="higher", default=True,
                    action="store_true",
                    help="B/A ratio >= gate passes (default; throughput)")
    ap.add_argument("--lower-is-better", dest="higher", action="store_false",
                    help="invert the ratio as A/B so the gate still reads "
                         "'>= gate passes' (latency metrics)")
    ap.add_argument("--a-env", action="append", metavar="KEY[=VAL]",
                    help="baseline-arm env overlay (repeatable; bare KEY "
                         "unsets)")
    ap.add_argument("--b-env", action="append", metavar="KEY[=VAL]",
                    help="candidate-arm env overlay (repeatable; bare KEY "
                         "unsets)")
    ap.add_argument("--bench-arg", action="append", metavar="ARG",
                    help="extra argv passed to both arms' bench runs "
                         "(repeatable; use the = form for dashed values: "
                         "--bench-arg=--stream-bytes --bench-arg=8M)")
    ap.add_argument("--gate", type=float, default=None,
                    help="minimum acceptable ratio p50; omitted = report "
                         "only, always exit 0")
    ap.add_argument("--run-timeout", type=int, default=600,
                    help="per-run subprocess timeout seconds (default 600)")
    ap.add_argument("--json", help="also write the report here")
    args = ap.parse_args()

    base = dict(os.environ)
    base.setdefault("JAX_PLATFORMS", "cpu")
    env_a = _apply_env(base, args.a_env)
    env_b = _apply_env(base, args.b_env)

    a_vals, b_vals, ratios = [], [], []
    for i in range(args.pairs):
        a = _one_run(env_a, args)
        b = _one_run(env_b, args)
        a_vals.append(a)
        b_vals.append(b)
        if args.higher:
            ratios.append(b / a if a > 0 else 0.0)
        else:
            ratios.append(a / b if b > 0 else 0.0)
        print(f"[pair {i}] a={a:.4f}  b={b:.4f}  ratio={ratios[-1]:.4f}",
              file=sys.stderr, flush=True)

    report = {
        "scenario": args.scenario,
        "metric": args.metric,
        "higher_is_better": args.higher,
        "pairs": args.pairs,
        "a_env": args.a_env or [],
        "b_env": args.b_env or [],
        "a_values": [round(v, 6) for v in a_vals],
        "b_values": [round(v, 6) for v in b_vals],
        "ratios": [round(r, 4) for r in ratios],
        "a_p50": round(statistics.median(a_vals), 6),
        "b_p50": round(statistics.median(b_vals), 6),
        "ratio_p50": round(statistics.median(ratios), 4),
        "ratio_min": round(min(ratios), 4),
        "ratio_max": round(max(ratios), 4),
        "p_sign": round(_sign_test_p(ratios), 4),
        "gate": args.gate,
    }
    report["ok"] = args.gate is None or report["ratio_p50"] >= args.gate
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(report, fh, indent=2)
    print(json.dumps(report))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
