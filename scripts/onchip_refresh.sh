#!/bin/bash
# One-shot on-chip measurement queue: run when TPU hardware is reachable.
#
# Refreshes every row in BASELINE.md's round-2 table, including the items
# the chip outage left pending (decode @ the new block_k=512 default,
# the decode_tune sweep behind it, and the windowed flash row).  Each
# section prints JSON rows; paste the results into BASELINE.md.
#
# Usage:  bash scripts/onchip_refresh.sh [outfile]
set -u
cd "$(dirname "$0")/.."
OUT="${1:-/tmp/onchip_rows.json}"
: > "$OUT"

probe() {
  timeout 90 python -c "import jax, jax.numpy as j; float((j.ones(4)+1).sum())" \
    2>/dev/null || { echo "device backend unresponsive; aborting" >&2; exit 1; }
}

run() {  # [ROW_TIMEOUT=secs] run <which> [extra flags...]
  local which="$1"; shift
  echo "== $which" >&2
  probe  # the tunnel can die mid-queue; fail fast, not per-row timeouts
  local log tmp rc t="${ROW_TIMEOUT:-1200}"
  log="$(mktemp)"; tmp="$(mktemp)"
  timeout "$t" python bench.py --kernels "$which" "$@" >"$tmp" 2>"$log"
  rc=$?
  grep '"metric"' "$tmp" | tee -a "$OUT"
  if [ $rc -ne 0 ] || ! grep -q '"metric"' "$tmp"; then
    echo "{\"metric\": \"${which}\", \"error\": \"rc=$rc (124=timeout); see $log\"}" \
      | tee -a "$OUT" >&2
  else
    rm -f "$log"
  fi
  rm -f "$tmp"
}

probe
run matmul
run flash
run flash_window
run flash_bwd
run decode            # block_k=512 default: the row BASELINE.md flags as pending
run decode_lax
run decode_tune       # stream/grid variant x block sweep; retune the default
run decode_shapes     # ours-vs-lax at the VERDICT r2 acceptance shapes
run train_mfu
# 672M-param compiles x two differenced loop lengths can exceed the default
# row timeout; give this one headroom.
ROW_TIMEOUT=3000 run train_mfu_large  # model-scale MFU (target >= 0.40)
run serve             # end-to-end generate() tokens/s (VERDICT r3 #4) ...
run serve_b8          # ... batch 8
run serve_ragged_b8   # ... ragged (mixed prompt lengths)
run serve_mistral     # ... rolling O(window) cache path
run serve_continuous  # continuous batching: wall tok/s through slot reuse
echo "== check" >&2
timeout 1200 python bench.py --kernels check 2>/dev/null | grep '"metric"' | tee -a "$OUT"
echo "rows written to $OUT" >&2
