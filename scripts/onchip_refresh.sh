#!/bin/bash
# One-shot on-chip measurement queue: run when TPU hardware is reachable.
#
# RESUMABLE: each section is skipped when $OUT already holds its success row
# (an "error" row does not count), so after a mid-queue tunnel death the next
# run goes straight to the still-pending rows.  The observed failure mode is
# exactly that — the tunnel came back for ~25 min in round 3, measured six
# rows, and died during decode_tune — so the queue is ordered fast/high-value
# first (driver headline, numerics checks, MFU, serving) and leaves the
# decode_tune sweep (pure retuning; the stream default already wins) for last.
#
# Usage:  bash scripts/onchip_refresh.sh [outfile]     (default /tmp/onchip_rows.json)
#         FORCE=1 re-measures everything regardless of existing rows.
#         REHEARSAL=1 runs every row's exact command on CPU with scaled
#         budgets (kernel_bench.REHEARSAL_KW) — the pre-flight that proves
#         no row can zero out a live tunnel window with a shape bug
#         (VERDICT r4 #3).  Default outfile /tmp/rehearsal_rows.json.
set -u
cd "$(dirname "$0")/.."
REHEARSAL="${REHEARSAL:-0}"
if [ "$REHEARSAL" = "1" ]; then
  export STARWAY_BENCH_REHEARSAL=1 STARWAY_BENCH_CPU=1
  OUT="${1:-/tmp/rehearsal_rows.json}"
else
  OUT="${1:-/tmp/onchip_rows.json}"
fi
touch "$OUT"

probe() {
  if [ "$REHEARSAL" = "1" ]; then
    # timeout matters here too: sitecustomize registers the tunnel backend
    # before the heredoc's config.update can run, and a wedged tunnel can
    # hang interpreter/jax init itself.
    timeout 90 python - <<'PY' 2>/dev/null || { echo "CPU jax unusable; aborting" >&2; exit 1; }
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as j
float((j.ones(4) + 1).sum())
PY
    return
  fi
  timeout 90 python -c "import jax, jax.numpy as j; float((j.ones(4)+1).sum())" \
    2>/dev/null || { echo "device backend unresponsive; aborting" >&2; exit 1; }
}

have() {  # have <metric>: a non-error row for <metric> is already recorded
  [ "${FORCE:-0}" = "1" ] && return 1
  grep "\"metric\": \"$1\"" "$OUT" | grep -qv '"error"'
}

want() {  # ROWS="a b c" restricts the queue to named rows; unset = all
  [ -z "${ROWS:-}" ] && return 0
  case " $ROWS " in *" $1 "*) return 0 ;; *) return 1 ;; esac
}

run() {  # [ROW_TIMEOUT=secs] run <which> <done_metric> [extra flags...]
  local which="$1" done_key="$2"; shift 2
  want "$which" || return 0
  if have "$done_key"; then echo "== $which (already measured; skip)" >&2; return; fi
  echo "== $which" >&2
  probe  # the tunnel can die mid-queue; fail fast, not per-row timeouts
  local log tmp rc t="${ROW_TIMEOUT:-1200}"
  log="$(mktemp)"; tmp="$(mktemp)"
  timeout "$t" python bench.py --kernels "$which" "$@" >"$tmp" 2>"$log"
  rc=$?
  grep '"metric"' "$tmp" | tee -a "$OUT"
  # kernel_bench catches bench exceptions into {"error": ...} rows and exits
  # 0 — an error row in the output is a failure too (keep the log).
  if [ $rc -ne 0 ] || ! grep -q '"metric"' "$tmp" || grep -q '"error"' "$tmp"; then
    echo "{\"metric\": \"${which}\", \"error\": \"rc=$rc (124=timeout); see $log\"}" \
      | tee -a "$OUT" >&2
  else
    rm -f "$log"
  fi
  rm -f "$tmp"
}

probe
if [ "${FORCE:-0}" = "1" ]; then
  # A re-measure must not leave two conflicting rows per metric — but only
  # drop the old rows once the device has answered a probe, so a dead
  # tunnel cannot destroy measured results while measuring nothing.
  : > "$OUT"
fi

# -- fast, high-value pending rows first ------------------------------------
if ! want headline; then
  : # ROWS filter excludes the headline
elif have driver_headline; then
  echo "== headline (already measured; skip)" >&2
else
  echo "== headline (driver bench.py)" >&2
  tmp="$(mktemp)"
  # bench.py's own watchdogs can burn 480s (device) + 240s (CPU retry);
  # the outer timeout must sit above that sum or the fallback dies unreported.
  timeout 780 python bench.py >"$tmp" 2>/dev/null
  # Rehearsal runs pipeline-validate on CPU: the FALLBACK label is the
  # expected outcome there, not a failure.
  if [ "$REHEARSAL" = "1" ]; then ok_filter='FAILED'; else ok_filter='CPU FALLBACK\|FAILED'; fi
  if grep -q vs_baseline "$tmp" && ! grep -q "$ok_filter" "$tmp"; then
    tee -a "$OUT" < "$tmp"
    # Marker row so resume can see the prose-named headline landed.
    echo '{"metric": "driver_headline", "value": 1, "unit": "done"}' >> "$OUT"
  else
    cat "$tmp"; echo '{"metric": "driver_headline", "error": "fallback or no output"}' | tee -a "$OUT" >&2
  fi
  rm -f "$tmp"
fi

run check            check_flash_fwd_onchip             # 9 on-chip numerics rows
run train_mfu        train_step_mfu
run serve            serve_llama_b1_tokens_per_s        # end-to-end generate() tok/s (VERDICT r3 #4)
run serve_b8         serve_llama_b8_tokens_per_s
run serve_mistral    serve_mistral_b1_tokens_per_s      # rolling O(window) cache path
run serve_mixtral    serve_mixtral_b1_tokens_per_s      # dropless top-2 MoE decode
run serve_ragged_b8  serve_llama_ragged_b8_tokens_per_s # mixed prompt lengths
run serve_continuous serve_continuous_tokens_per_s      # wall-clock through slot reuse
run decode_int8      decode_int8_us_per_token           # half-width int8 cache stream
run decode_paged     decode_paged_us_per_token          # page-table stream vs dense (expect ~decode_ours)
run serve_int8_b8    serve_llama_int8_b8_tokens_per_s   # int8 cache end to end
run spec_verify      spec_verify_amortisation           # chunk verify vs gamma decode steps
run serve_prefix     serve_prefix_admit_speedup         # prefix-cached admission vs full prefill
run gemv_int8        gemv_int8_speedup                  # W8A16 weight stream vs bf16
run serve_w8_b1      serve_llama_int8_w8_b1_tokens_per_s # whole-model int8 serving (KV + weights)
# 672M-param compiles x two differenced loop lengths can exceed the default
# row timeout; give this one headroom.
ROW_TIMEOUT="${ROW_TIMEOUT_LARGE:-3000}" run train_mfu_large train_step_mfu_large  # model-scale MFU (target >= 0.40)
run decode_shapes    decode_shape_wins                  # ours-vs-lax at the r2 acceptance shapes

# -- re-confirmation rows (captured 2026-07-31; skipped unless FORCE=1) -----
run matmul       matmul_ceiling_tflops
run flash        flash_fwd_ours_tflops
run flash_window flash_window_tflops
run flash_bwd    flash_fwdbwd_ours_tflops
run decode       decode_ours_us_per_token   # stream default: beats lax 2.30x
run decode_lax   decode_lax_us_per_token

# -- slow optimization sweep last (stream already wins at its default) ------
ROW_TIMEOUT="${ROW_TIMEOUT_LARGE:-2400}" run decode_tune decode_best_config

echo "rows written to $OUT" >&2
