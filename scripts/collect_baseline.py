"""Measure the five BASELINE.json configs + bench scenarios; prints a
markdown table for BASELINE.md.  Run on the virtual CPU mesh by default
(STARWAY_BASELINE_REAL=1 to use the real backend for device rows)."""

from __future__ import annotations

import asyncio
import json
import os
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

if os.environ.get("STARWAY_BASELINE_REAL") != "1":
    import jax

    jax.config.update("jax_platforms", "cpu")
    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import numpy as np

MASK = (1 << 64) - 1
rows: list[tuple[str, str]] = []


async def config1_pingpong_sweep():
    """pingpong 4B-1MB numpy uint8 over loopback (in-process fast path)."""
    from starway_tpu import Client, Server

    server = Server()
    server.listen("127.0.0.1", 0)
    client = Client()
    await client.aconnect_address(server.get_worker_address())
    ep = None
    for _ in range(200):
        if server.list_clients():
            ep = server.list_clients().pop()
            break
        await asyncio.sleep(0.005)
    out = []
    for size in (4, 1024, 64 * 1024, 1 << 20):
        buf = np.zeros(size, np.uint8)
        sink = np.zeros(size, np.uint8)
        rtts = []
        for i in range(300):
            t0 = time.perf_counter()
            f = server.arecv(sink, 1, MASK)
            await client.asend(buf, 1)
            await f
            f2 = client.arecv(buf, 2, MASK)
            await server.asend(ep, sink, 2)
            await f2
            if i >= 50:
                rtts.append(time.perf_counter() - t0)
        p50 = statistics.median(rtts)
        out.append(f"{size}B: rtt_p50={p50 * 1e6:.0f}us ({2 * size / p50 / 1e9:.2f} GB/s)")
    rows.append(("config 1: pingpong sweep 4B-1MB (loopback, inproc)", "; ".join(out)))
    await client.aclose()
    await server.aclose()


async def config2_fanin():
    """1 Server x 8 Clients, tag-routed fan-in."""
    from starway_tpu import Client, Server

    server = Server()
    server.listen("127.0.0.1", 0)
    addr = server.get_worker_address()
    clients = []
    for _ in range(8):
        c = Client()
        await c.aconnect_address(addr)
        clients.append(c)
    n_msgs = 200
    payload = np.zeros(1024, np.uint8)
    sink = np.zeros(1024, np.uint8)
    t0 = time.perf_counter()
    for _ in range(n_msgs):
        recvs = [server.arecv(sink, i, MASK) for i in range(8)]
        sends = [c.asend(payload, i) for i, c in enumerate(clients)]
        await asyncio.gather(*sends, *recvs)
    dt = time.perf_counter() - t0
    total = 8 * n_msgs
    rows.append(
        ("config 2: 8-client tag-matched fan-in (1KiB msgs)",
         f"{total / dt:.0f} msgs/s, {total * 1024 / dt / 1e6:.1f} MB/s")
    )
    for c in clients:
        await c.aclose()
    await server.aclose()


async def config3_worker_address():
    """Worker-address bootstrap latency (no TCP listener semantics)."""
    from starway_tpu import Client, Server

    times = []
    for _ in range(10):
        server = Server()
        blob = server.listen_address()
        t0 = time.perf_counter()
        client = Client()
        await client.aconnect_address(blob)
        times.append(time.perf_counter() - t0)
        await client.aclose()
        await server.aclose()
    rows.append(
        ("config 3: worker-address bootstrap (aconnect_address)",
         f"connect p50 = {statistics.median(times) * 1e3:.2f} ms")
    )


def config4_shuffle():
    """1GB-scale all-to-all shuffle over the 8-way mesh axis."""
    import jax
    import jax.numpy as jnp

    from starway_tpu.parallel import make_mesh, make_shuffle
    from starway_tpu.parallel.sharding import shard_array

    mesh = make_mesh({"x": 8})
    total = 1 << 28  # 256 MiB of f32 = 1 GiB
    s, b = 64, 16
    d = total // (s * b)
    x = jnp.zeros((s, b, d), jnp.float32)
    xs = shard_array(mesh, x, "x")
    shuffle = make_shuffle(mesh, "x")
    shuffle(xs).block_until_ready()  # compile
    t0 = time.perf_counter()
    iters = 3
    for _ in range(iters):
        shuffle(xs).block_until_ready()
    dt = (time.perf_counter() - t0) / iters
    nbytes = x.size * 4
    rows.append(
        ("config 4: 1GiB all-to-all shuffle (8-way mesh, jitted lax.all_to_all)",
         f"{nbytes / 1e9:.2f} GB in {dt * 1e3:.0f} ms = {nbytes / dt / 1e9:.2f} GB/s")
    )


async def config5_dp_exchange():
    """Llama gradient pytree transfer across the DP boundary."""
    import jax
    import jax.numpy as jnp

    from starway_tpu import Client, Server
    from starway_tpu.models import LlamaConfig, init_params
    from starway_tpu.parallel import ClientPort, ServerPort, recv_pytree, send_pytree

    cfg = LlamaConfig.preset("debug", n_layers=4, d_model=512, d_ff=1024)
    params = init_params(jax.random.PRNGKey(0), cfg)
    nbytes = sum(x.nbytes for x in jax.tree_util.tree_leaves(params))

    server = Server()
    server.listen("127.0.0.1", 0)
    client = Client()
    await client.aconnect_address(server.get_worker_address())
    for _ in range(200):
        if server.list_clients():
            break
        await asyncio.sleep(0.005)

    t0 = time.perf_counter()
    iters = 5
    for _ in range(iters):
        send_task = asyncio.ensure_future(
            send_pytree(ClientPort(client), params, base_tag=0x8000)
        )
        await recv_pytree(ServerPort(server), like=params, base_tag=0x8000)
        await send_task
    dt = (time.perf_counter() - t0) / iters
    rows.append(
        (f"config 5: Llama grad pytree DP transfer ({nbytes / 1e6:.0f} MB, {len(jax.tree_util.tree_leaves(params))} leaves)",
         f"{dt * 1e3:.0f} ms/transfer = {nbytes / dt / 1e9:.2f} GB/s")
    )
    await client.aclose()
    await server.aclose()


def main():
    asyncio.run(config1_pingpong_sweep())
    asyncio.run(config2_fanin())
    asyncio.run(config3_worker_address())
    config4_shuffle()
    asyncio.run(config5_dp_exchange())
    print("\n| Config | Measured |")
    print("|---|---|")
    for name, val in rows:
        print(f"| {name} | {val} |")
    out = {name: val for name, val in rows}
    Path("/tmp/baseline_results.json").write_text(json.dumps(out, indent=2))


if __name__ == "__main__":
    main()
