"""session-chaos smoke: a loopback pair through FaultProxy under periodic
connection resets, with swtrace counters as the exactly-once oracle.

The CI twin of tests/test_session.py::test_session_chaos_soak (which is
the @slow long variant): every cycle posts a burst of arecv/asend, kills
the proxied connection mid-burst with an RST, and the resilient-session
layer (STARWAY_SESSION=1, DESIGN.md §14) must redial + replay so that
every posted asend/arecv/aflush completes exactly once -- the server's
``recvs_completed`` counter equals the total posted, at least one
``sessions_resumed`` is recorded, no duplicate delivery escapes the
``dup_frames_dropped`` dedup, and no op fails.

Runs on both engines (the env is sampled at worker construction, so the
roles can differ):

    python scripts/session_chaos.py --server-engine native --client-engine py

Progress is LIVE: the swscope telemetry sampler (core/telemetry.py,
DESIGN.md §15) is armed for the run and every cycle prints the current
resume count and session-journal residency from its latest sample -- a
stalled chaos run shows where it stalled, not just a missing final line.

Exit 0 and one JSON result line on success; non-zero with a diagnostic on
any lost, duplicated, or failed op.
"""

import argparse
import asyncio
import json
import os
import sys
import time

# Runnable straight from a checkout (CI, release_smoke's sdist tree).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _parse():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--server-engine", choices=("py", "native"), default="py")
    ap.add_argument("--client-engine", choices=("py", "native"), default="py")
    ap.add_argument("--cycles", type=int, default=4,
                    help="kill/resume cycles (default 4)")
    ap.add_argument("--n", type=int, default=15,
                    help="ops per cycle (default 15)")
    ap.add_argument("--size", type=int, default=4096,
                    help="payload bytes per op (default 4096)")
    ap.add_argument("--corrupt", action="store_true",
                    help="mixed corruption soak (ISSUE 11, DESIGN.md §19): "
                         "STARWAY_INTEGRITY=1 + sessions + fc + rails, "
                         "driven through a corrupt-mode FaultProxy that "
                         "bit-flips eager DATA frames AND striped chunks "
                         "while connections are periodically killed; "
                         "oracle: every op completes exactly once with "
                         "byte-exact payloads, every flip is detected "
                         "(csum_fail), chunk flips recover by retransmit "
                         "and frame flips by suspend+replay")
    ap.add_argument("--overload", action="store_true",
                    help="many-client overload soak (DESIGN.md §18): "
                         "--clients concurrent senders against ONE server, "
                         "mixed fast/slow receivers, periodic kills; swtrace "
                         "counters + gauges are the no-OOM / exactly-once "
                         "oracle")
    ap.add_argument("--clients", type=int, default=8,
                    help="overload mode: concurrent client workers (default 8)")
    ap.add_argument("--slow-every", type=int, default=3,
                    help="overload mode: every k-th client's receives post "
                         "LATE (a slow consumer; default 3)")
    ap.add_argument("--fc-window", type=int, default=64 * 1024,
                    help="overload mode: STARWAY_FC_WINDOW bytes (default 64Ki)")
    return ap.parse_args()


def _monitor_check(report: dict) -> bool:
    """swrefine conformance checkpoint (DESIGN.md §22): with
    STARWAY_MONITOR=1 every chaos schedule is also a model<->code
    conformance check -- replay every traced ring through the protocol
    monitor and fail the soak hard on any divergence (the violation's
    flight dump + ring land under STARWAY_FLIGHT_DIR for CI artifacts)."""
    from starway_tpu.core import monitor, swtrace

    if not monitor.active():
        return True
    monitor.check_all()
    viols = monitor.violations()
    report["monitor_violations"] = len(viols)
    report["monitor_witnessed"] = len(monitor.witnessed())
    if viols:
        flight = os.environ.get("STARWAY_FLIGHT_DIR")
        if flight:
            swtrace.write_ring_dump(
                os.path.join(flight, f"monitor-rings-{os.getpid()}.json"))
        for v in viols:
            print(f"MONITOR VIOLATION: {v.render()}", file=sys.stderr)
        return False
    return True


def _print_live(cycle: int, total: int, sample: dict) -> None:
    """One progress line per cycle, read from the sampler's snapshot (the
    same JSONL shape STARWAY_METRICS_PATH emits)."""
    resumes = replayed = journal = 0
    for wk in sample.get("workers", {}).values():
        ctr = wk.get("counters", {})
        resumes += ctr.get("sessions_resumed", 0)
        replayed += ctr.get("frames_replayed", 0)
        for g in wk.get("gauges", {}).get("conns", {}).values():
            journal += g.get("journal_bytes", 0)
    print(f"[cycle {cycle}] ops={total} resumes={resumes} "
          f"replayed={replayed} journal_bytes={journal}",
          file=sys.stderr, flush=True)


async def _main(args) -> int:
    # Env before any worker is built: workers sample it at construction.
    os.environ["STARWAY_TLS"] = "tcp"
    os.environ["STARWAY_SESSION"] = "1"
    os.environ.setdefault("STARWAY_SESSION_GRACE", "30")
    # Arm the swscope sampler so progress prints come from live samples.
    os.environ.setdefault("STARWAY_METRICS_INTERVAL", "0.25")
    # swpulse sentinel (DESIGN.md §25): every chaos schedule doubles as a
    # liveness check -- kills + resumes are PROGRESS, so a healthy soak
    # must end with zero stall_alerts (asserted in the oracle below).
    os.environ.setdefault("STARWAY_STALL_MS", "5000")

    import socket

    import numpy as np

    from starway_tpu import Client, Server
    from starway_tpu.core import telemetry
    from starway_tpu.testing.faults import FaultProxy

    with socket.socket() as s:  # a free loopback port for the server
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    os.environ["STARWAY_NATIVE"] = "1" if args.server_engine == "native" else "0"
    server = Server()
    server.listen("127.0.0.1", port)
    proxy = FaultProxy("127.0.0.1", port).start()
    os.environ["STARWAY_NATIVE"] = "1" if args.client_engine == "native" else "0"
    client = Client()
    await client.aconnect("127.0.0.1", proxy.port)

    total = 0
    t0 = time.monotonic()
    try:
        for cycle in range(args.cycles):
            n, size, tag0 = args.n, args.size, cycle * 1000
            bufs = [np.zeros(size, dtype=np.uint8) for _ in range(n)]
            recvs = [server.arecv(bufs[i], tag0 + i, (1 << 64) - 1)
                     for i in range(n)]
            sends = []
            for i in range(n):
                sends.append(client.asend(
                    np.full(size, (tag0 + i) % 251, dtype=np.uint8), tag0 + i))
                if i == n // 2:
                    await asyncio.sleep(0.2)  # let part of the burst fly
                    proxy.kill_all(rst=True)  # the periodic conn reset
            await asyncio.wait_for(asyncio.gather(*sends), timeout=60)
            await asyncio.wait_for(client.aflush(), timeout=60)
            res = await asyncio.wait_for(asyncio.gather(*recvs), timeout=60)
            for i, (stag, ln) in enumerate(res):
                assert stag == tag0 + i and ln == size, (cycle, i, stag, ln)
                assert bufs[i][0] == (tag0 + i) % 251, (cycle, i)
                assert bufs[i][-1] == (tag0 + i) % 251, (cycle, i)
            total += n
            _print_live(cycle, total, telemetry.sample_now())

        ss = server._server.counters_snapshot()
        cs = client._client.counters_snapshot()
        report = {
            "server_engine": args.server_engine,
            "client_engine": args.client_engine,
            "cycles": args.cycles,
            "ops": total,
            "elapsed_s": round(time.monotonic() - t0, 3),
            "recvs_completed": ss["recvs_completed"],
            "sessions_resumed": cs["sessions_resumed"] + ss["sessions_resumed"],
            "frames_replayed": cs["frames_replayed"],
            "dup_frames_dropped": ss["dup_frames_dropped"],
            "ops_failed": cs["ops_timed_out"] + ss["ops_timed_out"],
            "stall_alerts": cs["stall_alerts"] + ss["stall_alerts"],
        }
        # The exactly-once oracle: each posted recv completed ONCE (the
        # matcher never double-fires a future, so == total also rules out
        # duplicate delivery), and the outage was ridden through by
        # resume, not by fresh conns.  The §25 sentinel doubles as the
        # liveness oracle: a schedule that completes must never have
        # tripped a stall alert along the way.
        ok = (ss["recvs_completed"] == total
              and report["sessions_resumed"] >= 1
              and report["stall_alerts"] == 0)
        ok = _monitor_check(report) and ok
        report["ok"] = ok
        print(json.dumps(report))
        return 0 if ok else 1
    finally:
        for obj in (client, server):
            try:
                await asyncio.wait_for(obj.aclose(), timeout=10)
            except Exception:
                pass
        proxy.stop()


async def _corrupt_soak(args) -> int:
    """Corruption chaos (ISSUE 11): integrity + sessions + fc + rails all
    on, a corrupt-mode proxy flipping bits in whatever body frames pass
    (eager DATA -> poison + suspend + replay; striped T_SDATA -> T_SNACK
    single-chunk retransmit), and periodic mid-burst kills layered on
    top.  Oracle: every posted op completes exactly once with byte-exact
    payloads, every injected flip was DETECTED (csum_fail + chunk_retx
    cover the injected count -- silent corruption is the one inadmissible
    outcome), and resumes covered the kills."""
    os.environ["STARWAY_TLS"] = "tcp"
    os.environ["STARWAY_SESSION"] = "1"
    os.environ["STARWAY_INTEGRITY"] = "1"
    os.environ.setdefault("STARWAY_SESSION_GRACE", "30")
    os.environ["STARWAY_FC_WINDOW"] = str(args.fc_window)
    os.environ["STARWAY_RAILS"] = "2"
    os.environ["STARWAY_STRIPE_THRESHOLD"] = str(1 << 20)
    os.environ["STARWAY_STRIPE_CHUNK"] = str(256 << 10)
    os.environ.setdefault("STARWAY_METRICS_INTERVAL", "0.25")
    os.environ.setdefault("STARWAY_STALL_MS", "5000")  # §25 liveness oracle

    import socket

    import numpy as np

    from starway_tpu import Client, Server
    from starway_tpu.core import telemetry
    from starway_tpu.testing.faults import FaultProxy

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    os.environ["STARWAY_NATIVE"] = "1" if args.server_engine == "native" else "0"
    server = Server()
    server.listen("127.0.0.1", port)
    # Phase 1 targets striped T_SDATA chunks (the NACK/retransmit path);
    # after half the cycles the selector flips to eager DATA frames (the
    # poison/suspend/replay path).  Payload-region flips only -- header
    # flips are the poison-always path, covered by tests/test_integrity.py.
    # Capped at one flip per cycle so late resumes see a clean pipe.
    proxy = FaultProxy("127.0.0.1", port, mode="corrupt", corrupt_ftype=12,
                       corrupt_count=max(1, args.cycles // 2)).start()
    os.environ["STARWAY_NATIVE"] = "1" if args.client_engine == "native" else "0"
    client = Client()
    await client.aconnect("127.0.0.1", proxy.port)

    total = 0
    t0 = time.monotonic()
    big_n = 2 << 20
    big = (np.arange(big_n, dtype=np.uint64) % 251).astype(np.uint8)
    try:
        for cycle in range(args.cycles):
            n, size, tag0 = args.n, args.size, cycle * 1000
            bufs = [np.zeros(size, dtype=np.uint8) for _ in range(n)]
            recvs = [server.arecv(bufs[i], tag0 + i, (1 << 64) - 1)
                     for i in range(n)]
            sink = np.zeros(big_n, dtype=np.uint8)
            bigrecv = server.arecv(sink, tag0 + 999, (1 << 64) - 1)
            sends = [client.asend(
                np.full(size, (tag0 + i) % 251, dtype=np.uint8), tag0 + i)
                for i in range(n)]
            bigsend = client.asend(big, tag0 + 999)  # striped across rails
            if cycle % 2 == 1:
                await asyncio.sleep(0.15)
                proxy.kill_all(rst=True)  # kills layered over corruption
            if cycle == args.cycles // 2:
                # Phase 2: retarget the live proxy at eager DATA frames.
                proxy.corrupt_ftype = 3
                proxy._corrupt_left = args.cycles - args.cycles // 2
            await asyncio.wait_for(asyncio.gather(*sends, bigsend), 90)
            await asyncio.wait_for(client.aflush(), 90)
            await asyncio.wait_for(asyncio.gather(*recvs, bigrecv), 90)
            for i in range(n):
                assert bufs[i][0] == (tag0 + i) % 251, (cycle, i)
                assert bufs[i][-1] == (tag0 + i) % 251, (cycle, i)
            assert (sink == big).all(), f"cycle {cycle}: striped corrupt"
            total += n + 1
            _print_live(cycle, total, telemetry.sample_now())

        ss = server._server.counters_snapshot()
        cs = client._client.counters_snapshot()
        detected = ss["csum_fail"] + cs["csum_fail"]
        retx = cs["chunk_retx"] + ss["chunk_retx"]
        report = {
            "mode": "corrupt",
            "server_engine": args.server_engine,
            "client_engine": args.client_engine,
            "cycles": args.cycles,
            "ops": total,
            "elapsed_s": round(time.monotonic() - t0, 3),
            "recvs_completed": ss["recvs_completed"],
            "flips_injected": proxy.corrupted_units,
            "csum_fail": detected,
            "chunk_retx": retx,
            "sessions_resumed": cs["sessions_resumed"] + ss["sessions_resumed"],
            "stall_alerts": cs["stall_alerts"] + ss["stall_alerts"],
        }
        # The inadmissible outcome is SILENT corruption -- pinned by the
        # byte-exact payload asserts above.  Detection counts are
        # evidence the plane is live (>=1; a flip whose frame died with
        # a killed conn is legitimately never completed, so flips and
        # detections need not match 1:1 under mixed kills), and resumes
        # prove the kills were ridden out.
        ok = (ss["recvs_completed"] == total
              and proxy.corrupted_units >= 1
              and detected >= 1
              and retx >= 1
              and report["sessions_resumed"] >= 1
              and report["stall_alerts"] == 0)
        ok = _monitor_check(report) and ok
        report["ok"] = ok
        print(json.dumps(report))
        return 0 if ok else 1
    finally:
        for obj in (client, server):
            try:
                await asyncio.wait_for(obj.aclose(), timeout=10)
            except Exception:
                pass
        proxy.stop()


async def _overload(args) -> int:
    """Many-client overload soak (ISSUE 9 satellite): dozens of client
    workers flood ONE server through per-client FaultProxies with the §18
    credit window armed; every --slow-every'th client's receives post
    late (slow consumer), and each cycle kills a rotating subset of
    connections mid-burst.  Oracle: every op completes exactly once
    (recvs_completed == posted), resumes cover the kills, and the
    telemetry samples never show unexpected-queue residency above
    clients x window -- bounded, not OOM."""
    os.environ["STARWAY_TLS"] = "tcp"
    os.environ["STARWAY_SESSION"] = "1"
    os.environ.setdefault("STARWAY_SESSION_GRACE", "30")
    os.environ["STARWAY_FC_WINDOW"] = str(args.fc_window)
    os.environ.setdefault("STARWAY_METRICS_INTERVAL", "0.25")
    os.environ.setdefault("STARWAY_STALL_MS", "5000")  # §25 liveness oracle

    import random
    import socket

    import numpy as np

    from starway_tpu import Client, Server
    from starway_tpu.core import telemetry
    from starway_tpu.testing.faults import FaultProxy

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    os.environ["STARWAY_NATIVE"] = "1" if args.server_engine == "native" else "0"
    server = Server()
    server.listen("127.0.0.1", port)
    os.environ["STARWAY_NATIVE"] = "1" if args.client_engine == "native" else "0"
    proxies = [FaultProxy("127.0.0.1", port).start()
               for _ in range(args.clients)]
    clients = []
    for p in proxies:
        c = Client()
        await c.aconnect("127.0.0.1", p.port)
        clients.append(c)

    rng = random.Random(0xC0FFEE)
    total = 0
    kills = 0
    peak_unexp = 0
    t0 = time.monotonic()
    try:
        for cycle in range(args.cycles):
            n, size = args.n, args.size
            sends = []
            recvs = []
            bufs = []
            for ci, c in enumerate(clients):
                tag0 = (cycle * len(clients) + ci) * 1000
                for i in range(n):
                    sends.append(c.asend(
                        np.full(size, (tag0 + i) % 251, dtype=np.uint8),
                        tag0 + i))

                async def post_recvs(ci=ci, tag0=tag0):
                    if args.slow_every and ci % args.slow_every == 0:
                        await asyncio.sleep(0.5)  # the slow consumer
                    for i in range(n):
                        buf = np.zeros(size, dtype=np.uint8)
                        bufs.append((tag0 + i, buf))
                        recvs.append(server.arecv(buf, tag0 + i,
                                                  (1 << 64) - 1))

                asyncio.ensure_future(post_recvs())
            await asyncio.sleep(0.1)
            for p in rng.sample(proxies, max(1, len(proxies) // 3)):
                p.kill_all(rst=True)  # the periodic mid-burst kill
                kills += 1
            await asyncio.wait_for(asyncio.gather(*sends), timeout=120)
            for _ in range(200):
                if len(recvs) == len(clients) * n:
                    break
                await asyncio.sleep(0.05)
            res = await asyncio.wait_for(asyncio.gather(*recvs), timeout=120)
            assert len(res) == len(clients) * n
            for tag, buf in bufs:
                assert buf[0] == tag % 251 and buf[-1] == tag % 251, tag
            total += len(res)
            sample = telemetry.sample_now()
            for wk in sample.get("workers", {}).values():
                for g in wk.get("gauges", {}).get("conns", {}).values():
                    peak_unexp = max(peak_unexp, g.get("unexp_bytes", 0))
            _print_live(cycle, total, sample)

        await asyncio.wait_for(
            asyncio.gather(*(c.aflush() for c in clients)), timeout=120)
        ss = server._server.counters_snapshot()
        resumes = ss["sessions_resumed"] + sum(
            c._client.counters_snapshot()["sessions_resumed"]
            for c in clients)
        parked = sum(c._client.counters_snapshot()["sends_parked"]
                     for c in clients)
        bound = args.fc_window  # per-conn bound: the §18 window
        report = {
            "mode": "overload",
            "server_engine": args.server_engine,
            "client_engine": args.client_engine,
            "clients": args.clients,
            "cycles": args.cycles,
            "ops": total,
            "kills": kills,
            "elapsed_s": round(time.monotonic() - t0, 3),
            "recvs_completed": ss["recvs_completed"],
            "sessions_resumed": resumes,
            "sends_parked": parked,
            "peak_unexp_bytes": peak_unexp,
            "unexp_bound": bound,
            "stall_alerts": ss["stall_alerts"] + sum(
                c._client.counters_snapshot()["stall_alerts"]
                for c in clients),
        }
        ok = (ss["recvs_completed"] == total and resumes >= 1
              and peak_unexp <= bound
              and report["stall_alerts"] == 0)
        ok = _monitor_check(report) and ok
        report["ok"] = ok
        print(json.dumps(report))
        return 0 if ok else 1
    finally:
        for obj in clients + [server]:
            try:
                await asyncio.wait_for(obj.aclose(), timeout=10)
            except Exception:
                pass
        for p in proxies:
            p.stop()


if __name__ == "__main__":
    _args = _parse()
    if _args.corrupt:
        sys.exit(asyncio.run(_corrupt_soak(_args)))
    sys.exit(asyncio.run(_overload(_args) if _args.overload
                         else _main(_args)))
