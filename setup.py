"""Wheel tagging shim.  All metadata lives in pyproject.toml.

The native engine is a ctypes-loaded shared object, not a CPython
extension module, so setuptools would tag the wheel py3-none-any even
when ``starway_tpu/_sw_native.so`` is bundled — and auditwheel refuses to
repair/verify a pure wheel.  Declaring binary content when the artifact
is present makes cibuildwheel's builds come out platform-tagged (then
manylinux-tagged by auditwheel), while a source build without the engine
still produces the honest pure-Python wheel.
"""

from pathlib import Path

from setuptools import setup
from setuptools.dist import Distribution


class _MaybeBinaryDistribution(Distribution):
    def has_ext_modules(self):
        return (Path(__file__).parent / "starway_tpu"
                / "_sw_native.so").exists()


setup(distclass=_MaybeBinaryDistribution)
