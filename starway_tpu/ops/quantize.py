"""Symmetric int8 quantization for the KV cache.

Single-token decode streams the whole KV cache through the core once per
generated token — it is HBM-bandwidth-bound (BASELINE.md: the bf16 decode
kernel runs at ~390 GB/s effective), so halving the cache's bytes is worth
~2x on the decode step and doubles the context a chip can serve.  The
scheme is the standard serving-stack one (per-token, per-head symmetric
int8): each cached [head_dim] vector x is stored as

    q = round(x / s),  s = max(|x|) / 127        (s in f32, q in int8)

Dequantization never materialises a wide cache in HBM or VMEM: the decode
kernel streams int8 blocks, folds ``k``'s scale into the score columns
(``(q . k_int8) * s_k``) and ``v``'s scale into the softmax weights before
the ``p @ v`` matmul (ops/pallas_decode.py) — the operands widen to the
compute dtype only inside the matmul itself, so the bandwidth-bound part
(the HBM/VMEM stream) stays at half width.  Accuracy: worst-case
per-element error is ``s/2 = amax/254`` (~0.4% of the vector's max); the
f32 softmax chain is unchanged.

No reference counterpart (/root/reference is a transport library); this is
the TPU build's own serving-stack extension, following the public KV-cache
quantization recipe used by mainstream inference engines.
"""

from __future__ import annotations

import jax.numpy as jnp

INT8_MAX = 127.0


def quantize_kv(x):
    """Quantize along the last axis: ``x [..., D]`` -> ``(q int8 [..., D],
    scale f32 [...])`` with ``x ~= q * scale[..., None]``.

    All-zero vectors (e.g. the cache's zero-initialised / padded slots) get
    scale 0 and quantize to zeros — dequantization returns exact zeros, so
    padding stays inert.
    """
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = amax / INT8_MAX
    # Avoid 0/0 on all-zero vectors; where scale == 0 the numerator is 0 too.
    div = jnp.where(scale > 0.0, scale, 1.0)[..., None]
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / div), -INT8_MAX, INT8_MAX)
    return q.astype(jnp.int8), scale


def dequantize_kv(q, scale, dtype=jnp.bfloat16):
    """Inverse of :func:`quantize_kv` (up to rounding): ``q int8 [..., D]``
    times ``scale [...]`` broadcast over the last axis."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def quantize_weight(w):
    """Weight-only int8 (W8A16), symmetric per-OUTPUT-channel: ``w [...,
    D, F]`` -> ``{"q": int8 same shape, "s": f32 [..., F]}`` with
    ``w ~= q * s`` broadcast over rows.  Per-out-channel scales commute
    with the matmul (``(x @ q) * s == x @ (q * s)``), so dequantization
    folds into the PRODUCT — the weight stream stays int8 end to end
    (ops/pallas_gemv.py).  Leading axes (the stacked-layer dim) are
    batch dims of the scheme."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2)
    scale = amax / INT8_MAX
    div = jnp.where(scale > 0.0, scale, 1.0)[..., None, :]
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / div), -INT8_MAX, INT8_MAX)
    return {"q": q.astype(jnp.int8), "s": scale}


# The matmul weights of the Llama tree (models/llama.py:init_params):
# everything consumed as ``x @ w``.  embed stays wide (it is a GATHER,
# not a matmul — rows leave one at a time); norms are vectors.
_MATMUL_LEAVES = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_params(params: dict):
    """Weight-only int8 serving tree: every matmul weight of a (dense)
    Llama parameter tree becomes a ``{"q", "s"}`` pair; embed, norms,
    and anything unrecognised stay untouched.  At batch-1 decode the
    weight stream is the dominant HBM bill (~2 bytes/param/token in
    bf16), so int8 weights are worth ~2x on the MLP-dominated share and
    halve weight memory.  The returned tree is INFERENCE-ONLY — it flows
    through forward/prefill/decode/serving/speculative via
    models/llama.py:matmul_w, but optimizers and the training step
    expect raw arrays.  MoE trees are refused (expert weights route
    through their own dispatch; not wired)."""
    layers = params["layers"]
    if "moe" in layers:
        raise NotImplementedError(
            "quantize_params covers dense models; MoE expert weights are "
            "not wired for weight-only int8 yet")
    new_layers = dict(layers)
    for name in _MATMUL_LEAVES:
        if name in new_layers:
            new_layers[name] = quantize_weight(new_layers[name])
    out = dict(params)
    out["layers"] = new_layers
    out["lm_head"] = quantize_weight(params["lm_head"])
    return out
