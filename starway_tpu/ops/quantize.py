"""Symmetric int8 quantization for the KV cache.

Single-token decode streams the whole KV cache through the core once per
generated token — it is HBM-bandwidth-bound (BASELINE.md: the bf16 decode
kernel runs at ~390 GB/s effective), so halving the cache's bytes is worth
~2x on the decode step and doubles the context a chip can serve.  The
scheme is the standard serving-stack one (per-token, per-head symmetric
int8): each cached [head_dim] vector x is stored as

    q = round(x / s),  s = max(|x|) / 127        (s in f32, q in int8)

Dequantization never materialises a wide cache in HBM or VMEM: the decode
kernel streams int8 blocks, folds ``k``'s scale into the score columns
(``(q . k_int8) * s_k``) and ``v``'s scale into the softmax weights before
the ``p @ v`` matmul (ops/pallas_decode.py) — the operands widen to the
compute dtype only inside the matmul itself, so the bandwidth-bound part
(the HBM/VMEM stream) stays at half width.  Accuracy: worst-case
per-element error is ``s/2 = amax/254`` (~0.4% of the vector's max); the
f32 softmax chain is unchanged.

No reference counterpart (/root/reference is a transport library); this is
the TPU build's own serving-stack extension, following the public KV-cache
quantization recipe used by mainstream inference engines.
"""

from __future__ import annotations

import jax.numpy as jnp

INT8_MAX = 127.0


def quantize_kv(x):
    """Quantize along the last axis: ``x [..., D]`` -> ``(q int8 [..., D],
    scale f32 [...])`` with ``x ~= q * scale[..., None]``.

    All-zero vectors (e.g. the cache's zero-initialised / padded slots) get
    scale 0 and quantize to zeros — dequantization returns exact zeros, so
    padding stays inert.
    """
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = amax / INT8_MAX
    # Avoid 0/0 on all-zero vectors; where scale == 0 the numerator is 0 too.
    div = jnp.where(scale > 0.0, scale, 1.0)[..., None]
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / div), -INT8_MAX, INT8_MAX)
    return q.astype(jnp.int8), scale


def dequantize_kv(q, scale, dtype=jnp.bfloat16):
    """Inverse of :func:`quantize_kv` (up to rounding): ``q int8 [..., D]``
    times ``scale [...]`` broadcast over the last axis."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)
