"""Paged KV-cache decode attention (vLLM-style block tables, TPU-first).

The dense serving cache ``[L, n_slots, Hkv, max_len, D]`` reserves
``max_len`` positions per slot whether a request uses them or not; real
workloads mix short and long requests, so most of that HBM is dead.
Paging shares one POOL of fixed-size pages across all slots:

* pool:  ``k/v [n_pages, Hkv, page, D]`` — the only large allocation;
  sized by expected TOTAL live tokens, not slots x max_len;
* table: ``[n_slots, max_pages] int32`` page ids per slot (host-managed
  free list, models/paged.py);
* decode reads the pages through the table with NO materialisation of a
  dense view — the indirection lives in the kernel's DMA stream.

The kernel is the stream decode kernel's structure (pallas_decode.py:
one grid cell per (slot, kv head), whole-cache sweep as a fori_loop with
double-buffered manual ``make_async_copy``) with one change: block i's
DMA source is ``pool.at[table[slot, i], head]`` instead of a contiguous
``cache.at[slot*hkv+head, i*block]`` slice.  Page id and cursor ride the
scalar-prefetch operand (SMEM), so the address is known when the copy
starts — the pipeline still overlaps compute on page i with the stream
of page i+1, and pages past the cursor are never fetched.  Bandwidth per
decoded token is identical to the dense stream kernel: the pool pages
the slot actually owns, once, narrow (grouped heads, no repeat_kv).

Same online-softmax block body as the dense kernels
(``_softmax_block_update``); numerics pinned against the dense oracle in
tests/test_paged.py.  int8 pools are not wired yet (the dense kernel's
quant path shows the shape; refused loudly below).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import NEG_BIG
from .pallas_attention import _round_up
from .pallas_decode import _row_offsets, _softmax_block_update


def _paged_stream_kernel(meta_ref, q_ref, k_pool, v_pool, o_ref, k_buf,
                         v_buf, sems, m_scr, l_scr, acc_scr, *,
                         sm_scale: float, page: int, hkv: int,
                         max_pages: int, n_q: int):
    """One grid cell per (slot, kv head); fori_loop over the slot's pages
    with double-buffered DMA through the block table.

    ``meta_ref`` (scalar prefetch, SMEM): ``[n_slots, 1 + max_pages]`` —
    column 0 is the slot's cursor, columns 1.. its page ids."""
    bh = pl.program_id(0)
    b = bh // hkv
    h = jax.lax.rem(bh, hkv)
    pos = meta_ref[b, 0]
    hi = (pos + n_q - 1) // page  # last live page (queries span n_q)

    def copies(i, slot):
        pid = meta_ref[b, 1 + i]
        return [
            pltpu.make_async_copy(
                k_pool.at[pid, h], k_buf.at[slot], sems.at[slot, 0]),
            pltpu.make_async_copy(
                v_pool.at[pid, h], v_buf.at[slot], sems.at[slot, 1]),
        ]

    m_scr[:] = jnp.full_like(m_scr, NEG_BIG)
    l_scr[:] = jnp.zeros_like(l_scr)
    acc_scr[:] = jnp.zeros_like(acc_scr)
    for cp in copies(0, 0):
        cp.start()
    q = q_ref[0]  # [rows, D]

    def body(i, _):
        live = i <= hi

        @pl.when(live)
        def _live():
            slot = jax.lax.rem(i, 2)

            @pl.when(i + 1 <= hi)
            def _prefetch():
                for cp in copies(i + 1, jax.lax.rem(i + 1, 2)):
                    cp.start()

            for cp in copies(i, slot):
                cp.wait()
            _softmax_block_update(
                q, k_buf[slot], v_buf[slot], i * page, pos, m_scr, l_scr,
                acc_scr, sm_scale=sm_scale, window=None,
                row_off=_row_offsets(q.shape[0], n_q))

        return 0

    jax.lax.fori_loop(0, max_pages, body, 0)
    o_ref[0] = (acc_scr[:] / jnp.maximum(l_scr[:, :1], 1e-30)).astype(o_ref.dtype)


def paged_decode_attention(q, k_pool, v_pool, table, pos, *, sm_scale=None,
                           interpret=None):
    """Decode attention over a paged KV pool.

    q: ``[B, Hq, C, D]`` (C consecutive query positions per slot, like
    the dense kernel — C=1 is plain decode).  k_pool/v_pool:
    ``[n_pages, Hkv, page, D]``; table: ``[B, max_pages] int32`` (page i
    of slot b holds positions ``i*page .. (i+1)*page - 1``; ids past the
    cursor may be anything — they are never fetched); pos: scalar or
    ``[B]`` cursors.  Returns ``[B, Hq, C, D]``, numerically matching
    the dense :func:`~starway_tpu.ops.pallas_decode.decode_attention`
    over the gathered logical cache (tests/test_paged.py).
    """
    if k_pool.dtype == jnp.int8 or v_pool.dtype == jnp.int8:
        raise NotImplementedError(
            "int8 paged pools are not wired yet; serve int8 caches "
            "through the dense kernel (ops/pallas_decode.py)")
    b, hq, n_q, d = q.shape
    n_pages_total, hkv, page, _ = k_pool.shape
    max_pages = table.shape[1]
    n_rep = hq // hkv
    if sm_scale is None:
        sm_scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    n_rows = n_rep * n_q
    rows = _round_up(max(n_rows, 8), 8)
    qg = q.reshape(b, hkv, n_rows, d)
    if rows != n_rows:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, rows - n_rows), (0, 0)))
    qf = qg.reshape(b * hkv, rows, d)

    pos_arr = jnp.broadcast_to(jnp.asarray(pos, jnp.int32).reshape(-1), (b,))
    meta = jnp.concatenate([pos_arr[:, None], table.astype(jnp.int32)],
                           axis=1)

    any_spec = pl.BlockSpec(memory_space=pltpu.MemorySpace.ANY)
    out = pl.pallas_call(
        functools.partial(
            _paged_stream_kernel, sm_scale=sm_scale, page=page, hkv=hkv,
            max_pages=max_pages, n_q=n_q),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b * hkv,),
            in_specs=[
                pl.BlockSpec((1, rows, d), lambda bh, meta_ref: (bh, 0, 0)),
                any_spec,
                any_spec,
            ],
            out_specs=pl.BlockSpec((1, rows, d),
                                   lambda bh, meta_ref: (bh, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((2, page, d), k_pool.dtype),
                pltpu.VMEM((2, page, d), v_pool.dtype),
                pltpu.SemaphoreType.DMA((2, 2)),
                pltpu.VMEM((rows, 128), jnp.float32),
                pltpu.VMEM((rows, 128), jnp.float32),
                pltpu.VMEM((rows, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b * hkv, rows, d), q.dtype),
        interpret=interpret,
    )(meta, qf, k_pool, v_pool)
    return out.reshape(b, hkv, rows, d)[:, :, :n_rows, :].reshape(
        b, hq, n_q, d)


def gather_logical(pool, table):
    """Dense view of each slot's logical cache (TEST/ORACLE use only —
    materialising this is exactly what the kernel avoids): pool
    ``[n_pages, Hkv, page, D]`` + table ``[B, max_pages]`` ->
    ``[B, Hkv, max_pages*page, D]``."""
    g = pool[table]  # [B, max_pages, Hkv, page, D]
    b, mp, hkv, page, d = g.shape
    return g.transpose(0, 2, 1, 3, 4).reshape(b, hkv, mp * page, d)
