"""Attention building blocks: online-softmax partials for blockwise and ring
attention.

All functions are pure jax/lax (compiler-friendly static shapes, scan-based
control flow) so they run identically on the virtual CPU mesh and on TPU,
where XLA fuses the softmax chain and tiles the matmuls onto the MXU.  A
hand-tuned pallas kernel for the block partial lands behind the same
interface (ops/pallas_attention.py).

Layout convention: ``q, k, v: [batch, heads, seq, head_dim]``.

The decomposition is the standard flash/ring-attention algebra: a block
produces an *unnormalised* output ``o = exp(s - m) @ v`` with row statistics
``(m = rowmax(s), l = rowsum(exp(s - m)))``; partials merge associatively
with :func:`merge_partials`, which is what lets kv blocks arrive in any
order around the ICI ring (parallel/ring_attention.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_BIG = -0.9e30  # mask fill; avoids -inf NaN traps in exp/max chains


def repeat_kv(x, n_rep: int):
    """Expand grouped KV heads to match query heads (GQA)."""
    if n_rep == 1:
        return x
    b, h, t, d = x.shape
    return jnp.broadcast_to(x[:, :, None, :, :], (b, h, n_rep, t, d)).reshape(b, h * n_rep, t, d)


def partial_attention(
    q,
    k,
    v,
    *,
    q_offset=0,
    kv_offset=0,
    causal: bool = False,
    kv_limit: Optional[int] = None,
    sm_scale: Optional[float] = None,
    window: Optional[int] = None,
    kv_min: Optional[int] = None,
):
    """Attention of ``q`` against one kv block, in mergeable partial form.

    Returns ``(o, m, l)``: unnormalised output ``[B,H,Tq,D]``, row max
    ``[B,H,Tq]``, row sum ``[B,H,Tq]``.  ``q_offset``/``kv_offset`` are the
    global positions of the first query/key token -- the causal mask is
    computed in global coordinates so blocks can come from anywhere in the
    sequence (ring steps pass traced offsets).  ``kv_limit`` masks key
    positions at or beyond that global index (padding); ``kv_min`` masks
    positions below it (a cold rolling cache holds no keys before 0).
    ``window`` (requires ``causal``) keeps only the last ``window`` keys
    per query: ``kv_pos in (q_pos - window, q_pos]`` (Mistral-style
    sliding window).
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    # Scores and row stats in f32 (MXU takes bf16 inputs, accumulates f32).
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * sm_scale
    kv_pos = kv_offset + jnp.arange(k.shape[2])
    mask = jnp.ones((q.shape[2], k.shape[2]), dtype=bool)
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[2])
        mask = mask & (q_pos[:, None] >= kv_pos[None, :])
        if window is not None:
            mask = mask & (kv_pos[None, :] > q_pos[:, None] - window)
    elif window is not None:
        raise ValueError("window requires causal attention")
    if kv_limit is not None:
        mask = mask & (kv_pos < kv_limit)[None, :]
    if kv_min is not None:
        mask = mask & (kv_pos >= kv_min)[None, :]
    s = jnp.where(mask[None, None, :, :], s, NEG_BIG)
    m = jnp.max(s, axis=-1)
    # Rows with no visible keys: exp(s - m) would be exp(0)=1; zero them.
    p = jnp.where(s > NEG_BIG / 2, jnp.exp(s - m[..., None]), 0.0)
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v, preferred_element_type=jnp.float32
    )
    return o, m, l


def merge_partials(a, b):
    """Associatively merge two attention partials over the same queries."""
    o_a, m_a, l_a = a
    o_b, m_b, l_b = b
    m = jnp.maximum(m_a, m_b)
    sa = jnp.exp(m_a - m)
    sb = jnp.exp(m_b - m)
    l = l_a * sa + l_b * sb
    o = o_a * sa[..., None].astype(o_a.dtype) + o_b * sb[..., None].astype(o_b.dtype)
    return o, m, l


def zero_partial(q):
    """Identity element for merge_partials over queries shaped like ``q``.
    Accumulators are f32 regardless of compute dtype."""
    b, h, tq, d = q.shape
    return (
        jnp.zeros((b, h, tq, d), dtype=jnp.float32),
        jnp.full((b, h, tq), NEG_BIG, dtype=jnp.float32),
        jnp.zeros((b, h, tq), dtype=jnp.float32),
    )


def finalize_partial(o, m, l, out_dtype=None):
    """Normalise a merged partial into the attention output."""
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(out_dtype) if out_dtype is not None else out


def blockwise_attention(
    q,
    k,
    v,
    *,
    causal: bool = False,
    block_k: int = 512,
    sm_scale: Optional[float] = None,
    window: Optional[int] = None,
):
    """Single-device flash-style attention: scan over kv blocks with the
    online-softmax merge, never materialising the full [Tq, Tkv] matrix.
    Grouped-query kv (fewer kv heads than q heads) is expanded here.
    ``window``: sliding-window causal (see :func:`partial_attention`)."""
    if k.shape[1] != q.shape[1]:
        n_rep = q.shape[1] // k.shape[1]
        k = repeat_kv(k, n_rep)
        v = repeat_kv(v, n_rep)
    b, h, tq, d = q.shape
    tkv = k.shape[2]
    block_k = min(block_k, tkv)
    nblocks = (tkv + block_k - 1) // block_k
    pad = nblocks * block_k - tkv
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kb = k.reshape(b, h, nblocks, block_k, d).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(b, h, nblocks, block_k, d).transpose(2, 0, 1, 3, 4)
    offs = jnp.arange(nblocks) * block_k

    def step(carry, blk):
        k_i, v_i, off = blk
        part = partial_attention(
            q, k_i, v_i,
            q_offset=0, kv_offset=off,
            causal=causal, kv_limit=tkv if pad else None, sm_scale=sm_scale,
            window=window,
        )
        return merge_partials(carry, part), None

    (o, m, l), _ = jax.lax.scan(step, zero_partial(q), (kb, vb, offs))
    return finalize_partial(o, m, l, out_dtype=q.dtype)


def attention_reference(q, k, v, *, causal: bool = False,
                        sm_scale: Optional[float] = None,
                        window: Optional[int] = None):
    """Plain materialised-softmax attention (test oracle)."""
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * sm_scale
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    if causal:
        tq, tkv = q.shape[2], k.shape[2]
        qp = jnp.arange(tq)[:, None]
        kp = jnp.arange(tkv)[None, :]
        mask = qp >= kp
        if window is not None:
            mask = mask & (kp > qp - window)
        s = jnp.where(mask[None, None, :, :], s, NEG_BIG)
    elif window is not None:
        raise ValueError("window requires causal attention")
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)
