"""Pallas TPU int8-weight matmul: the W8A16 serving hot path.

Single-token decode at small batch is WEIGHT-bandwidth bound: every
generated token streams every matmul weight of the model through the core
once (~2 bytes/param in bf16).  This kernel streams the weights as int8 —
half the bytes — and folds the per-output-channel dequantisation scale
into the product after the MXU matmul (``(x @ q) * s == x @ (q * s)``,
ops/quantize.py:quantize_weight), so no wide weight tile ever exists in
VMEM or HBM.

Left operand ``x [M, D]`` is small (M = batch x chunk rows) and rides
whole; the grid walks output-channel blocks, and Pallas's pipeline
double-buffers the int8 weight DMA exactly like any blocked matmul — the
structural point is only that the streamed operand is int8 while the MXU
consumes the activation dtype.

No reference counterpart (/root/reference is a transport library); this is
the TPU build's serving-stack extension implementing standard weight-only
quantization.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .pallas_attention import _round_up


def _gemv_kernel(x_ref, w_ref, s_ref, o_ref):
    x = x_ref[...]
    w = w_ref[...].astype(x.dtype)  # widen in-register, post-DMA
    acc = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    o_ref[...] = (acc * s_ref[0][None, :]).astype(o_ref.dtype)


def int8_matmul(x, wq, scale, *, block_f: "int | None" = None,
                interpret=None, out_dtype=None):
    """``x [M, D] @ (wq int8 [D, F] * scale f32 [F]) -> [M, F]``.

    Matches ``(x @ wq.astype(f32)) * scale`` up to float rounding (f32
    accumulate on the MXU).  ``block_f`` tunes the output-channel block
    (default sized so a double-buffered int8 [D, block_f] tile stays
    within a few MB of VMEM).  M is padded to the 8-sublane tile, F to
    the block; both paddings are sliced off.
    """
    m, d = x.shape
    d2, f = wq.shape
    assert d == d2 and scale.shape == (f,)
    if out_dtype is None:
        out_dtype = x.dtype
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    f128 = _round_up(f, 128)
    if block_f is None:
        # ~4 MB of int8 weight block per buffer, lane-aligned.
        block_f = max(128, min(512, ((4 << 20) // max(d, 1)) // 128 * 128))
    # The block must DIVIDE the padded width: padding to a 512-multiple
    # would copy the whole weight inside the traced hot path whenever f
    # is merely 128-aligned (e.g. a 128256 vocab head) — fall down the
    # lane-multiple ladder instead, so the pad stays <= 127 columns.
    block_f = min(block_f, f128)
    while f128 % block_f:
        block_f -= 128
    m_pad = _round_up(max(m, 8), 8)
    if m_pad != m:
        x = jnp.pad(x, ((0, m_pad - m), (0, 0)))
    f_pad = f128
    if f_pad != f:
        wq = jnp.pad(wq, ((0, 0), (0, f_pad - f)))
        scale = jnp.pad(scale, (0, f_pad - f))
    scale2 = scale.reshape(1, f_pad)  # rank-2 for the TPU lane layout

    out = pl.pallas_call(
        _gemv_kernel,
        grid=(f_pad // block_f,),
        in_specs=[
            pl.BlockSpec((m_pad, d), lambda i: (0, 0)),
            pl.BlockSpec((d, block_f), lambda i: (0, i)),
            pl.BlockSpec((1, block_f), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((m_pad, block_f), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((m_pad, f_pad), out_dtype),
        interpret=interpret,
    )(x, wq, scale2)
    return out[:m, :f]
