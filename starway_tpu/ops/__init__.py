"""Jitted device-plane building blocks: collectives and attention kernels.

This is the SPMD-native layer of the framework: where the host runtime
(core/) moves opaque tagged buffers between workers, these ops move sharded
``jax.Array`` data across a ``jax.sharding.Mesh`` with XLA collectives over
ICI -- the idiomatic TPU equivalent of composing transfers from the
reference's P2P primitives (SURVEY.md section 5 "Long-context / sequence
parallelism": "ring attention = asend/arecv to ring neighbors + overlap,
i.e. CollectivePermute; Ulysses = all-to-all composed from P2P").
"""

from .collectives import (
    all_gather,
    all_to_all,
    psum,
    reduce_scatter,
    ring_shift,
)
from .quantize import quantize_params

__all__ = ["ring_shift", "all_to_all", "all_gather", "psum",
           "reduce_scatter", "quantize_params"]
