"""Pallas TPU decode-attention kernel for KV-cache inference.

Single-token decode is HBM-bandwidth bound: the whole KV cache streams
through the core once per generated token.  The lax path
(models/generate.py:_attend_cached) materialises ``repeat_kv`` — expanding
the grouped cache ``n_rep``× before the einsum — so a GQA model reads (and
first writes) n_rep times more HBM than the cache actually holds.  This
kernel keeps the cache narrow: the grid walks ``(batch*kv_head, kv_block)``,
loads each cache block exactly once, and attends all ``n_rep`` query heads
of the group against it as the rows of one MXU matmul.  Masking and the
online-softmax accumulation are fused; fully-masked blocks (beyond the
current position) are skipped via scalar-prefetched ``pos``.

Two variants share the same online-softmax block body:

* **stream** (default): one grid cell per (batch, kv head); the whole T
  sweep is a ``fori_loop`` with double-buffered manual DMA
  (``make_async_copy``) — compute on block i overlaps the HBM stream of
  block i+1, and the per-cell pipeline cost is paid b*hkv times total,
  independent of T.  Structural response to the r2 measurement below.
* **grid** (``stream=False``): one grid cell per kv block, Pallas-pipelined.
  Decode is bandwidth-bound with a ~0.4 µs fixed cost per grid cell, so
  small blocks drown in cell overhead (measured r2: block 128 at T=8192 =
  128 cells ≈ 51 µs of overhead on a 60.8 µs total — slower than the lax
  path); block 512 quarters the cell count.

``bench.py --kernels decode_tune`` sweeps both variants x block sizes on
real hardware; the stream default is the structural bet until the chip
confirms it.

Same online-softmax algebra as ops/pallas_attention.py; layouts follow
models/generate.py: ``q [B, Hq, 1, D]``, caches ``[B, Hkv, T, D]``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import NEG_BIG
from .pallas_attention import _round_up


def _softmax_block_update(q, k, v, k_start, pos, m_scr, l_scr, acc_scr, *,
                          sm_scale: float, window: "int | None",
                          k_scale=None, v_scale=None, row_off=None):
    """The one online-softmax block body both kernel variants share: score
    the group's query rows against one [block_k, D] cache block, mask by
    global position (and window), and fold into the m/l/acc scratches.

    ``row_off`` ([rows, 1] int32 — rank-2, Mosaic rejects rank-1 iota;
    multi-query decode): row r's query sits at global position
    ``pos + row_off[r, 0]`` — the speculative chunk verify packs C chunk
    positions x n_rep query heads as the matmul rows, so each row masks
    by its own cursor.  ``None`` = all rows at ``pos``.

    ``k_scale``/``v_scale`` ([block_k] f32, int8 cache): dequantization is
    folded into the existing algebra instead of widening the operands —
    k's scale multiplies the score COLUMNS (``(q . k_int8[c]) * s_k[c]``)
    and v's scale folds into the softmax weights before the ``p @ v``
    matmul, so no dequantized [block_k, D] tile is ever materialised."""
    s = jax.lax.dot_general(
        q, k.astype(q.dtype), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [rows, block_k]
    if k_scale is not None:
        s = s * (k_scale[None, :] * sm_scale)
    else:
        s = s * sm_scale
    kv_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    q_pos = pos if row_off is None else pos + row_off  # [rows, 1]
    keep = kv_pos <= q_pos
    if window is not None:
        keep = keep & (kv_pos > q_pos - window)
    s = jnp.where(keep, s, NEG_BIG)

    m_prev = m_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    p = jnp.where(s > NEG_BIG / 2, jnp.exp(s - m_new), 0.0)
    corr = jnp.exp(m_prev - m_new)
    l_new = l_scr[:, :1] * corr + jnp.sum(p, axis=1, keepdims=True)
    pv_dtype = q.dtype
    if v_scale is not None:
        p = p * v_scale[None, :]
    acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
        p.astype(pv_dtype), v.astype(pv_dtype), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)


def _row_offsets(rows: int, n_q: int):
    """Row r's query-position offset in the packed [n_rep, C] row layout
    (r = rep * C + ci -> offset ci), shaped [rows, 1] (rank-2: Mosaic
    rejects rank-1 iota); None when single-position."""
    if n_q == 1:
        return None
    return jax.lax.rem(
        jax.lax.broadcasted_iota(jnp.int32, (rows, 1), 0), n_q)


def _decode_kernel(pos_ref, q_ref, k_ref, v_ref, *refs, sm_scale: float,
                   block_k: int, hkv: int, window: "int | None",
                   quant: bool = False, n_q: int = 1):
    if quant:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = refs
    else:
        ks_ref = vs_ref = None
        o_ref, m_scr, l_scr, acc_scr = refs
    ki = pl.program_id(1)
    n_k = pl.num_programs(1)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_BIG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Per-ROW positions (ragged batches): this grid cell serves batch row
    # bh // hkv, whose own cursor bounds both masking and the DMA clamp.
    # Multi-query (n_q > 1): queries span pos .. pos + n_q - 1.
    pos = pos_ref[pl.program_id(0) // hkv]
    k_start = ki * block_k

    live = k_start <= pos + (n_q - 1)
    if window is not None:
        # Sliding window: this block must overlap (pos - window,
        # pos + n_q - 1] (the union of every query's band).
        live = live & (k_start + block_k - 1 > pos - window)

    @pl.when(live)
    def _body():
        _softmax_block_update(
            q_ref[0], k_ref[0], v_ref[0], k_start, pos, m_scr, l_scr,
            acc_scr, sm_scale=sm_scale, window=window,
            k_scale=None if ks_ref is None else ks_ref[0],
            v_scale=None if vs_ref is None else vs_ref[0],
            row_off=_row_offsets(q_ref.shape[1], n_q))

    @pl.when(ki == n_k - 1)
    def _finalize():
        o_ref[0] = (acc_scr[:] / jnp.maximum(l_scr[:, :1], 1e-30)).astype(o_ref.dtype)


def _decode_stream_kernel(pos_ref, q_ref, k_hbm, v_hbm, *refs,
                          sm_scale: float, block_k: int, hkv: int,
                          window: "int | None", n_blocks: int,
                          quant: bool = False, n_q: int = 1):
    """One grid cell per (batch, kv head): the WHOLE cache sweep runs in a
    single cell as a fori_loop over kv blocks with double-buffered manual
    DMA (compute on block i overlaps the HBM stream of block i+1).

    Rationale: the grid kernel pays a fixed ~0.4 us pipeline cost per cell
    (measured r2: 64 cells at block 128 ~= 51 us of a 60.8 us total — slower
    than the lax path).  Here the cell count is b*hkv regardless of T, so
    the overhead term is gone and the kernel's time is the max of the DMA
    stream (~cache bytes / HBM bandwidth) and the (tiny) grouped-GQA
    matmuls.

    ``quant``: two extra HBM inputs (per-token f32 scales) and two extra
    scratch buffers ride the same double-buffered pipeline; the int8 cache
    blocks halve the DMA bytes (the scales add 1/(2*D) back).
    """
    if quant:
        (ks_hbm, vs_hbm, o_ref, k_buf, v_buf, ks_buf, vs_buf, sems, m_scr,
         l_scr, acc_scr) = refs
    else:
        ks_hbm = vs_hbm = ks_buf = vs_buf = None
        o_ref, k_buf, v_buf, sems, m_scr, l_scr, acc_scr = refs
    bh = pl.program_id(0)
    pos = pos_ref[bh // hkv]
    hi = (pos + n_q - 1) // block_k  # last live block (queries span n_q)
    if window is None:
        lo = jnp.int32(0)
    else:
        lo = jnp.maximum(pos - window + 1, 0) // block_k

    def copies(i, slot):
        cps = [
            pltpu.make_async_copy(
                k_hbm.at[bh, pl.ds(i * block_k, block_k)], k_buf.at[slot],
                sems.at[slot, 0]),
            pltpu.make_async_copy(
                v_hbm.at[bh, pl.ds(i * block_k, block_k)], v_buf.at[slot],
                sems.at[slot, 1]),
        ]
        if quant:
            cps.append(pltpu.make_async_copy(
                ks_hbm.at[bh, pl.ds(i * block_k, block_k)], ks_buf.at[slot],
                sems.at[slot, 2]))
            cps.append(pltpu.make_async_copy(
                vs_hbm.at[bh, pl.ds(i * block_k, block_k)], vs_buf.at[slot],
                sems.at[slot, 3]))
        return cps

    m_scr[:] = jnp.full_like(m_scr, NEG_BIG)
    l_scr[:] = jnp.zeros_like(l_scr)
    acc_scr[:] = jnp.zeros_like(acc_scr)
    for cp in copies(lo, 0):
        cp.start()
    q = q_ref[0]  # [rows, D] — the group's query heads (padded to tile)

    # STATIC trip count with liveness guards (not a dynamic-bound loop —
    # simpler Mosaic lowering): dead iterations run a few scalar ops; DMA,
    # waits, and compute all sit under pl.when, so only live blocks move
    # bytes — a windowed decode still streams ~window bytes however big T.
    def body(i, _):
        live = (i >= lo) & (i <= hi)

        @pl.when(live)
        def _live():
            slot = jax.lax.rem(i - lo, 2)

            @pl.when(i + 1 <= hi)
            def _prefetch():
                ns = jax.lax.rem(i + 1 - lo, 2)
                for cp in copies(i + 1, ns):
                    cp.start()

            for cp in copies(i, slot):
                cp.wait()
            _softmax_block_update(
                q, k_buf[slot], v_buf[slot], i * block_k, pos, m_scr, l_scr,
                acc_scr, sm_scale=sm_scale, window=window,
                k_scale=None if not quant else ks_buf[slot],
                v_scale=None if not quant else vs_buf[slot],
                row_off=_row_offsets(q.shape[0], n_q))

        return 0

    jax.lax.fori_loop(0, n_blocks, body, 0)
    o_ref[0] = (acc_scr[:] / jnp.maximum(l_scr[:, :1], 1e-30)).astype(o_ref.dtype)


def decode_attention(q, k_cache, v_cache, pos, *, sm_scale=None,
                     block_k: int = 512, interpret=None, window=None,
                     stream: "bool | None" = None, k_scale=None,
                     v_scale=None):
    """Cached decode attention (1..C query positions) without expanding
    the grouped cache.

    q: [B, Hq, C, D] — C consecutive query positions per row (C=1 is
    plain single-token decode; C>1 is the speculative chunk verify:
    models/speculative.py packs C positions x n_rep grouped heads as the
    rows of the SAME per-(batch, kv head) matmul, so the cache still
    streams exactly once, narrow and int8-capable).  k_cache/v_cache:
    [B, Hkv, T, D]; pos: scalar int or per-row [B] int (ragged batches)
    — row b's queries sit at ``pos[b] .. pos[b] + C - 1``, key positions
    above each query are masked, and row b's DMA stops at its last
    query's block.  Write-then-attend callers must have the C entries in
    the cache already.  ``window`` (static): sliding-window attention
    over the last ``window`` positions — blocks entirely below the
    window are DMA-elided too, so a windowed decode streams ~window
    bytes of cache regardless of T.  Returns [B, Hq, C, D].  Numerically
    matches models/generate.py:_attend_cached (softmax in f32).

    ``k_scale``/``v_scale`` ([B, Hkv, T] f32): int8-quantized caches
    (ops/quantize.py) — the kernel streams the int8 blocks (half the HBM
    bytes of bf16) and folds dequantization into the score/weight algebra;
    both or neither must be given, matching the caches' int8 dtype.

    ``stream`` (default True; ``STARWAY_DECODE_STREAM=0`` flips the
    default — the manual-DMA lowering's escape hatch on hardware this
    kernel has not run on yet): the double-buffered single-cell kernel
    (:func:`_decode_stream_kernel`) — b*hkv grid cells total, per-cell
    pipeline overhead independent of T.  ``stream=False`` keeps the
    grid-pipelined kernel (one cell per kv block); ``bench.py --kernels
    decode_tune`` sweeps both on-chip.
    """
    if stream is None:
        from ..config import decode_stream_enabled

        stream = decode_stream_enabled()
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    quant = k_scale is not None or v_scale is not None
    if quant and (k_scale is None or v_scale is None):
        raise ValueError("int8 caches need BOTH k_scale and v_scale")
    for name, c in (("k_cache", k_cache), ("v_cache", v_cache)):
        if quant != (c.dtype == jnp.int8):
            raise ValueError(
                f"{name} dtype {c.dtype} inconsistent with "
                f"{'present' if quant else 'absent'} scales (int8 caches "
                f"carry per-token scales; see ops/quantize.py)")
    b, hq, n_q, d = q.shape
    hkv, t = k_cache.shape[1], k_cache.shape[2]
    n_rep = hq // hkv
    if sm_scale is None:
        sm_scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # Group query heads by their kv head: rows of the per-group matmul,
    # packed [n_rep, C] (row r = rep * C + ci — _row_offsets relies on
    # this layout).  repeat_kv maps q head h -> kv head h // n_rep, so the
    # reshape groups correctly (ops/attention.py:repeat_kv).
    n_rows = n_rep * n_q
    rows = _round_up(max(n_rows, 8), 8)  # TPU sublane tile
    qg = q.reshape(b, hkv, n_rows, d)
    if rows != n_rows:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, rows - n_rows), (0, 0)))
    qf = qg.reshape(b * hkv, rows, d)

    block_k = min(block_k, _round_up(t, 128))
    t_pad = _round_up(t, block_k)
    kf = k_cache.reshape(b * hkv, t, d)
    vf = v_cache.reshape(b * hkv, t, d)
    if t_pad != t:
        kf = jnp.pad(kf, ((0, 0), (0, t_pad - t), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, t_pad - t), (0, 0)))
    scales = []
    if quant:
        for s in (k_scale, v_scale):
            sf = s.astype(jnp.float32).reshape(b * hkv, t)
            if t_pad != t:
                sf = jnp.pad(sf, ((0, 0), (0, t_pad - t)))
            scales.append(sf)

    pos_arr = jnp.broadcast_to(jnp.asarray(pos, jnp.int32).reshape(-1), (b,))

    if stream:
        any_spec = pl.BlockSpec(memory_space=pltpu.MemorySpace.ANY)
        quant_scratch = [
            pltpu.VMEM((2, block_k), jnp.float32),
            pltpu.VMEM((2, block_k), jnp.float32),
        ] if quant else []
        out = pl.pallas_call(
            functools.partial(
                _decode_stream_kernel, sm_scale=sm_scale, block_k=block_k,
                hkv=hkv, window=None if window is None else int(window),
                n_blocks=t_pad // block_k, quant=quant, n_q=n_q),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(b * hkv,),
                in_specs=[
                    pl.BlockSpec((1, rows, d), lambda bh, pos_ref: (bh, 0, 0)),
                    any_spec,
                    any_spec,
                ] + [any_spec] * (2 * quant),
                out_specs=pl.BlockSpec((1, rows, d),
                                       lambda bh, pos_ref: (bh, 0, 0)),
                scratch_shapes=[
                    pltpu.VMEM((2, block_k, d), kf.dtype),
                    pltpu.VMEM((2, block_k, d), vf.dtype),
                ] + quant_scratch + [
                    pltpu.SemaphoreType.DMA((2, 4 if quant else 2)),
                    pltpu.VMEM((rows, 128), jnp.float32),
                    pltpu.VMEM((rows, 128), jnp.float32),
                    pltpu.VMEM((rows, d), jnp.float32),
                ],
            ),
            out_shape=jax.ShapeDtypeStruct((b * hkv, rows, d), q.dtype),
            interpret=interpret,
        )(pos_arr, qf, kf, vf, *scales)
        return out.reshape(b, hkv, rows, d)[:, :, :n_rows, :].reshape(
            b, hq, n_q, d)

    grid = (b * hkv, t_pad // block_k)

    # Clamp the K/V block index into the live range: the kernel body is
    # skipped outside it (pl.when), and a repeated block index makes the
    # Pallas pipeline elide the HBM copy entirely -- so a decode at pos
    # streams only the blocks holding (pos - window, pos + n_q - 1], not
    # the whole padded cache.  (pl.when alone skips compute, not DMA.)
    def _kv_index(bh, ki, pos_ref):
        p = pos_ref[bh // hkv]
        hi = (p + n_q - 1) // block_k
        if window is None:
            return (bh, jnp.minimum(ki, hi), 0)
        lo = jnp.maximum(p - window + 1, 0) // block_k
        return (bh, jnp.clip(ki, lo, hi), 0)

    def _scale_index(bh, ki, pos_ref):
        bh_, ki_, _ = _kv_index(bh, ki, pos_ref)
        return (bh_, ki_)

    out = pl.pallas_call(
        functools.partial(_decode_kernel, sm_scale=sm_scale, block_k=block_k,
                          hkv=hkv, window=None if window is None else int(window),
                          quant=quant, n_q=n_q),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, rows, d), lambda bh, ki, pos_ref: (bh, 0, 0)),
                pl.BlockSpec((1, block_k, d), _kv_index),
                pl.BlockSpec((1, block_k, d), _kv_index),
            ] + [pl.BlockSpec((1, block_k), _scale_index)] * (2 * quant),
            out_specs=pl.BlockSpec((1, rows, d), lambda bh, ki, pos_ref: (bh, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((rows, 128), jnp.float32),
                pltpu.VMEM((rows, 128), jnp.float32),
                pltpu.VMEM((rows, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b * hkv, rows, d), q.dtype),
        interpret=interpret,
    )(pos_arr, qf, kf, vf, *scales)
    return out.reshape(b, hkv, rows, d)[:, :, :n_rows, :].reshape(
        b, hq, n_q, d)
