"""Mesh collectives: thin, explicit wrappers over lax collectives.

Used inside ``shard_map``-decorated functions (the per-device SPMD view).
On TPU hardware every one of these lowers to XLA collectives scheduled on
ICI links; ``ring_shift`` is the CollectivePermute underlying ring attention
and pipeline-style neighbor exchange -- the device-plane analogue of the
reference's tagged neighbor sends (BASELINE config 4/5 patterns).
"""

from __future__ import annotations

import jax
from jax import lax


def axis_size(axis_name: str) -> int:
    return lax.axis_size(axis_name)


def axis_index(axis_name: str):
    return lax.axis_index(axis_name)


def ring_shift(x, axis_name: str, shift: int = 1):
    """Rotate shards around the mesh axis ring: device i -> device (i+shift).

    CollectivePermute over ICI; with ``shift=+1``/``-1`` both neighbor
    directions of a ring attention pass.
    """
    n = lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name: str, split_axis: int, concat_axis: int, *, tiled: bool = True):
    """Transpose shard ownership: split local data along ``split_axis`` into
    one block per device on the mesh axis, exchange, concatenate received
    blocks along ``concat_axis``.  The Ulysses-style sequence<->head
    re-sharding primitive and the KV-cache shuffle (BASELINE config 4)."""
    return lax.all_to_all(x, axis_name, split_axis, concat_axis, tiled=tiled)


def all_gather(x, axis_name: str, axis: int = 0, *, tiled: bool = True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def psum(x, axis_name: str):
    return lax.psum(x, axis_name)


def pmean(x, axis_name: str):
    return lax.pmean(x, axis_name)


def reduce_scatter(x, axis_name: str, scatter_axis: int = 0):
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_axis, tiled=True)


def ring_reduce(x, axis_name: str, op=None):
    """Explicit ring all-reduce built from CollectivePermute steps.

    XLA's psum is normally what you want (it already schedules a ring over
    ICI); this exists as the transparent composition example -- the
    device-plane mirror of building collectives from P2P sends, and a
    teaching/verification tool for the link model in perf.py.
    """
    import jax.numpy as jnp

    n = lax.axis_size(axis_name)
    if op is None:
        op = jnp.add

    def body(i, acc_and_buf):
        acc, buf = acc_and_buf
        buf = ring_shift(buf, axis_name, 1)
        return op(acc, buf), buf

    acc, _ = jax.lax.fori_loop(0, n - 1, body, (x, x))
    return acc
