"""Pallas TPU flash-attention: forward + backward, differentiable end-to-end.

The hand-scheduled counterpart of ops/attention.py's lax implementation:
same online-softmax algebra, but tiled explicitly onto VMEM with f32
accumulator scratch that persists across the (sequential, innermost) kv-block
grid dimension, bf16 inputs feeding the MXU, and causal blocks that are
entirely masked skipped outright (their HBM DMA elided by repeating the
clamped block index).

Block sizes matter enormously on TPU: the per-grid-cell fixed cost (DMA
setup, softmax VPU work that cannot overlap the first matmul) is ~1 µs, so
128x128 cells leave the MXU >90% idle.  The defaults (block_q=1024,
block_k=1024) measure ~115 TFLOP/s forward / ~97 TFLOP/s effective fwd+bwd
on a v5e at S=8192 causal GQA bf16 — ~60% of the same chip's 8192^3 matmul
rate (185-198 TFLOP/s) and ~7x the stock jax.experimental flash kernel at
the same shape, 16.9 TFLOP/s (harness: scripts/kernel_bench.py, which
differences two long on-device fori_loop runs so the sandbox tunnel's RTT
cancels).

The backward runs as two passes in the same [block_q, block_k] score layout
as the forward; the transposed products (dK = dS^T Q, dV = P^T dO) are
expressed as dot_generals contracting dimension 0 of both operands, so no
in-kernel transposes are needed.  Per-q-row constants (lse, delta) are
carried as [BH, S, 8] arrays — lane dim 8 keeps the block shape legal while
column 0 broadcasts along lanes, the cheap direction:

  pass A (kv-stationary): grid (B*Hkv, kv blocks, rep*q blocks); accumulates
    dK/dV in f32 VMEM scratch across the q-block sweep, summing the grouped
    query heads of each kv head (GQA) in the same sweep.
  pass B (q-stationary): grid (B*Hq, q blocks, kv blocks); accumulates dQ.

Both recompute p = exp(s - lse) from the forward's saved log-sum-exp, the
standard flash trade (FLOPs for HBM).  `flash_attention` carries a
jax.custom_vjp, so consumers (models/llama.py's default_attn on TPU)
differentiate through the kernel on TPU and through interpret mode in CPU
tests.

Layouts: ``q [B, Hq, S, D]``, ``k/v [B, Hkv, S, D]`` (grouped kv accepted
directly — the kernel indexes the right kv head per q head, no repeat_kv
materialisation).

Reference hook: the reference (Clouder0/starway) has no kernels — this layer
is the TPU build's own; the lax oracle it must match is
ops/attention.py::blockwise_attention.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import NEG_BIG

DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
# The backward holds ~4 [block_q, block_k] f32 intermediates live per cell
# (s, p, dp, ds) on top of the kv-resident blocks; 1024x1024 exceeds v5e
# VMEM (the compile never converges), 512x1024 fits and measures ~97
# TFLOP/s effective fwd+bwd.
DEFAULT_BWD_BLOCK_Q = 512
DEFAULT_BWD_BLOCK_K = 1024


class _Cfg(NamedTuple):
    """Static kernel configuration (hashable: custom_vjp nondiff arg)."""

    causal: bool
    sm_scale: float
    block_q: int
    block_k: int
    bwd_block_q: int
    bwd_block_k: int
    interpret: bool
    window: Optional[int] = None  # sliding window (requires causal)


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _mask_scores(s, q_start, k_start, kv_len, kv_pad, causal,
                 k_start_local=None, window=None):
    """Apply causal/window/padding masking to a score block.

    ``q_start``/``k_start`` are GLOBAL sequence coordinates (they differ
    from the in-array block position when a ring step supplies offsets);
    ``k_start_local`` is the in-array key position the padding compare
    needs — it defaults to ``k_start`` for the offset-free path.
    ``window`` (with ``causal``) keeps ``k_pos in (q_pos - window, q_pos]``.

    The kv-padding compare is skipped at *trace* time when the sequence
    needs no padding (the common case); a scalar `lax.cond` around the
    whole thing was measured slower than unconditional masking — Mosaic
    fuses the iota/compare/select into the softmax chain, a vector branch
    does not.
    """
    if k_start_local is None:
        k_start_local = k_start
    mask = None
    if kv_pad != kv_len:  # Python-level: only traced when padding exists
        k_pos = k_start_local + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = k_pos < kv_len
    if causal:
        q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        c = q_pos >= k_pos
        if window is not None:
            c = c & (k_pos > q_pos - window)
        mask = c if mask is None else mask & c
    return s if mask is None else jnp.where(mask, s, NEG_BIG)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *rest, causal: bool,
                sm_scale: float, block_q: int, block_k: int, kv_len: int,
                kv_pad: int, save_lse: bool, window: "int | None" = None):
    if save_lse:
        lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        m_scr, l_scr, acc_scr = rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_BIG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_start = qi * block_q
    k_start = ki * block_k

    def _body():
        q = q_ref[0]  # [block_q, D]
        k = k_ref[0]  # [block_k, D]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale  # [block_q, block_k]
        s = _mask_scores(s, q_start, k_start, kv_len, kv_pad, causal,
                         window=window)

        # Row stats live in (block_q, 128) lanes (TPU tile granularity);
        # column 0 is authoritative.  Masked entries hold NEG_BIG, so
        # exp(s - m_new) underflows to exactly 0 — no select needed for
        # full causal (every row sees at least key 0 on its first live kv
        # block, so m_new is always finite).  With a WINDOW an entire row
        # of a live block can be masked (its window starts in a later
        # block); clamping only exp's argument keeps its p at exactly 0.
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        if window is not None:
            p = jnp.exp(s - jnp.maximum(m_new, NEG_BIG / 2))
        else:
            p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_scr[:, :1] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        # Live iff the block's first key position can be visible to the
        # block's last query position — and, with a window, its last key
        # position can still be inside the block's first query's window.
        live = k_start <= q_start + block_q - 1
        if window is not None:
            live = live & (k_start + block_k - 1 > q_start - window)
        pl.when(live)(_body)
    else:
        _body()

    @pl.when(ki == n_k - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        if save_lse:
            lse = m_scr[:, :1] + jnp.log(l)  # [block_q, 1]
            lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _fwd_impl(q, k, v, cfg: _Cfg, save_lse: bool):
    b, hq, s, d = q.shape
    hkv = k.shape[1]
    n_rep = hq // hkv
    kv_len = k.shape[2]

    block_q = min(cfg.block_q, _round_up(s, 8))
    block_k = min(cfg.block_k, _round_up(kv_len, 8))
    s_pad = _round_up(s, block_q)
    kv_pad = _round_up(kv_len, block_k)
    if s_pad != s:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, s_pad - s), (0, 0)))
    if kv_pad != kv_len:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, kv_pad - kv_len), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, kv_pad - kv_len), (0, 0)))

    qf = q.reshape(b * hq, s_pad, d)
    kf = k.reshape(b * hkv, kv_pad, d)
    vf = v.reshape(b * hkv, kv_pad, d)

    def kv_head(bh):  # q-head flat index -> kv-head flat index
        return (bh // hq) * hkv + (bh % hq) // n_rep

    def kv_index(bh, i, j):
        # Causal: clamp at the last block any query row of q-block i can
        # see.  The kernel skips those blocks' compute (pl.when); repeating
        # the block index makes the pipeline elide their HBM copies too, so
        # the upper triangle costs no bandwidth (~2x saving at long S).
        # A window adds the symmetric LOWER clamp: blocks entirely below
        # every row's window are elided the same way.
        if cfg.causal:
            j = jnp.minimum(j, (i * block_q + block_q - 1) // block_k)
            if cfg.window is not None:
                lo = jnp.maximum(i * block_q - (cfg.window - 1), 0) // block_k
                j = jnp.maximum(j, lo)
        return (kv_head(bh), j, 0)

    grid = (b * hq, s_pad // block_q, kv_pad // block_k)
    out_shapes = [jax.ShapeDtypeStruct((b * hq, s_pad, d), q.dtype)]
    out_specs = [pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0))]
    if save_lse:
        # Lane dim 8 (not 1): keeps the block tiling legal; col 0 is the
        # value, the rest redundant broadcast (tiny: S*8 f32 per head).
        out_shapes.append(jax.ShapeDtypeStruct((b * hq, s_pad, 8), jnp.float32))
        out_specs.append(
            pl.BlockSpec((1, block_q, 8), lambda bh, i, j: (bh, i, 0)))
    out = pl.pallas_call(
        functools.partial(
            _fwd_kernel, causal=cfg.causal, sm_scale=cfg.sm_scale,
            block_q=block_q, block_k=block_k, kv_len=kv_len, kv_pad=kv_pad,
            save_lse=save_lse, window=cfg.window,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
        ],
        out_specs=out_specs,
        out_shape=out_shapes,
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=cfg.interpret,
    )(qf, kf, vf)
    if save_lse:
        o, lse = out
        return o.reshape(b, hq, s_pad, d)[:, :, :s, :], lse[:, :s]
    return out[0].reshape(b, hq, s_pad, d)[:, :, :s, :]


# ---------------------------------------------------------------------------
# backward + ring-step partials
# ---------------------------------------------------------------------------
#
# Same [block_q, block_k] score layout as the forward.  p is recomputed
# already *normalised* (p = exp(s - lse)), so no l bookkeeping:
#   dV  = P^T dO                      dP = dO V^T
#   dS  = P o (dP - delta)            delta = rowsum(dO o O)
#   dK  = sm_scale * dS^T Q           dQ = sm_scale * dS K
# The transposed products contract dim 0 of both operands (A^T B form) —
# the MXU takes them directly.  sm_scale on dK/dQ is applied once at
# emission, not per block element.
#
# Every kernel below takes a scalar-prefetch int32[2] = [q_offset, kv_offset]
# in GLOBAL sequence coordinates.  The plain flash_attention backward passes
# zeros; ring attention (parallel/ring_attention.py) passes the traced
# rotation offsets, which feed both the causal masking and the runtime
# DMA-elision clamps in the index maps — dead blocks cost neither MXU nor
# HBM bandwidth regardless of which ring step is executing.


def _bwd_block(q, do, k, v, lse, delta, *, causal, sm_scale, q_glob, k_glob,
               k_local, kv_len, kv_pad, window=None):
    """Shared recompute: returns (p, ds), both [block_q, block_k] f32.
    Masked entries get p = exp(NEG_BIG - lse) = 0 (lse is finite for every
    real row), so no all-masked-row handling is needed here even with a
    window."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * sm_scale
    s = _mask_scores(s, q_glob, k_glob, kv_len, kv_pad, causal,
                     k_start_local=k_local, window=window)
    p = jnp.exp(s - lse)  # normalised probs; masked entries -> 0
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = p * (dp - delta)
    return p, ds


def _bwd_dkv_kernel(offs_ref, q_ref, do_ref, k_ref, v_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, causal: bool,
                    sm_scale: float, block_q: int, block_k: int,
                    kv_len: int, kv_pad: int, n_q: int,
                    window: "int | None" = None):
    ki = pl.program_id(1)
    inner = pl.program_id(2)
    n_inner = pl.num_programs(2)
    qi = jax.lax.rem(inner, n_q)

    @pl.when(inner == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    k_local = ki * block_k
    q_glob = offs_ref[0] + qi * block_q
    k_glob = offs_ref[1] + k_local

    def _body():
        q = q_ref[0]                 # [block_q, D]
        do = do_ref[0]
        p, ds = _bwd_block(
            q, do, k_ref[0], v_ref[0], lse_ref[0][:, :1], delta_ref[0][:, :1],
            causal=causal, sm_scale=sm_scale, q_glob=q_glob,
            k_glob=k_glob, k_local=k_local, kv_len=kv_len, kv_pad=kv_pad,
            window=window,
        )
        # P^T dO and dS^T Q: contract the shared block_q dim (dim 0 of both).
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        # Live iff this q block reaches at or below the kv block's first
        # row — and, with a window, starts before the block's last key
        # falls out of every query's window.
        live = q_glob + block_q - 1 >= k_glob
        if window is not None:
            live = live & (k_glob + block_k - 1 > q_glob - window)
        pl.when(live)(_body)
    else:
        _body()

    @pl.when(inner == n_inner - 1)
    def _emit():
        dk_ref[0] = (dk_scr[:] * sm_scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(offs_ref, q_ref, do_ref, k_ref, v_ref, lse_ref, delta_ref,
                   dq_ref, dq_scr, *, causal: bool, sm_scale: float,
                   block_q: int, block_k: int, kv_len: int, kv_pad: int,
                   window: "int | None" = None):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    k_local = ki * block_k
    q_glob = offs_ref[0] + qi * block_q
    k_glob = offs_ref[1] + k_local

    def _body():
        k = k_ref[0]
        _, ds = _bwd_block(
            q_ref[0], do_ref[0], k, v_ref[0], lse_ref[0][:, :1],
            delta_ref[0][:, :1], causal=causal, sm_scale=sm_scale,
            q_glob=q_glob, k_glob=k_glob, k_local=k_local, kv_len=kv_len,
            kv_pad=kv_pad, window=window,
        )
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        live = k_glob <= q_glob + block_q - 1
        if window is not None:
            live = live & (k_glob + block_k - 1 > q_glob - window)
        pl.when(live)(_body)
    else:
        _body()

    @pl.when(ki == n_k - 1)
    def _emit():
        dq_ref[0] = (dq_scr[:] * sm_scale).astype(dq_ref.dtype)


def _run_bwd_passes(qf, dof, kf, vf, lse8, delta8, offs, *, b, hq, hkv,
                    s_pad, kv_pad, d, kv_len, block_q, block_k, causal,
                    sm_scale, interpret, dq_dtype, dkv_dtype, window=None):
    """Both backward passes over flattened [BH, S, D] operands.

    ``offs`` is the int32[2] global-offset vector (zeros for the plain
    path).  Returns (dq [b*hq, s_pad, d], dk, dv [b*hkv, kv_pad, d]).
    """
    n_rep = hq // hkv
    n_q = s_pad // block_q
    n_kv = kv_pad // block_k

    # ---- pass A: dK/dV (kv-stationary, sweeps rep x q blocks) ----
    def q_head(bkv, inner):
        r = inner // n_q
        return (bkv // hkv) * hq + (bkv % hkv) * n_rep + r

    def qi_eff(ki, inner, offs):
        qi = jax.lax.rem(inner, n_q)
        if causal:
            # Clamp dead (above-diagonal) q blocks onto the first live one:
            # their compute is skipped and their HBM DMA elided.  Global
            # coords: first live q row is kv_off + ki*bk - q_off.
            first = (offs[1] + ki * block_k - offs[0]) // block_q
            qi = jnp.maximum(qi, jnp.clip(first, 0, n_q - 1))
            if window is not None:
                # Window: q blocks past every key's window are dead too.
                last = (offs[1] + ki * block_k + block_k - 1 + window - 1
                        - offs[0]) // block_q
                qi = jnp.minimum(qi, jnp.clip(last, 0, n_q - 1))
        return qi

    qdo_spec = pl.BlockSpec(
        (1, block_q, d),
        lambda bkv, ki, inner, offs: (q_head(bkv, inner),
                                      qi_eff(ki, inner, offs), 0))
    row_spec = pl.BlockSpec(
        (1, block_q, 8),
        lambda bkv, ki, inner, offs: (q_head(bkv, inner),
                                      qi_eff(ki, inner, offs), 0))
    kv_spec = pl.BlockSpec(
        (1, block_k, d), lambda bkv, ki, inner, offs: (bkv, ki, 0))

    grid_a = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * hkv, n_kv, n_rep * n_q),
        in_specs=[qdo_spec, qdo_spec, kv_spec, kv_spec, row_spec, row_spec],
        out_specs=[kv_spec, kv_spec],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
    )
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, causal=causal, sm_scale=sm_scale,
            block_q=block_q, block_k=block_k, kv_len=kv_len, kv_pad=kv_pad,
            n_q=n_q, window=window,
        ),
        grid_spec=grid_a,
        out_shape=[
            jax.ShapeDtypeStruct((b * hkv, kv_pad, d), dkv_dtype),
            jax.ShapeDtypeStruct((b * hkv, kv_pad, d), dkv_dtype),
        ],
        interpret=interpret,
    )(offs, qf, dof, kf, vf, lse8, delta8)

    # ---- pass B: dQ (q-stationary, sweeps kv blocks) ----
    def kv_head(bh):
        return (bh // hq) * hkv + (bh % hq) // n_rep

    def ki_eff(i, j, offs):
        if causal:
            # Last kv block any row of q block i can see, in global coords.
            last = (offs[0] + i * block_q + block_q - 1 - offs[1]) // block_k
            j = jnp.minimum(j, jnp.clip(last, 0, n_kv - 1))
            if window is not None:
                first = (offs[0] + i * block_q - (window - 1)
                         - offs[1]) // block_k
                j = jnp.maximum(j, jnp.clip(first, 0, n_kv - 1))
        return j

    qdo_spec_b = pl.BlockSpec(
        (1, block_q, d), lambda bh, i, j, offs: (bh, i, 0))
    row_spec_b = pl.BlockSpec(
        (1, block_q, 8), lambda bh, i, j, offs: (bh, i, 0))
    kv_spec_b = pl.BlockSpec(
        (1, block_k, d), lambda bh, i, j, offs: (kv_head(bh),
                                                 ki_eff(i, j, offs), 0))

    grid_b = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * hq, s_pad // block_q, n_kv),
        in_specs=[qdo_spec_b, qdo_spec_b, kv_spec_b, kv_spec_b, row_spec_b,
                  row_spec_b],
        out_specs=pl.BlockSpec(
            (1, block_q, d), lambda bh, i, j, offs: (bh, i, 0)),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
    )
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, causal=causal, sm_scale=sm_scale,
            block_q=block_q, block_k=block_k, kv_len=kv_len, kv_pad=kv_pad,
            window=window,
        ),
        grid_spec=grid_b,
        out_shape=jax.ShapeDtypeStruct((b * hq, s_pad, d), dq_dtype),
        interpret=interpret,
    )(offs, qf, dof, kf, vf, lse8, delta8)
    return dq, dk, dv


def _bwd_operands(q, do, k, v, lse8, delta, block_q, block_k):
    """Pad + flatten backward operands; returns dict of kernel inputs."""
    b, hq, s, d = q.shape
    hkv = k.shape[1]
    kv_len = k.shape[2]
    s_pad = _round_up(s, block_q)
    kv_pad = _round_up(kv_len, block_k)

    if s_pad != s:
        pad = ((0, 0), (0, 0), (0, s_pad - s), (0, 0))
        q = jnp.pad(q, pad)
        do = jnp.pad(do, pad)  # zero rows -> zero dk/dv/ds contributions
        delta = jnp.pad(delta, ((0, 0), (0, 0), (0, s_pad - s)))
        # Padded q rows contribute nothing (do = 0), but pad lse with +big
        # so p = exp(s - lse) underflows to 0 instead of risking inf*0.
        lse8 = jnp.pad(lse8, ((0, 0), (0, s_pad - s), (0, 0)),
                       constant_values=-NEG_BIG)
    if kv_pad != kv_len:
        pad = ((0, 0), (0, 0), (0, kv_pad - kv_len), (0, 0))
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)

    return dict(
        qf=q.reshape(b * hq, s_pad, d),
        dof=do.reshape(b * hq, s_pad, d),
        kf=k.reshape(b * hkv, kv_pad, d),
        vf=v.reshape(b * hkv, kv_pad, d),
        lse8=lse8,
        delta8=jnp.broadcast_to(
            delta.reshape(b * hq, s_pad)[:, :, None], (b * hq, s_pad, 8)),
        b=b, hq=hq, hkv=hkv, s_pad=s_pad, kv_pad=kv_pad, d=d, kv_len=kv_len,
    )


def _bwd_impl(q, k, v, o, lse, do, cfg: _Cfg):
    b, hq, s, d = q.shape
    hkv = k.shape[1]
    kv_len = k.shape[2]
    block_q = min(cfg.bwd_block_q, _round_up(s, 8))
    block_k = min(cfg.bwd_block_k, _round_up(kv_len, 8))

    # delta = rowsum(dO o O): one cheap fused XLA pass, [B,Hq,S].
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    ops = _bwd_operands(q, do, k, v, lse, delta, block_q, block_k)
    dq, dk, dv = _run_bwd_passes(
        ops.pop("qf"), ops.pop("dof"), ops.pop("kf"), ops.pop("vf"),
        ops.pop("lse8"), ops.pop("delta8"), jnp.zeros((2,), jnp.int32),
        block_q=block_q, block_k=block_k, causal=cfg.causal,
        sm_scale=cfg.sm_scale, interpret=cfg.interpret,
        dq_dtype=q.dtype, dkv_dtype=k.dtype, window=cfg.window, **ops)

    dq = dq.reshape(b, hq, -1, d)[:, :, :s, :]
    dk = dk.reshape(b, hkv, -1, d)[:, :, :kv_len, :]
    dv = dv.reshape(b, hkv, -1, d)[:, :, :kv_len, :]
    return dq, dk, dv


# ---------------------------------------------------------------------------
# ring-step primitives: unnormalised partials at traced global offsets
# ---------------------------------------------------------------------------


def _partial_kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
                    m_scr, l_scr, acc_scr, *, causal: bool, sm_scale: float,
                    block_q: int, block_k: int, kv_len: int, kv_pad: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_BIG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    k_local = ki * block_k
    q_glob = offs_ref[0] + qi * block_q
    k_glob = offs_ref[1] + k_local

    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        s = _mask_scores(s, q_glob, k_glob, kv_len, kv_pad, causal,
                         k_start_local=k_local)
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # Rows with NO visible key in any block so far have m_new = NEG_BIG;
        # clamping only exp's argument (not the emitted m) keeps their p at
        # exactly 0, so the emitted partial is the true identity (o=0, l=0,
        # m=NEG_BIG) per partial_attention's mergeable contract.  Live rows
        # always have m_new > NEG_BIG/2, so this is a no-op for them.
        p = jnp.exp(s - jnp.maximum(m_new, NEG_BIG / 2))
        corr = jnp.exp(m_prev - m_new)
        l_new = l_scr[:, :1] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        pl.when(k_glob <= q_glob + block_q - 1)(_body)
    else:
        _body()

    @pl.when(ki == n_k - 1)
    def _emit():
        # Unnormalised partial: (acc, m, l) merge associatively across ring
        # steps (ops/attention.py::merge_partials).  Fully-masked rows --
        # whether from skipped blocks or from masking inside a live block --
        # emit the identity partial (acc=0, m=NEG_BIG, l=0; see the exp
        # clamp above).
        o_ref[0] = acc_scr[:].astype(o_ref.dtype)
        m_ref[0] = jnp.broadcast_to(m_scr[:, :1], m_ref.shape[1:])
        l_ref[0] = jnp.broadcast_to(l_scr[:, :1], l_ref.shape[1:])


def flash_partial(q, k, v, q_offset, kv_offset, *, causal: bool = True,
                  sm_scale: Optional[float] = None,
                  block_q: Optional[int] = None,
                  block_k: Optional[int] = None,
                  interpret: Optional[bool] = None):
    """One ring step's attention partial, Pallas-tiled.

    ``q [B,Hq,T,D]`` against one kv shard ``[B,Hkv,Tkv,D]`` (grouped heads
    accepted) whose global sequence positions start at ``kv_offset`` while
    the queries start at ``q_offset`` — both may be traced scalars (they
    ride a scalar-prefetch SMEM operand into the kernel and its index-map
    DMA clamps).  Returns ``(o, m, l)`` in the mergeable unnormalised form
    of ops/attention.py::partial_attention: o f32 ``[B,Hq,T,D]``, m/l f32
    ``[B,Hq,T]``.

    NOT differentiable — ring attention's custom_vjp (parallel/
    ring_attention.py) pairs it with :func:`flash_partial_bwd`.
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, hq, s, d = q.shape
    hkv = k.shape[1]
    n_rep = hq // hkv
    kv_len = k.shape[2]

    block_q = min(block_q or DEFAULT_BLOCK_Q, _round_up(s, 8))
    block_k = min(block_k or DEFAULT_BLOCK_K, _round_up(kv_len, 8))
    s_pad = _round_up(s, block_q)
    kv_pad = _round_up(kv_len, block_k)
    if s_pad != s:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, s_pad - s), (0, 0)))
    if kv_pad != kv_len:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, kv_pad - kv_len), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, kv_pad - kv_len), (0, 0)))

    qf = q.reshape(b * hq, s_pad, d)
    kf = k.reshape(b * hkv, kv_pad, d)
    vf = v.reshape(b * hkv, kv_pad, d)
    offs = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                      jnp.asarray(kv_offset, jnp.int32)])

    n_q = s_pad // block_q
    n_kv = kv_pad // block_k

    def kv_head(bh):
        return (bh // hq) * hkv + (bh % hq) // n_rep

    def kv_index(bh, i, j, offs):
        if causal:
            last = (offs[0] + i * block_q + block_q - 1 - offs[1]) // block_k
            j = jnp.minimum(j, jnp.clip(last, 0, n_kv - 1))
        return (kv_head(bh), j, 0)

    row8 = pl.BlockSpec((1, block_q, 8), lambda bh, i, j, offs: (bh, i, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * hq, n_q, n_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j, offs: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j, offs: (bh, i, 0)),
            row8,
            row8,
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
    )
    o, m8, l8 = pl.pallas_call(
        functools.partial(
            _partial_kernel, causal=causal, sm_scale=float(sm_scale),
            block_q=block_q, block_k=block_k, kv_len=kv_len, kv_pad=kv_pad,
        ),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b * hq, s_pad, d), jnp.float32),
            jax.ShapeDtypeStruct((b * hq, s_pad, 8), jnp.float32),
            jax.ShapeDtypeStruct((b * hq, s_pad, 8), jnp.float32),
        ],
        interpret=bool(interpret),
    )(offs, qf, kf, vf)
    o = o.reshape(b, hq, s_pad, d)[:, :, :s, :]
    m = m8[:, :, 0].reshape(b, hq, s_pad)[:, :, :s]
    l = l8[:, :, 0].reshape(b, hq, s_pad)[:, :, :s]
    return o, m, l


def flash_partial_bwd(q, do, k, v, lse, delta, q_offset, kv_offset, *,
                      causal: bool = True, sm_scale: Optional[float] = None,
                      block_q: Optional[int] = None,
                      block_k: Optional[int] = None,
                      interpret: Optional[bool] = None):
    """Gradient contributions of one ring step.

    Inputs mirror :func:`flash_partial` plus the *globally merged* ``lse``
    and ``delta = rowsum(dO o O)`` (both ``[B,Hq,T]`` f32) — with global
    statistics, each step's contribution is exactly its slice of the full
    attention gradient, so contributions sum across ring steps.  Returns
    ``(dq, dk, dv)`` in f32 with dk/dv GROUPED ``[B,Hkv,Tkv,D]``.
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, hq, s, d = q.shape
    hkv = k.shape[1]
    kv_len = k.shape[2]
    block_q = min(block_q or DEFAULT_BWD_BLOCK_Q, _round_up(s, 8))
    block_k = min(block_k or DEFAULT_BWD_BLOCK_K, _round_up(kv_len, 8))

    lse8 = jnp.broadcast_to(
        lse.astype(jnp.float32).reshape(b * hq, s)[:, :, None],
        (b * hq, s, 8))
    ops = _bwd_operands(q, do, k, v, lse8, delta.astype(jnp.float32),
                        block_q, block_k)
    offs = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                      jnp.asarray(kv_offset, jnp.int32)])
    dq, dk, dv = _run_bwd_passes(
        ops.pop("qf"), ops.pop("dof"), ops.pop("kf"), ops.pop("vf"),
        ops.pop("lse8"), ops.pop("delta8"), offs,
        block_q=block_q, block_k=block_k, causal=causal,
        sm_scale=float(sm_scale), interpret=bool(interpret),
        dq_dtype=jnp.float32, dkv_dtype=jnp.float32, **ops)
    dq = dq.reshape(b, hq, -1, d)[:, :, :s, :]
    dk = dk.reshape(b, hkv, -1, d)[:, :, :kv_len, :]
    dv = dv.reshape(b, hkv, -1, d)[:, :, :kv_len, :]
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp plumbing + public entry point
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash(q, k, v, cfg: _Cfg):
    # lse is a PRIMAL output (not just a vjp residual), tagged here so that
    # llama.py's "dots" remat policy (save attn_out + attn_lse) makes every
    # backward residual a subset of {inputs} ∪ {saved outputs} — the layer
    # backward then never re-runs this kernel.  With lse residual-only (the
    # pre-round-5 design), jax.checkpoint had to replay the forward kernel
    # inside every rematted layer just to regenerate lse, silently costing
    # a full extra flash forward per layer per step.  The extra [B*H, S, 8]
    # f32 store in inference paths is noise next to the O(S^2) compute.
    o, lse = _fwd_impl(q, k, v, cfg, save_lse=True)
    return checkpoint_name(o, "attn_out"), checkpoint_name(lse, "attn_lse")


def _flash_fwd(q, k, v, cfg: _Cfg):
    o, lse = _flash(q, k, v, cfg)
    return (o, lse), (q, k, v, o, lse)


def _flash_bwd(cfg: _Cfg, res, cts):
    q, k, v, o, lse = res
    do, _dlse = cts  # lse is an aux statistic; its cotangent is discarded
    return _bwd_impl(q, k, v, o, lse, do, cfg)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    bwd_block_q: Optional[int] = None,
    bwd_block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
    window: Optional[int] = None,
):
    """Flash attention, differentiable.  q: [B,Hq,S,D]; k/v: [B,Hkv,S,D]
    (grouped).

    Pads S to the block size internally; padded keys are masked, padded
    query rows are sliced off the output.  ``window`` (requires
    ``causal``): sliding-window attention — kv blocks outside
    ``(q - window, q]`` are masked, compute-skipped, AND DMA-elided in
    both the forward and the two backward passes, so a windowed pass
    streams O(S·window) bytes, not O(S²).  Backward runs the hand-written
    two-pass Pallas kernel (see module docstring).  Explicit forward blocks
    are inherited by the backward only up to the safe backward defaults —
    the backward holds more live intermediates per cell, and oversized
    blocks there hang the Mosaic compile (see DEFAULT_BWD_* above); pass
    ``bwd_block_q``/``bwd_block_k`` to override deliberately.
    """
    if window is not None:
        if not causal:
            raise ValueError("window requires causal attention")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    cfg = _Cfg(
        causal=causal,
        sm_scale=float(sm_scale),
        block_q=int(block_q) if block_q else DEFAULT_BLOCK_Q,
        block_k=int(block_k) if block_k else DEFAULT_BLOCK_K,
        bwd_block_q=int(bwd_block_q) if bwd_block_q else min(
            int(block_q) if block_q else DEFAULT_BWD_BLOCK_Q,
            DEFAULT_BWD_BLOCK_Q),
        bwd_block_k=int(bwd_block_k) if bwd_block_k else min(
            int(block_k) if block_k else DEFAULT_BWD_BLOCK_K,
            DEFAULT_BWD_BLOCK_K),
        interpret=bool(interpret),
        window=None if window is None else int(window),
    )
    o, _lse = _flash(q, k, v, cfg)
    return o
