"""Pallas TPU flash-attention forward kernel.

The hand-scheduled counterpart of ops/attention.py's lax implementation:
same online-softmax algebra, but tiled explicitly onto VMEM with f32
accumulator scratch that persists across the (sequential, innermost) kv-block
grid dimension, bf16 inputs feeding the MXU, and causal blocks that are
entirely masked skipped outright.

Layouts: ``q [B, Hq, S, D]``, ``k/v [B, Hkv, S, D]`` (grouped kv accepted
directly -- the kernel indexes the right kv head per q head, no repeat_kv
materialisation).  Use :func:`flash_attention`; it lowers to the kernel on
TPU and to interpret mode elsewhere (tests run it on CPU).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import NEG_BIG


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                  causal: bool, sm_scale: float, block_q: int, block_k: int,
                  kv_len: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_BIG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_start = qi * block_q
    k_start = ki * block_k

    def _body():
        q = q_ref[0]  # [block_q, D]
        k = k_ref[0]  # [block_k, D]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale  # [block_q, block_k]
        q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = k_pos < kv_len
        if causal:
            mask = mask & (q_pos >= k_pos)
        s = jnp.where(mask, s, NEG_BIG)

        # Row stats live in (block_q, 128) lanes (TPU tile granularity);
        # column 0 is authoritative.
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.where(s > NEG_BIG / 2, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_scr[:, :1] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        # Live iff the block's first key position can be visible to the
        # block's last query position.
        pl.when(k_start <= q_start + block_q - 1)(_body)
    else:
        _body()

    @pl.when(ki == n_k - 1)
    def _finalize():
        o_ref[0] = (acc_scr[:] / jnp.maximum(l_scr[:, :1], 1e-30)).astype(o_ref.dtype)


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
):
    """Flash attention forward.  q: [B,Hq,S,D]; k/v: [B,Hkv,S,D] (grouped).

    Pads S to the block size internally; padded keys are masked, padded
    query rows are sliced off the output.
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    b, hq, s, d = q.shape
    hkv = k.shape[1]
    n_rep = hq // hkv
    kv_len = k.shape[2]

    block_q = min(block_q, _round_up(s, 8))
    block_k = min(block_k, _round_up(kv_len, 8))
    s_pad = _round_up(s, block_q)
    kv_pad = _round_up(kv_len, block_k)
    if s_pad != s:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, s_pad - s), (0, 0)))
    if kv_pad != kv_len:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, kv_pad - kv_len), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, kv_pad - kv_len), (0, 0)))

    qf = q.reshape(b * hq, s_pad, d)
    kf = k.reshape(b * hkv, kv_pad, d)
    vf = v.reshape(b * hkv, kv_pad, d)

    def kv_head(bh):  # q-head flat index -> kv-head flat index
        return (bh // hq) * hkv + (bh % hq) // n_rep

    def kv_index(bh, i, j):
        # Causal: clamp at the last block any query row of q-block i can
        # see.  The kernel skips those blocks' compute (pl.when); repeating
        # the block index makes the pipeline elide their HBM copies too, so
        # the upper triangle costs no bandwidth (~2x saving at long S).
        if causal:
            j = jnp.minimum(j, (i * block_q + block_q - 1) // block_k)
        return (kv_head(bh), j, 0)

    grid = (b * hq, s_pad // block_q, kv_pad // block_k)
    out = pl.pallas_call(
        functools.partial(
            _flash_kernel, causal=causal, sm_scale=sm_scale,
            block_q=block_q, block_k=block_k, kv_len=kv_len,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * hq, s_pad, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, hq, s_pad, d)[:, :, :s, :]


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m
