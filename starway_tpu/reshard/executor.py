"""swshard executor: run a compiled Plan over the Client/Server fabric.

The executor is deliberately dumb: the :class:`~.plan.Plan` already fixed
*what* moves, *when* (rounds), and *under which tag*; this module just
drives one participant's share of it over duck-typed **ports** (anything
with ``asend(buf, tag)`` / ``arecv(buf, tag, mask)`` / ``aflush()`` --
parallel/dp_exchange.py's ``ClientPort``/``ServerPort`` fit as-is), with
a **flush barrier between rounds** so the §20 staging bound holds: at
any instant one host stages at most one outgoing and one incoming
transfer (<= 2 x plan.budget = O(shard)), plus at most one early-arrived
transfer in the matcher's unexpected queue when a peer runs a round
ahead.

Data moves as flat uint8 host buffers by default; the jax adapter
(reshard/api.py) swaps in device payloads/sinks through the optional
``make_payload``/``make_sink``/``consume_sink`` hooks, which is how a
schedule rides the device plane (and devpull, when the conn negotiated
it) without this module importing jax -- the same duck-typed boundary
core/ keeps with device.py (analysis rule ``layering-reshard``).

Observability: each executed round records a ``reshard_round`` stage
span (perf.record_stage -> EV_STAGE when tracing is armed), the
process-global ``reshard_bytes``/``reshard_rounds`` counters advance
(core/swtrace.py GLOBAL -- overlaid onto every worker snapshot like the
staging-pool counters), and live staging occupancy is exported through
the ``reshard_staging_bytes``/``reshard_staging_peak`` gauges
(core/telemetry.py merge_global_gauges).
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Callable, Mapping, Optional

from .plan import Plan, box_nbytes

__all__ = ["execute", "staging_snapshot", "reset_staging_peak", "FULL_MASK"]

FULL_MASK = (1 << 64) - 1


# ------------------------------------------------------- staging accounting
#
# Process-global (schedules may run on several event loops at once): the
# live bytes all in-flight transfers have staged, plus the high-water
# mark -- the gauge the §20 acceptance bound is asserted against.

class _Staging:
    def __init__(self):
        self._lock = threading.Lock()
        self.now = 0
        self.peak = 0

    def add(self, n: int) -> None:
        with self._lock:
            self.now += n
            if self.now > self.peak:
                self.peak = self.now

    def sub(self, n: int) -> None:
        with self._lock:
            self.now -= n


_staging = _Staging()


def staging_snapshot() -> dict:
    """{"now": bytes, "peak": bytes} across every schedule this process
    has executed (telemetry overlays these as reshard_staging_*)."""
    with _staging._lock:
        return {"now": _staging.now, "peak": _staging.peak}


def reset_staging_peak() -> None:
    """Reset the high-water mark (bench/test isolation)."""
    with _staging._lock:
        _staging.peak = _staging.now


# ----------------------------------------------------------------- executor


def _default_payload(transfer, plan: Plan, read_box: Callable):
    """Host path: one flat uint8 buffer, pieces concatenated in order."""
    import numpy as np

    buf = np.empty(transfer.nbytes, dtype=np.uint8)
    off = 0
    for p in transfer.pieces:
        nb = box_nbytes(p.box, plan.itemsize)
        buf[off:off + nb] = read_box(p.box)
        off += nb
    return buf


def _default_sink(transfer, plan: Plan):
    import numpy as np

    return np.empty(transfer.nbytes, dtype=np.uint8)


def _default_consume(transfer, plan: Plan, sink, write_box: Callable) -> None:
    import numpy as np

    view = memoryview(np.ascontiguousarray(sink)).cast("B")
    off = 0
    for p in transfer.pieces:
        nb = box_nbytes(p.box, plan.itemsize)
        write_box(p.box, view[off:off + nb])
        off += nb


async def execute(plan: Plan, rank: int, ports: Mapping,
                  read_box: Callable, write_box: Callable, *,
                  tag_of: Optional[Callable] = None,
                  make_payload: Optional[Callable] = None,
                  make_sink: Optional[Callable] = None,
                  consume_sink: Optional[Callable] = None,
                  round_timeout: Optional[float] = None) -> dict:
    """Run ``rank``'s share of ``plan`` over ``ports`` ({rank: port}).

    ``read_box(box) -> flat uint8 buffer`` supplies local source bytes
    (global coordinates); ``write_box(box, view)`` lands received (or
    locally copied) bytes.  ``tag_of(transfer) -> int`` maps a transfer
    to its wire tag (default: the raw ``tag_off`` -- pass a
    :class:`~.tags.TagLease`'s ``data_tag`` for collision-free tags).
    ``round_timeout`` bounds each round's completion (a dead peer then
    surfaces as that round's failure instead of a hang).  A timed-out
    round may leave receives posted in the matcher (the §10 contract:
    peer death leaves posted recvs pending) -- retry a failed schedule
    on a FRESH lease, never by re-leasing the same slot, so orphaned
    receives can't steal the retry's transfers (tags.lease() rotates
    auto-assigned slots for exactly this reason).

    Returns ``{"rounds": executed, "tx_bytes": ..., "rx_bytes": ...,
    "peak_staging": ..., "seconds": ...}`` -- ``peak_staging`` is THIS
    invocation's own staging high-water (the process-global gauge
    aggregates every concurrent schedule and role).
    """
    from .. import perf
    from ..core import swtrace

    tag_fn = tag_of if tag_of is not None else (lambda t: t.tag_off)
    pay_fn = make_payload or (lambda t: _default_payload(t, plan, read_box))
    sink_fn = make_sink or (lambda t: _default_sink(t, plan))
    eat_fn = consume_sink or (
        lambda t, s: _default_consume(t, plan, s, write_box))

    # Local copies first: they share no round budget (no staging, no
    # wire) and unblock nothing -- but doing them up front means a
    # schedule with zero network pieces completes without touching ports.
    for p in plan.local_pieces.get(rank, ()):
        write_box(p.box, read_box(p.box))

    t_start = time.perf_counter()
    tx_bytes = rx_bytes = 0
    executed = 0
    my_peak = 0  # THIS invocation's staging high-water (the global
    #              gauge aggregates every concurrent schedule/role)
    for rnd in range(plan.rounds):
        sends = plan.sends_for(rank, rnd)
        recvs = plan.recvs_for(rank, rnd)
        if not sends and not recvs:
            continue
        t0 = time.perf_counter()
        rnd_bytes = sum(t.nbytes for t in sends + recvs)
        my_peak = max(my_peak, rnd_bytes)
        _staging.add(rnd_bytes)
        try:
            # Payloads and sinks are materialised BEFORE anything is
            # posted: a payload-build failure (a box no local shard
            # covers, an allocator error) must surface with zero ops in
            # flight -- a receive posted ahead of a failed build would
            # strand in the matcher holding its sink, and a retried
            # schedule reusing the tag would feed it (the contract:
            # nothing posted unless the whole round's inputs exist).
            payloads = [(t, pay_fn(t)) for t in sends]
            sinks = [(t, sink_fn(t)) for t in recvs]
            ops = []
            # Receives first: posted before the payload can arrive in the
            # common case, keeping early-round traffic off the
            # unexpected queue (§18's matched fast path).
            ops.extend(ports[t.src].arecv(sink, tag_fn(t), FULL_MASK)
                       for t, sink in sinks)
            ops.extend(ports[t.dst].asend(buf, tag_fn(t))
                       for t, buf in payloads)
            gathered = asyncio.gather(*ops)
            if round_timeout is not None:
                await asyncio.wait_for(gathered, round_timeout)
            else:
                await gathered
            # Flush barrier: sends are only LOCALLY complete -- the
            # barrier promises delivery, which is what licenses dropping
            # the staged payloads and starting the next round.
            flushed = set()
            for t in sends:
                if id(ports[t.dst]) not in flushed:
                    flushed.add(id(ports[t.dst]))
                    await ports[t.dst].aflush()
            for t, sink in sinks:
                eat_fn(t, sink)
            del payloads, sinks
        finally:
            _staging.sub(rnd_bytes)
        tx_bytes += sum(t.nbytes for t in sends)
        rx_bytes += sum(t.nbytes for t in recvs)
        executed += 1
        dt = time.perf_counter() - t0
        perf.record_stage("reshard_round", dt, rnd_bytes)
        swtrace.GLOBAL.reshard_rounds += 1
        swtrace.GLOBAL.reshard_bytes += rnd_bytes
    return {
        "rounds": executed,
        "tx_bytes": tx_bytes,
        "rx_bytes": rx_bytes,
        "peak_staging": my_peak,
        "seconds": time.perf_counter() - t_start,
    }
