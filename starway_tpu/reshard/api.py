"""swshard jax adapter + the public ``redistribute()`` entry point.

This is the ONLY module under reshard/ allowed to import jax (analysis
rule ``layering-reshard`` -- the planner/executor stay pure so the
schedule machinery works in jax-free processes, mirroring core/'s
no-jax rule).  It lowers ``jax.sharding.NamedSharding`` into the
planner's pure-data :class:`~.plan.ShardSpec`, exchanges per-rank spec
contributions over the fabric itself (so participants on *different
meshes/process sets* never need a shared jax namespace), drives
:func:`~.executor.execute`, and re-assembles the destination
``jax.Array``.

>>> res = await redistribute(src_array, dst_sharding, peers={1: port},
...                          rank=0, lease_slot=3)
>>> res.array   # the redistributed jax.Array under dst_sharding

Participants coordinate on three things only: the same ``lease_slot``
(tag namespace, reshard/tags.py), a ``rank`` per process, and a port per
peer -- exactly the coordination surface parallel/dp_exchange.py already
asks for.
"""

from __future__ import annotations

import asyncio
import json
from typing import Callable, Optional

from . import executor as _executor
from . import tags as _tags
from .plan import Block, ShardSpec, box_nbytes, build_plan

__all__ = ["ArrayRef", "ReshardResult", "redistribute", "spec_from_sharding",
           "default_rank_of"]


class ArrayRef:
    """Descriptor standing in for an array this process does not hold
    (the pure-receiver side of a cross-pod redistribution)."""

    __slots__ = ("shape", "dtype")

    def __init__(self, shape, dtype):
        self.shape = tuple(int(s) for s in shape)
        self.dtype = _np_dtype(dtype)


def _np_dtype(dtype):
    # One normaliser for the whole device-adjacent surface (handles
    # ml_dtypes by name -- the spec exchange ships dtypes as strings).
    from ..device import _np_dtype as _dev_np_dtype

    return _dev_np_dtype(dtype)


def default_rank_of(device) -> int:
    """Device -> participant rank: the owning process (the real-cluster
    mapping; tests override to simulate many ranks on one host mesh)."""
    return int(device.process_index)


def _slices_to_box(idx, shape):
    box = []
    for sl, dim in zip(idx, shape):
        lo = 0 if sl.start is None else int(sl.start)
        hi = int(dim) if sl.stop is None else int(sl.stop)
        box.append((lo, hi))
    # Trailing dims a PartitionSpec left unmentioned are unsharded.
    for dim in shape[len(idx):]:
        box.append((0, int(dim)))
    return tuple(box)


def spec_from_sharding(sharding, shape, itemsize,
                       rank_of: Callable = default_rank_of,
                       only_rank: Optional[int] = None) -> ShardSpec:
    """Lower a NamedSharding (or any jax sharding with
    ``devices_indices_map``) into a pure-data :class:`ShardSpec`.
    ``only_rank`` keeps just that rank's blocks -- the per-process
    contribution the spec exchange ships to peers."""
    blocks = []
    for dev, idx in sharding.devices_indices_map(tuple(shape)).items():
        r = rank_of(dev)
        if only_rank is not None and r != only_rank:
            continue
        blocks.append(Block(r, _slices_to_box(idx, shape)))
    return ShardSpec(tuple(shape), itemsize, blocks)


class ReshardResult:
    """Per-device destination buffers + lazy assembly into a jax.Array.

    ``shards`` maps local destination devices to filled host buffers.
    :attr:`array` assembles them under ``sharding`` once every
    addressable device of the sharding is present; simulated-rank
    callers (several ranks in one process) :meth:`merge` their partial
    results first."""

    def __init__(self, shape, dtype, sharding, shards: dict, stats: dict):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.sharding = sharding
        self.shards = shards
        self.stats = stats
        self._array = None

    def merge(self, other: "ReshardResult") -> "ReshardResult":
        self.shards.update(other.shards)
        return self

    @property
    def array(self):
        import jax

        if self._array is not None:
            return self._array
        if self.sharding is None:
            raise ValueError("no destination sharding on this rank "
                             "(pure sender) -- there is nothing to assemble")
        want = set(self.sharding.addressable_devices)
        have = set(self.shards)
        if have != want:
            raise ValueError(
                f"destination incomplete: {len(have)}/{len(want)} local "
                "device shards filled -- merge() the other simulated "
                "ranks' results first")
        arrays = [jax.device_put(buf, dev) for dev, buf in self.shards.items()]
        self._array = jax.make_array_from_single_device_arrays(
            self.shape, self.sharding, arrays)
        return self._array


# ------------------------------------------------------------ spec exchange


def _ctl_payload(obj: dict):
    import numpy as np

    raw = json.dumps(obj, separators=(",", ":"), sort_keys=True).encode()
    return np.frombuffer(raw, dtype=np.uint8).copy()


async def _exchange_specs(rank, peers, lease, src_spec, dst_spec,
                          shape, itemsize, dtype_name, ctl_bytes, timeout):
    """All-gather the per-rank spec contributions over the ports: my
    contribution goes out on ``ctl_tag(rank)``, each peer's arrives on
    ``ctl_tag(peer)``.  Returns the merged (src, dst) specs."""
    import numpy as np

    mine = {
        "rank": rank,
        "shape": list(shape),
        "itemsize": itemsize,
        "dtype": dtype_name,
        "src": src_spec.to_dict()["blocks"],
        "dst": dst_spec.to_dict()["blocks"],
    }
    payload = _ctl_payload(mine)
    if len(payload) > ctl_bytes:
        raise ValueError(
            f"spec contribution ({len(payload)} B) exceeds the ctl buffer "
            f"({ctl_bytes} B); raise ctl_bytes")
    bufs = {p: np.empty(ctl_bytes, dtype=np.uint8) for p in peers}
    ops = [peers[p].arecv(bufs[p], lease.ctl_tag(p), _executor.FULL_MASK)
           for p in sorted(peers)]
    ops += [peers[p].asend(payload, lease.ctl_tag(rank)) for p in sorted(peers)]
    gathered = asyncio.gather(*ops)
    if timeout is not None:
        results = await asyncio.wait_for(gathered, timeout)
    else:
        results = await gathered
    src, dst = src_spec, dst_spec
    for (_, ln), p in zip(results[:len(peers)], sorted(peers)):
        theirs = json.loads(bytes(memoryview(bufs[p])[:ln]).decode())
        if (tuple(theirs["shape"]) != tuple(shape)
                or int(theirs["itemsize"]) != itemsize
                or theirs["dtype"] != dtype_name):
            raise ValueError(
                f"rank {p} describes a different array "
                f"({theirs['shape']}/{theirs['dtype']}) than this rank "
                f"({list(shape)}/{dtype_name})")
        src = src.merged(ShardSpec(shape, itemsize,
                                   [Block.from_dict(b) for b in theirs["src"]]))
        dst = dst.merged(ShardSpec(shape, itemsize,
                                   [Block.from_dict(b) for b in theirs["dst"]]))
    return src, dst


# ----------------------------------------------------------- local adapters


def _local_src_shards(array, rank, rank_of):
    """[(box, lazy host getter, jax shard array)] for this rank's share
    of the source array."""
    import numpy as np

    shape = array.shape
    out = []
    for shard in array.addressable_shards:
        if rank_of(shard.device) != rank:
            continue
        box = _slices_to_box(shard.index, shape)
        out.append([box, None, shard.data])
    def host_of(entry):
        if entry[1] is None:
            entry[1] = np.ascontiguousarray(np.asarray(entry[2]))
        return entry[1]
    return out, host_of


def _box_contains(outer, inner) -> bool:
    return all(olo <= ilo and ihi <= ohi
               for (olo, ohi), (ilo, ihi) in zip(outer, inner))


def _local_slices(outer, inner):
    return tuple(slice(ilo - olo, ihi - olo)
                 for (olo, _), (ilo, ihi) in zip(outer, inner))


# -------------------------------------------------------------- entry point


async def redistribute(array_or_ref, dst_sharding=None, peers=None, *,
                       rank: int = 0, rank_of: Callable = default_rank_of,
                       src_sharding=None, lease=None, lease_slot=None,
                       budget: Optional[int] = None, via: str = "host",
                       round_timeout: Optional[float] = None,
                       ctl_bytes: int = 1 << 18) -> ReshardResult:
    """Move an array between two shardings over the starway fabric.

    ``array_or_ref`` is this process's view of the SOURCE: a sharded
    ``jax.Array`` (source holder) or an :class:`ArrayRef` (pure
    receiver).  ``dst_sharding`` is the destination sharding for this
    process's devices (None on a pure sender).  ``peers`` maps the other
    participants' ranks to duck-typed ports (``asend``/``arecv``/
    ``aflush`` -- parallel/dp_exchange.py ports fit); omit it for a
    purely local retile.

    Every participant must pass the same ``lease_slot`` (reserved-tag
    coordination, reshard/tags.py) and a unique ``rank``.  ``via`` picks
    the transfer representation: ``"host"`` (flat uint8 staging, works
    everywhere) or ``"device"`` (jax.Array payloads/DeviceBuffer sinks
    through device.py's duck-typed protocols -- rides devpull when the
    connection negotiated it).  ``budget`` caps one message's bytes
    (default: the largest shard, the §20 memory unit).

    Returns a :class:`ReshardResult`; ``result.array`` is the assembled
    destination ``jax.Array`` (raises on a pure sender).
    """
    import numpy as np

    peers = dict(peers or {})
    if rank in peers:
        raise ValueError(f"peers must not contain this rank ({rank})")
    if via not in ("host", "device"):
        raise ValueError(f"via={via!r}: expected 'host' or 'device'")

    is_ref = isinstance(array_or_ref, ArrayRef)
    array = None if is_ref else array_or_ref
    if array is not None and not hasattr(array, "addressable_shards"):
        raise TypeError(
            f"array_or_ref must be a jax.Array or ArrayRef, got "
            f"{type(array_or_ref)!r}")
    shape = tuple(array_or_ref.shape)
    dtype = _np_dtype(array_or_ref.dtype)
    itemsize = int(dtype.itemsize)

    # ---- local contributions ----------------------------------------
    if array is not None:
        src_sh = src_sharding if src_sharding is not None else array.sharding
        src_spec = spec_from_sharding(src_sh, shape, itemsize,
                                      rank_of, only_rank=rank)
        src_shards, src_host = _local_src_shards(array, rank, rank_of)
    else:
        src_spec = ShardSpec(shape, itemsize, [])
        src_shards, src_host = [], None

    dst_devs: dict = {}
    if dst_sharding is not None:
        for dev, idx in dst_sharding.devices_indices_map(shape).items():
            if rank_of(dev) == rank:
                dst_devs[dev] = _slices_to_box(idx, shape)
    dst_spec = ShardSpec(shape, itemsize,
                         [Block(rank, box) for box in dst_devs.values()])

    # ---- spec exchange + plan ---------------------------------------
    own_lease = None
    if lease is None:
        lease = own_lease = _tags.lease(lease_slot)
    try:
        if peers:
            src_spec, dst_spec = await _exchange_specs(
                rank, peers, lease, src_spec, dst_spec, shape, itemsize,
                str(dtype), ctl_bytes, round_timeout)
        plan = build_plan(src_spec, dst_spec, budget=budget)

        # ---- local IO callbacks -------------------------------------
        dst_bufs = {dev: np.empty(tuple(hi - lo for lo, hi in box),
                                  dtype=dtype)
                    for dev, box in dst_devs.items()}

        def read_box(box):
            for entry in src_shards:
                if _box_contains(entry[0], box):
                    sub = src_host(entry)[_local_slices(entry[0], box)]
                    return np.ascontiguousarray(sub).view(np.uint8).reshape(-1)
            raise KeyError(f"no local source shard contains {box}")

        def write_box(box, view):
            shaped = None
            for dev, dbox in dst_devs.items():
                if _box_contains(dbox, box):
                    if shaped is None:
                        flat = np.frombuffer(view, dtype=np.uint8)
                        shaped = flat.view(dtype).reshape(
                            tuple(hi - lo for lo, hi in box))
                    dst_bufs[dev][_local_slices(dbox, box)] = shaped

        hooks = {}
        if via == "device":
            hooks = _device_hooks(plan, src_shards, write_box, dtype)

        stats = await _executor.execute(
            plan, rank, peers, read_box, write_box,
            tag_of=lambda t: lease.data_tag(t.tag_off),
            round_timeout=round_timeout, **hooks)
    finally:
        if own_lease is not None:
            own_lease.release()

    stats["plan_rounds"] = plan.rounds
    stats["peak_staging_bound"] = 2 * plan.budget
    return ReshardResult(shape, dtype, dst_sharding, dst_bufs, stats)


def _device_hooks(plan, src_shards, write_box, dtype):
    """Device-plane transfer hooks: payloads are jax.Arrays sliced on
    device (sent through device.py's DevicePayload path -- devpull when
    negotiated), sinks are DeviceBuffers.  Assembly still lands through
    ``write_box`` (the host buffers are the destination staging)."""
    import jax.numpy as jnp
    import numpy as np

    from ..device import DeviceBuffer

    def dev_read(box):
        for entry in src_shards:
            if _box_contains(entry[0], box):
                return entry[2][_local_slices(entry[0], box)].reshape(-1)
        raise KeyError(f"no local source shard contains {box}")

    def make_payload(t):
        parts = [dev_read(p.box) for p in t.pieces]
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts)

    def make_sink(t):
        elems = t.nbytes // dtype.itemsize
        return DeviceBuffer((elems,), dtype)

    def consume_sink(t, sink):
        host = np.ascontiguousarray(np.asarray(sink.array))
        flat = host.view(np.uint8).reshape(-1)
        off = 0
        for p in t.pieces:
            nb = box_nbytes(p.box, plan.itemsize)
            write_box(p.box, flat[off:off + nb])
            off += nb

    return {"make_payload": make_payload, "make_sink": make_sink,
            "consume_sink": consume_sink}
