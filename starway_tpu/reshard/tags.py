"""swshard tag-space leases: schedule tags that cannot collide with users.

Redistribution schedules address their messages with ordinary matcher
tags, so a schedule tag equal to a user tag would cross-deliver.  The
fix is a **reserved namespace**: the top byte ``0xE5`` ("swshard") of the
64-bit tag space belongs to this module -- user code keeps every tag
below ``RESHARD_TAG_BASE`` (all prior tag users in this tree do:
benchmark tags sit at 0x1AA0-0x2B5x, the trainer's DP exchange under
0x90000, perf probes at 0x7E57...0000) -- and inside it, concurrent
schedules are kept apart by **leases**: fixed-width slots handed out by
a process-local registry.

A lease is a coordination point, not a lock server: all participants of
one redistribution pass the same ``slot`` (the way they already share a
``base_tag`` in parallel/dp_exchange.py) and the registry guarantees
that two live leases *in one process* never overlap -- double-acquiring
a slot, or leasing while every slot is live, raises instead of silently
reusing tags.  ``python -m starway_tpu.analysis`` has no opinion here;
tests/test_reshard.py pins the collision behaviour.

Layout of one lease (``SLOT_SPAN`` = 2^20 tags):

* ``base + 0 .. base + CTL_TAGS-1`` -- control tags (spec exchange:
  ``ctl_tag(rank)`` = ``base + rank``).
* ``base + CTL_TAGS ..`` -- data tags (``data_tag(i)`` for transfer
  ``tag_off`` ``i``).
"""

from __future__ import annotations

import threading

__all__ = [
    "RESHARD_TAG_BASE",
    "RESHARD_TAG_END",
    "SLOT_SPAN",
    "SLOTS",
    "CTL_TAGS",
    "TagLease",
    "lease",
    "is_reshard_tag",
]

#: Bottom of the reserved namespace: tags with top byte 0xE5.
RESHARD_TAG_BASE = 0xE5 << 56
#: One past the last reserved tag.
RESHARD_TAG_END = 0xE6 << 56
#: Tags per lease slot (control + data).
SLOT_SPAN = 1 << 20
#: Concurrent lease slots the namespace is divided into (bounded so the
#: registry's bookkeeping stays a small set; the namespace itself would
#: fit 2^36 slots).
SLOTS = 1 << 12
#: Control tags reserved at the bottom of each slot (one per participant
#: rank for the spec exchange; ranks above this use an explicit spec).
CTL_TAGS = 1 << 10

_lock = threading.Lock()
_live: set = set()
_next_slot = 0  # rotating auto-assign cursor (see lease())


def is_reshard_tag(tag: int) -> bool:
    """True for tags inside the reserved swshard namespace."""
    return RESHARD_TAG_BASE <= int(tag) < RESHARD_TAG_END


class TagLease:
    """One leased slot of the reserved namespace.  Context-manageable;
    releasing twice is a no-op.  Tag accessors bounds-check so a
    schedule can never silently spill into a neighbouring lease.

    Direct construction (``TagLease(slot)``) is pure tag arithmetic --
    no registry entry, so its release() never touches the registry; only
    :func:`lease` registers (``_owned``), so a direct instance used as a
    context manager cannot silently free a slot some live lease() holds.
    """

    __slots__ = ("slot", "base", "_released", "_owned")

    def __init__(self, slot: int, _owned: bool = False):
        self.slot = int(slot)
        self.base = RESHARD_TAG_BASE + self.slot * SLOT_SPAN
        self._released = False
        self._owned = _owned

    def ctl_tag(self, rank: int) -> int:
        if not (0 <= rank < CTL_TAGS):
            raise ValueError(f"ctl rank {rank} outside lease (max {CTL_TAGS})")
        return self.base + rank

    def data_tag(self, i: int) -> int:
        if not (0 <= i < SLOT_SPAN - CTL_TAGS):
            raise ValueError(
                f"data tag index {i} outside lease span {SLOT_SPAN}")
        return self.base + CTL_TAGS + i

    def release(self) -> None:
        if not self._released:
            self._released = True
            if self._owned:
                with _lock:
                    _live.discard(self.slot)

    def __enter__(self) -> "TagLease":
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"TagLease(slot={self.slot}, base=0x{self.base:x})"


def lease(slot=None) -> TagLease:
    """Acquire a lease.

    ``slot=None`` auto-assigns a free slot (single-process / tests) from
    a ROTATING cursor, not lowest-free: a schedule that failed with
    receives still posted must not see its slot -- and therefore its
    tags -- handed straight back to the retry (executor.py round_timeout
    note).  Distributed participants pass the SAME explicit ``slot`` --
    the shared-coordinate contract -- and each process's registry still
    refuses a slot already live locally (two overlapping redistributions
    coordinating on one slot is the collision this exists to catch).
    """
    global _next_slot
    with _lock:
        if slot is None:
            slot = next((s % SLOTS for s in range(_next_slot,
                                                  _next_slot + SLOTS)
                         if s % SLOTS not in _live), None)
            if slot is None:
                raise RuntimeError(
                    f"swshard tag namespace exhausted ({SLOTS} live leases)")
            _next_slot = (slot + 1) % SLOTS
        else:
            slot = int(slot)
            if not (0 <= slot < SLOTS):
                raise ValueError(f"lease slot {slot} outside [0, {SLOTS})")
            if slot in _live:
                raise RuntimeError(
                    f"swshard tag lease slot {slot} is already live in this "
                    "process -- concurrent schedules must use distinct slots")
        _live.add(slot)
    return TagLease(slot, _owned=True)
