"""swshard planner: sharding -> sharding retiles as minimal-memory schedules.

The planner compiles a (source sharding, destination sharding) pair over
one global index space into a schedule of tagged point-to-point transfers
whose **peak per-host staging stays O(shard), never O(array)** -- the
construction of "Memory-efficient array redistribution through portable
collective communication" (arxiv 2112.01075) applied to starway's p2p
fabric instead of XLA collectives (DESIGN.md §20, ROADMAP item 2).

Everything here is **pure data + stdlib**: a sharding side is a
:class:`ShardSpec` (global shape, element size, and per-rank index-space
boxes), serialisable to/from plain JSON-able dicts so *different
processes on different meshes* can agree on one plan without sharing a
jax namespace -- the cross-process lingua franca.  jax enters only in
reshard/api.py, which lowers ``jax.sharding.NamedSharding`` into specs
(the layering twin of core/'s no-jax rule; analysis rule
``layering-reshard``).

The algorithm, in four deterministic steps (every participant computes
the identical plan from the identical specs):

1. **Dedup regions.**  Blocks of one spec either partition the index
   space or replicate it (several ranks holding the same box -- jax's
   partial replication).  Distinct boxes are deduped; each keeps the set
   of holder ranks.
2. **Intersect.**  Every (distinct src box x distinct dst box) overlap
   is one *piece*.  A piece whose destination rank also holds a source
   copy becomes a local copy (never touches the network); otherwise one
   source holder is chosen deterministically, least-loaded-first, so
   replicated sources spread the send load.
3. **Split.**  Pieces for one (src, dst) rank pair are packed into
   *transfers* of at most ``budget`` bytes each (default: the largest
   distinct shard of either side).  A transfer is ONE tagged message --
   its pieces concatenate in deterministic order, so the wire needs no
   per-piece header.
4. **Round-assign.**  Transfers are greedily placed (largest first)
   into rounds where each rank sends at most one and receives at most
   one transfer -- the all-to-all shape.  The executor puts a flush
   barrier between rounds, so per-host concurrent staging is bounded by
   one outgoing + one incoming transfer: **<= 2 x budget = O(shard)**.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

__all__ = [
    "Block",
    "ShardSpec",
    "Piece",
    "Transfer",
    "Plan",
    "build_plan",
    "box_nbytes",
    "box_overlap",
]

Box = tuple  # tuple[(lo, hi), ...] -- half-open per-dim intervals


def box_elems(box: Box) -> int:
    n = 1
    for lo, hi in box:
        n *= max(0, hi - lo)
    return n


def box_nbytes(box: Box, itemsize: int) -> int:
    return box_elems(box) * int(itemsize)


def box_overlap(a: Box, b: Box) -> Optional[Box]:
    """Intersection box of two half-open boxes, or None when empty."""
    out = []
    for (alo, ahi), (blo, bhi) in zip(a, b):
        lo, hi = max(alo, blo), min(ahi, bhi)
        if lo >= hi:
            return None
        out.append((lo, hi))
    return tuple(out)


@dataclass(frozen=True)
class Block:
    """One rank's claim on one index-space box (a device shard's global
    slice, lifted to the rank that owns the device)."""

    rank: int
    box: Box

    def to_dict(self) -> dict:
        return {"rank": self.rank, "box": [list(d) for d in self.box]}

    @classmethod
    def from_dict(cls, d: dict) -> "Block":
        return cls(int(d["rank"]),
                   tuple((int(lo), int(hi)) for lo, hi in d["box"]))


@dataclass
class ShardSpec:
    """One side of a redistribution: the global array plus who holds what.

    ``blocks`` may repeat a box across ranks (replication) and may list
    several boxes per rank (several local devices).  The spec must
    *cover* the global index space -- checked in :func:`build_plan` by
    the uncovered-volume test on the destination side.
    """

    shape: tuple
    itemsize: int
    blocks: list = field(default_factory=list)

    def __post_init__(self):
        self.shape = tuple(int(s) for s in self.shape)
        self.itemsize = int(self.itemsize)
        for b in self.blocks:
            if len(b.box) != len(self.shape):
                raise ValueError(
                    f"block {b} rank mismatch with shape {self.shape}")
            for (lo, hi), dim in zip(b.box, self.shape):
                if not (0 <= lo < hi <= dim):
                    raise ValueError(
                        f"block {b} outside the global shape {self.shape}")

    def ranks(self) -> set:
        return {b.rank for b in self.blocks}

    def distinct_boxes(self) -> dict:
        """{box: sorted holder ranks} -- replication collapsed."""
        out: dict = {}
        for b in sorted(self.blocks, key=lambda b: (b.box, b.rank)):
            out.setdefault(b.box, [])
            if b.rank not in out[b.box]:
                out[b.box].append(b.rank)
        return out

    def max_shard_nbytes(self) -> int:
        return max((box_nbytes(b.box, self.itemsize) for b in self.blocks),
                   default=0)

    def rank_nbytes(self, rank: int) -> int:
        """Distinct bytes resident on ``rank`` (replicated boxes counted
        once)."""
        seen = set()
        total = 0
        for b in self.blocks:
            if b.rank == rank and b.box not in seen:
                seen.add(b.box)
                total += box_nbytes(b.box, self.itemsize)
        return total

    # ------------------------------------------------------------- wire
    def to_dict(self) -> dict:
        return {
            "shape": list(self.shape),
            "itemsize": self.itemsize,
            "blocks": [b.to_dict() for b in self.blocks],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ShardSpec":
        return cls(tuple(d["shape"]), int(d["itemsize"]),
                   [Block.from_dict(b) for b in d["blocks"]])

    def merged(self, other: "ShardSpec") -> "ShardSpec":
        """Union of two partial specs (per-rank contributions exchanged
        over the fabric); shape/itemsize must agree."""
        if self.shape != other.shape or self.itemsize != other.itemsize:
            raise ValueError(
                f"spec mismatch: {self.shape}/{self.itemsize} vs "
                f"{other.shape}/{other.itemsize} -- all participants must "
                "describe the same global array")
        seen = {(b.rank, b.box) for b in self.blocks}
        extra = [b for b in other.blocks if (b.rank, b.box) not in seen]
        return ShardSpec(self.shape, self.itemsize, self.blocks + extra)


@dataclass(frozen=True)
class Piece:
    """One contiguous global box moving src_rank -> dst_rank (or copied
    locally when the ranks agree)."""

    src: int
    dst: int
    box: Box


@dataclass
class Transfer:
    """One tagged message: >=1 pieces between one (src, dst) rank pair.
    Pieces concatenate in list order, each flattened C-order -- both ends
    derive the identical layout from the plan, so no wire header."""

    src: int
    dst: int
    pieces: list
    nbytes: int
    tag_off: int = -1   # lease-relative tag (assigned once, plan order)
    round: int = -1     # flush-barrier round (assigned by round_assign)


@dataclass
class Plan:
    """The compiled schedule.  Deterministic given (src, dst) specs:
    every participant builds bit-identical transfers/tags/rounds."""

    shape: tuple
    itemsize: int
    transfers: list               # Transfer, tag_off order
    local_pieces: dict            # rank -> [Piece] (src == dst, no network)
    rounds: int
    budget: int

    def sends_for(self, rank: int, rnd: Optional[int] = None) -> list:
        return [t for t in self.transfers
                if t.src == rank and (rnd is None or t.round == rnd)]

    def recvs_for(self, rank: int, rnd: Optional[int] = None) -> list:
        return [t for t in self.transfers
                if t.dst == rank and (rnd is None or t.round == rnd)]

    def total_wire_nbytes(self) -> int:
        return sum(t.nbytes for t in self.transfers)

    def peak_staging(self, rank: int) -> int:
        """Upper bound on ``rank``'s concurrently staged bytes under the
        executor's round barriers: the worst round's one outgoing + one
        incoming transfer.  <= 2 x budget by construction."""
        peak = 0
        for rnd in range(self.rounds):
            here = sum(t.nbytes for t in self.transfers
                       if t.round == rnd and rank in (t.src, t.dst))
            peak = max(peak, here)
        return peak


def _choose_source(holders: list, dst: int, load: dict) -> int:
    """Deterministic source pick for one piece: the destination itself
    when it already holds a copy (local, free), else the least-loaded
    holder (ties to the lowest rank) so replicated sources share the
    send work."""
    if dst in holders:
        return dst
    return min(holders, key=lambda r: (load.get(r, 0), r))


def build_plan(src: ShardSpec, dst: ShardSpec,
               budget: Optional[int] = None) -> Plan:
    """Compile ``src -> dst`` into a round schedule.

    ``budget`` caps one transfer's bytes (default: the larger of the two
    sides' largest distinct shard -- the O(shard) unit the memory bound
    is stated in).  A single piece larger than the budget still travels
    whole (a piece is the indivisible unit); that only happens when one
    destination shard alone exceeds every source shard, where O(shard)
    is that piece's size anyway.
    """
    if src.shape != dst.shape or src.itemsize != dst.itemsize:
        raise ValueError(
            f"src {src.shape}/{src.itemsize} and dst {dst.shape}/"
            f"{dst.itemsize} describe different arrays")
    if budget is None:
        budget = max(src.max_shard_nbytes(), dst.max_shard_nbytes(), 1)
    budget = max(1, int(budget))

    src_boxes = src.distinct_boxes()
    dst_boxes = dst.distinct_boxes()

    # ---- steps 1+2: intersect distinct regions, choose sources --------
    pieces: list = []          # network pieces
    local: dict = {}           # rank -> [Piece]
    load: dict = {}            # src rank -> bytes already assigned
    covered = 0
    for dbox, dst_holders in dst_boxes.items():
        for sbox, src_holders in src_boxes.items():
            ov = box_overlap(dbox, sbox)
            if ov is None:
                continue
            nb = box_nbytes(ov, src.itemsize)
            # Every holder of the dst box needs these bytes; holders that
            # also hold the src copy it locally, the rest receive it.
            for dr in dst_holders:
                p = Piece(_choose_source(src_holders, dr, load), dr, ov)
                if p.src == dr:
                    local.setdefault(dr, []).append(p)
                else:
                    load[p.src] = load.get(p.src, 0) + nb
                    pieces.append(p)
            covered += nb
    dst_volume = sum(box_nbytes(b, dst.itemsize) for b in dst_boxes)
    if covered != dst_volume:
        raise ValueError(
            f"source spec does not cover the destination: {covered} of "
            f"{dst_volume} destination bytes have a source")

    # ---- step 3: pack pieces into <=budget transfers per pair ---------
    by_pair: dict = {}
    for p in sorted(pieces, key=lambda p: (p.src, p.dst, p.box)):
        by_pair.setdefault((p.src, p.dst), []).append(p)
    transfers: list = []
    for (s, d) in sorted(by_pair):
        group, size = [], 0
        for p in by_pair[(s, d)]:
            nb = box_nbytes(p.box, src.itemsize)
            if group and size + nb > budget:
                transfers.append(Transfer(s, d, group, size))
                group, size = [], 0
            group.append(p)
            size += nb
        if group:
            transfers.append(Transfer(s, d, group, size))

    # ---- step 4: largest-first greedy round assignment ----------------
    # Stable total order first (pair, then descending size) so ties
    # break identically everywhere; tags follow the same order.
    transfers.sort(key=lambda t: (-t.nbytes, t.src, t.dst,
                                  t.pieces[0].box if t.pieces else ()))
    busy_tx: list = []   # round -> set of sending ranks
    busy_rx: list = []   # round -> set of receiving ranks
    for t in transfers:
        rnd = 0
        while True:
            if rnd == len(busy_tx):
                busy_tx.append(set())
                busy_rx.append(set())
            if t.src not in busy_tx[rnd] and t.dst not in busy_rx[rnd]:
                busy_tx[rnd].add(t.src)
                busy_rx[rnd].add(t.dst)
                t.round = rnd
                break
            rnd += 1
    transfers.sort(key=lambda t: (t.round, t.src, t.dst,
                                  t.pieces[0].box if t.pieces else ()))
    for i, t in enumerate(transfers):
        t.tag_off = i

    for rank, ps in local.items():
        ps.sort(key=lambda p: p.box)
    return Plan(src.shape, src.itemsize, transfers, local,
                len(busy_tx), budget)
