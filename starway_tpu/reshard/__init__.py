"""swshard: array redistribution compiled into minimal-memory p2p schedules.

The bridge between the SPMD layer (DESIGN.md §8) and the p2p runtime
(ROADMAP item 2; DESIGN.md §20): given a source and a destination
sharding -- possibly on different meshes or different process sets -- a
**planner** (plan.py) computes the per-rank block intersections and
compiles them into rounds of all-to-all-shaped tagged transfers whose
per-host staging stays O(shard), an **executor** (executor.py) runs the
schedule over the existing Client/Server fabric with flush barriers
between rounds, and a **tag lease** (tags.py) keeps schedule tags in a
reserved namespace that cannot collide with user tags.  The jax face --
``redistribute()`` / ``ArrayRef`` / ``spec_from_sharding`` -- lives in
api.py, the only module here allowed to import jax (analysis rule
``layering-reshard``).

Follows "Memory-efficient array redistribution through portable
collective communication" (arxiv 2112.01075), built from starway p2p
instead of XLA collectives, so it composes with every opt-in plane the
fabric carries: sessions (§14), striping (§17), flow control (§18),
integrity (§19).
"""

from __future__ import annotations

from .plan import Block, Piece, Plan, ShardSpec, Transfer, build_plan  # noqa: F401
from .tags import RESHARD_TAG_BASE, TagLease, is_reshard_tag, lease  # noqa: F401
from .executor import execute, reset_staging_peak, staging_snapshot  # noqa: F401


def __getattr__(name):
    # jax-importing names resolve lazily so `import starway_tpu.reshard`
    # stays cheap (and possible) in jax-free processes.
    if name in ("redistribute", "ArrayRef", "ReshardResult",
                "spec_from_sharding", "default_rank_of"):
        from . import api

        return getattr(api, name)
    raise AttributeError(name)


__all__ = [
    "Block", "Piece", "Plan", "ShardSpec", "Transfer", "build_plan",
    "RESHARD_TAG_BASE", "TagLease", "is_reshard_tag", "lease",
    "execute", "staging_snapshot", "reset_staging_peak",
    "redistribute", "ArrayRef", "ReshardResult", "spec_from_sharding",
    "default_rank_of",
]
