"""Error types for starway-tpu.

The reference surfaces failures as plain ``Exception(reason)`` built from UCS
status strings (reference: src/starway/__init__.py:127-128) and raises
``RuntimeError`` for lifecycle violations such as double close (reference:
tests/test_basic.py:500-511).  We keep those observable contracts:

* lifecycle violations raise :class:`StarwayStateError` (a ``RuntimeError``),
* operation failures are delivered to ``fail_callback(reason: str)`` where
  ``reason`` contains a stable keyword:

  - ``"cancel"``     -- op cancelled by local close (tests/test_basic.py:638-663)
  - ``"not connected"`` -- connect failure / op on dead endpoint
    (tests/test_basic.py:514-518), including peer-liveness expiry when
    keepalive is enabled (STARWAY_KEEPALIVE, see config.py)
  - ``"truncated"``  -- message larger than the posted receive buffer
  - ``"timed out"``  -- op deadline (``timeout=`` on asend/arecv/aflush/
    aconnect) expired before completion (tests/test_faults.py)
  - ``"session expired"`` -- a session-enabled connection (``STARWAY_SESSION``,
    see config.py) stayed dead past ``STARWAY_SESSION_GRACE``, or the peer
    answered the resume handshake with a new epoch; ops that were riding
    out the outage fail with this reason instead of completing late
    (tests/test_session.py)
  - ``"corrupt"``    -- the negotiated integrity plane (``STARWAY_INTEGRITY``,
    DESIGN.md §19) detected silent data corruption that cannot be repaired
    by a chunk retransmit: a frame-header/payload checksum mismatch on a
    non-striped frame, or a torn shared-memory ring record.  The poisoned
    connection resets; with ``STARWAY_SESSION=1`` it suspends and the
    journal replay re-delivers verified bytes instead (tests/test_integrity.py)
"""

from __future__ import annotations


class StarwayError(Exception):
    """Base class for all starway-tpu errors."""


class StarwayStateError(RuntimeError):
    """Lifecycle violation: op issued while the worker is in the wrong state.

    RuntimeError subclass so ``pytest.raises(RuntimeError)`` on double close
    matches the reference behaviour (tests/test_basic.py:508-511).
    """


# Stable reason strings passed to fail callbacks.  Keyword contracts mirror the
# reference's UCS status strings surfaced through Exception(reason).
REASON_CANCELLED = "Operation cancelled (local endpoint closed before completion)"
REASON_NOT_CONNECTED = "Endpoint is not connected"
REASON_TRUNCATED = "Message truncated: payload larger than posted receive buffer"
REASON_TIMEOUT = "Operation timed out (deadline exceeded before completion)"
REASON_SESSION_EXPIRED = "Session expired (resume window elapsed or peer restarted)"
REASON_CORRUPT = "Data integrity violation (corrupt frame detected)"
REASON_INTERNAL = "Internal transport error"
