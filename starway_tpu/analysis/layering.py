"""Pass 3: layering lint -- the import-direction rules between layers.

Two rows, one discipline (dependencies point DOWN the stack only):

* **core/ imports no jax** (``layering-jax``).  The matcher and
  transports are byte-oriented; device awareness enters only through the
  duck-typed sink/payload protocols in device.py (CLAUDE.md architecture
  invariants).  A jax import in core/ would make the host transport
  unimportable in jax-free processes (the wheel's test-command imports
  core.native with only numpy installed) and couple the engine to the
  device plane.
* **reshard/ sits above core/** (``layering-reshard``, DESIGN.md §20).
  Both directions of the boundary: no module under core/ may import
  ``starway_tpu.reshard`` (the engine must not know schedules exist),
  and under reshard/ only ``api.py`` -- the jax adapter -- may import
  jax, so the planner/executor stay runnable in jax-free processes the
  same way core/ does.
"""

from __future__ import annotations

import ast
from pathlib import Path

from .base import Finding, core_py_files, parse_or_finding, rel

#: The one reshard/ module allowed to bind jax (the adapter) -- exact
#: repo-relative path, so a nested helper named api.py is NOT exempt.
RESHARD_JAX_OK = ("starway_tpu/reshard/api.py",)


def _is_jax(module: str) -> bool:
    return module == "jax" or module.startswith("jax.")


def _is_reshard(module: str, level: int) -> bool:
    if level == 0:
        return (module == "starway_tpu.reshard"
                or module.startswith("starway_tpu.reshard."))
    # Relative imports from core/ modules: `..reshard` is level 2,
    # module "reshard" (or "reshard.plan").
    return module == "reshard" or module.startswith("reshard.")


def _names_package_root(node: "ast.ImportFrom") -> bool:
    """Does this ImportFrom's module part resolve to the starway_tpu
    package root (from a core/ module)?  Then its alias names can bind
    reshard: `from starway_tpu import reshard`, `from .. import
    reshard`."""
    if node.level == 0:
        return node.module == "starway_tpu"
    return node.level == 2 and not node.module


def reshard_py_files(root: Path) -> list:
    pkg = root / "starway_tpu" / "reshard"
    if not pkg.is_dir():
        return []
    return sorted(p for p in pkg.rglob("*.py") if "__pycache__" not in p.parts)


def run(root: Path) -> list:
    out: list = []
    for path in core_py_files(root):
        relpath = rel(root, path)
        tree, err = parse_or_finding(path, relpath)
        if tree is None:
            out.append(err)
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if _is_jax(alias.name):
                        out.append(Finding(
                            relpath, node.lineno, "layering-jax",
                            f"`import {alias.name}` under core/ -- device "
                            "awareness enters only via device.py's "
                            "duck-typed sink/payload protocols"))
                    elif _is_reshard(alias.name, 0):
                        out.append(Finding(
                            relpath, node.lineno, "layering-reshard",
                            f"`import {alias.name}` under core/ -- "
                            "reshard/ sits ABOVE core/; the engine must "
                            "not import the schedule layer"))
            elif isinstance(node, ast.ImportFrom):
                if node.level == 0 and node.module and _is_jax(node.module):
                    out.append(Finding(
                        relpath, node.lineno, "layering-jax",
                        f"`from {node.module} import ...` under core/ -- "
                        "device awareness enters only via device.py"))
                elif node.module and _is_reshard(node.module, node.level):
                    out.append(Finding(
                        relpath, node.lineno, "layering-reshard",
                        f"`from {'.' * node.level}{node.module} import ...` "
                        "under core/ -- reshard/ sits ABOVE core/; the "
                        "engine must not import the schedule layer"))
                elif _names_package_root(node):
                    # `from starway_tpu import reshard` / `from .. import
                    # reshard` bind the subpackage through the package
                    # root -- same boundary, different spelling.
                    for alias in node.names:
                        if (alias.name == "reshard"
                                or alias.name.startswith("reshard.")):
                            out.append(Finding(
                                relpath, node.lineno, "layering-reshard",
                                f"`from {node.module or '.' * node.level} "
                                f"import {alias.name}` under core/ -- "
                                "reshard/ sits ABOVE core/; the engine "
                                "must not import the schedule layer"))
    for path in reshard_py_files(root):
        relpath = rel(root, path)
        if relpath in RESHARD_JAX_OK:
            continue
        tree, err = parse_or_finding(path, relpath)
        if tree is None:
            out.append(err)
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if _is_jax(alias.name):
                        out.append(Finding(
                            relpath, node.lineno, "layering-reshard",
                            f"`import {alias.name}` in reshard/{path.name} "
                            "-- only the api.py adapter may bind jax; the "
                            "planner/executor stay jax-free (DESIGN.md §20)"))
            elif isinstance(node, ast.ImportFrom):
                if node.level == 0 and node.module and _is_jax(node.module):
                    out.append(Finding(
                        relpath, node.lineno, "layering-reshard",
                        f"`from {node.module} import ...` in "
                        f"reshard/{path.name} -- only the api.py adapter "
                        "may bind jax (DESIGN.md §20)"))
    return out
