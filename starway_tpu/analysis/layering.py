"""Pass 3: layering lint -- no ``import jax`` anywhere under core/.

The matcher and transports are byte-oriented; device awareness enters
only through the duck-typed sink/payload protocols in device.py
(CLAUDE.md architecture invariants).  A jax import in core/ would make
the host transport unimportable in jax-free processes (the wheel's
test-command imports core.native with only numpy installed) and couple
the engine to the device plane.
"""

from __future__ import annotations

import ast
from pathlib import Path

from .base import Finding, core_py_files, parse_or_finding, rel


def _is_jax(module: str) -> bool:
    return module == "jax" or module.startswith("jax.")


def run(root: Path) -> list:
    out: list = []
    for path in core_py_files(root):
        relpath = rel(root, path)
        tree, err = parse_or_finding(path, relpath)
        if tree is None:
            out.append(err)
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if _is_jax(alias.name):
                        out.append(Finding(
                            relpath, node.lineno, "layering-jax",
                            f"`import {alias.name}` under core/ -- device "
                            "awareness enters only via device.py's "
                            "duck-typed sink/payload protocols"))
            elif isinstance(node, ast.ImportFrom):
                if node.level == 0 and node.module and _is_jax(node.module):
                    out.append(Finding(
                        relpath, node.lineno, "layering-jax",
                        f"`from {node.module} import ...` under core/ -- "
                        "device awareness enters only via device.py"))
    return out
