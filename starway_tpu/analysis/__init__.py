"""swcheck + swproof + swcompose: static cross-engine contract checking.

``python -m starway_tpu.analysis`` runs twelve passes and exits non-zero
on any finding (the CI merge gate; also step 1 of
scripts/release_smoke.sh):

* **contract** -- diffs the wire/shm/ABI/reason/handshake contract between
  ``core/engine.py``-side sources and ``native/sw_engine.{h,cpp}``
  ("two engines, one contract", CLAUDE.md).
* **concurrency** -- callbacks never fire under a worker lock (direct or
  *reachable* through the call graph); no blocking calls on the engine
  thread or reachable under a lock; lock-order cycle detection spanning
  the Python locks and the native mutex sites; the TX-item duck-type
  attribute contract; lint-surface coverage audit (DESIGN.md §2, §16).
* **layering** -- no jax imports under core/.
* **markers** -- multi-GiB test payloads must carry @pytest.mark.slow.
* **hotpath** -- no full-payload ``bytes(...)``/``.tobytes()`` copies on
  core/ data paths (the zero-copy discipline, DESIGN.md §12).
* **protomodel** -- extracts the protocol state machine from BOTH engines
  (ast over the Python dispatch; ``swcheck: state(...)`` annotations in
  the native engine) and diffs them transition-by-transition
  (DESIGN.md §16).
* **explore** -- bounded exhaustive model checking of the §14 session
  layer: every fault schedule (kill/dup/reorder/restart) over a bounded
  workload, against the exactly-once / journal-trim / flush-order /
  epoch / quiescence invariants.
* **compose** -- the swcompose product model (DESIGN.md §21): sessions
  x striped chunks x credit window x integrity retransmit explored
  under conn kills, rail deaths, corruption, and duplication, against
  the stripe-exactly-once / pin-release / credit-conservation /
  no-wrong-answer / quiescence invariants.
* **wirefuzz** -- a contract-derived differential fuzzer for the frame
  and sm-slot-record decoders: identical adversarial bytes through a
  grammar oracle, ``frames.decode_stream`` / ``decode_sm_records``, and
  the native ``sw_wire_decode`` export; a checked-in regression corpus
  replays every run (DESIGN.md §21).
* **taint** -- the §19 unverified-byte lint: every rx delivery sink in
  BOTH engines is dominated by a CRC verify whose mismatch arm aborts,
  every payload read accumulates, and sm slot corruption poisons
  before parse (DESIGN.md §21).
* **refine** -- swrefine model<->code conformance (DESIGN.md §22): the
  canonical protocol-event vocabulary diffed across both engines, the
  checked-in event corpus replayed through the monitor automaton
  compiled from the engines' own extracted state machines, and
  transition coverage (every model arm witnessed by a pinned run or a
  justified waiver).  ``refine --replay <dump>`` replays any swtrace
  ring/flight dump through the same monitor.
* **cost** -- swcost hot-path cost certification (DESIGN.md §23): a
  per-contract-path ``{syscalls, copies, allocs, locks}`` site vector
  extracted from BOTH engines and ratcheted against the checked-in
  ``analysis/cost_budgets.txt`` ledger (over OR under a pin is a
  finding), plus liveness of the ``io_syscalls``/``hot_copies``
  runtime twin the tests/test_cost.py conformance check rides on.
  ``cost --write-budgets`` re-pins the ledger from head.

Waivers: a finding is suppressed by an explicit justified comment on (or
directly above) the flagged line::

    # swcheck: allow(blocking-call): bench harness runs off-engine

A waiver without the ``: why`` justification, or naming an unknown rule,
is itself a finding (``bad-waiver``).  See DESIGN.md §11 and §16.
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Iterable, Optional

from . import (compose, concurrency, contract, cost, explore, hotpath,
               layering, markers, protomodel, refine, taint, wirefuzz)
from .base import (  # noqa: F401  (re-exported for tests and tooling)
    RULES,
    Finding,
    apply_waivers,
    clear_caches,
    core_py_files,
    find_root,
    lint_py_files,
    scan_bad_waivers,
    test_files,
    waiver_audit_files,
)

PASSES = {
    "contract": contract.run,
    "concurrency": concurrency.run,
    "layering": layering.run,
    "markers": markers.run,
    "hotpath": hotpath.run,
    "protomodel": protomodel.run,
    "explore": explore.run,
    "compose": compose.run,
    "wirefuzz": wirefuzz.run,
    "taint": taint.run,
    "refine": refine.run,
    "cost": cost.run,
}


def run_all(root: Optional[str] = None,
            passes: Optional[Iterable[str]] = None,
            timings: Optional[dict] = None) -> list:
    """Run the selected passes (default: all) against ``root`` and return
    the post-waiver findings, sorted by location.  ``timings``, when a
    dict, receives per-pass wall seconds (the --timings CLI surface)."""
    rootp = find_root(root) if not isinstance(root, Path) else root
    clear_caches()  # parse-once per gate run; files may change between runs
    selected = list(passes) if passes else list(PASSES)
    findings: list = []
    for name in selected:
        t0 = time.perf_counter()
        findings.extend(PASSES[name](rootp))
        if timings is not None:
            timings[name] = time.perf_counter() - t0
    findings = apply_waivers(rootp, findings)
    findings.extend(scan_bad_waivers(rootp, waiver_audit_files(rootp)))
    seen = set()
    unique = []
    for f in sorted(findings, key=lambda f: (f.file, f.line, f.rule, f.message)):
        key = (f.file, f.line, f.rule, f.message)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    return unique
