"""swcheck: static cross-engine contract checker and concurrency lint.

``python -m starway_tpu.analysis`` runs five passes and exits non-zero on
any finding (the CI merge gate; also step 1 of scripts/release_smoke.sh):

* **contract** -- diffs the wire/shm/ABI/reason/handshake contract between
  ``core/engine.py``-side sources and ``native/sw_engine.{h,cpp}``
  ("two engines, one contract", CLAUDE.md).
* **concurrency** -- callbacks never fire under a worker lock; no blocking
  calls on the engine thread (DESIGN.md §2).
* **layering** -- no jax imports under core/.
* **markers** -- multi-GiB test payloads must carry @pytest.mark.slow.
* **hotpath** -- no full-payload ``bytes(...)``/``.tobytes()`` copies on
  core/ data paths (the zero-copy discipline, DESIGN.md §12).

Waivers: a finding is suppressed by an explicit justified comment on (or
directly above) the flagged line::

    # swcheck: allow(blocking-call): bench harness runs off-engine

A waiver without the ``: why`` justification, or naming an unknown rule,
is itself a finding (``bad-waiver``).  See DESIGN.md §11.
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterable, Optional

from . import concurrency, contract, hotpath, layering, markers
from .base import (  # noqa: F401  (re-exported for tests and tooling)
    RULES,
    Finding,
    apply_waivers,
    core_py_files,
    find_root,
    scan_bad_waivers,
    test_files,
    waiver_audit_files,
)

PASSES = {
    "contract": contract.run,
    "concurrency": concurrency.run,
    "layering": layering.run,
    "markers": markers.run,
    "hotpath": hotpath.run,
}


def run_all(root: Optional[str] = None,
            passes: Optional[Iterable[str]] = None) -> list:
    """Run the selected passes (default: all) against ``root`` and return
    the post-waiver findings, sorted by location."""
    rootp = find_root(root) if not isinstance(root, Path) else root
    selected = list(passes) if passes else list(PASSES)
    findings: list = []
    for name in selected:
        findings.extend(PASSES[name](rootp))
    findings = apply_waivers(rootp, findings)
    findings.extend(scan_bad_waivers(rootp, waiver_audit_files(rootp)))
    seen = set()
    unique = []
    for f in sorted(findings, key=lambda f: (f.file, f.line, f.rule, f.message)):
        key = (f.file, f.line, f.rule, f.message)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    return unique
