"""Pass: refine -- model<->code conformance (swrefine, DESIGN.md §22).

swproof/swcompose (analysis/protomodel.py, explore.py, compose.py) verify
extracted and hand-written *models* of the protocol; nothing there proves
those models match the *running engines* -- a drifted model makes every
`explore`/`compose` proof vacuous.  swrefine closes that refinement gap
from both ends:

* **Monitor compilation.**  The protomodel-extracted state machines of
  BOTH engines (the ``swcheck: state(...)`` annotations in
  native/sw_engine.cpp; the ast-extracted dispatch of core/conn.py +
  core/engine.py) compile into one nondeterministic-but-checkable
  per-conn monitor automaton over the canonical protocol-event
  vocabulary below.  The automaton tracks the SET of model states a conn
  may be in; an event no tracked state can take is a divergence.

* **Protocol event taps.**  Both engines emit the same event channel
  (swtrace ``EV_PROTO``; armed by STARWAY_PROTO_TRACE / STARWAY_MONITOR,
  zero events on the seed path): ``st:hello-sent``/``st:estab`` at conn
  creation, ``rx:<FRAME>`` at every inbound dispatch, ``tx:<FRAME>`` at
  ctl-plane handoff (context only -- the model describes the *dispatch*
  machine, so the monitor checks rx + lifecycle), and
  ``lost``/``resume``/``expire``/``down`` for the lifecycle.  ``python -m
  starway_tpu.analysis refine --replay <dump>`` replays any ring dump
  through the monitor; ``core/monitor.py`` does the same in-process when
  STARWAY_MONITOR is armed.

* **The gate legs** (this pass, every merge):

  - the canonical frame-name tables -- frames.py ``FRAME_NAMES`` and the
    native ``proto_frame_name()`` switch -- diffed against each other,
    against the T_* constants, and against the protomodel input
    vocabulary (rule ``refine``);
  - the checked-in event corpus (``refine_corpus.txt`` next to this
    file, the wirefuzz_corpus.txt pattern) replayed through the
    freshly-compiled monitor: real event sequences pinned from traced
    runs must stay accepted, and each divergence class must still be
    *detected* (an expected violation that stops firing means the
    monitor went soft);
  - **transition coverage** (rule ``monitor-coverage``): every model
    transition must be witnessed by the corpus or carry a justified
    entry in ``UNWITNESSED_WAIVERS`` -- a transition no pinned run ever
    exercises is a stale model arm or dead code.  (tests/test_swcheck.py
    additionally asserts the LIVE floor: quick scenarios on both engines
    must witness ``COVERAGE_FLOOR`` at runtime.)

**Monitor semantics.**  States: the protomodel vocabulary
(``hello-sent``/``estab``/``suspended``) plus the terminal sinks
``down``/``expired``.  A conn starts from its ``st:`` declaration, or --
for mid-stream replays of a bounded ring -- from the universal live set.
``down`` is always enabled (a transport can die under any state) and is
terminal.  ``expire`` is enabled from ``suspended`` (the model's
grace-expiry row) and, as a documented monitor extension
(``MONITOR_EXTRA``), from ``estab``: the T_BYE arm
``(estab, BYE, estab|expired)`` and both engines' stale-epoch /
one-sided-resume paths expire sessions that never suspended.  Divergence
classes: ``no-transition`` (no tracked state accepts the input),
``state-decl`` (an engine-declared state the monitor contradicts),
``event-after-terminal`` (dispatch after the conn reached only terminal
states), ``bad-event`` (an event outside the canonical vocabulary).
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional

from . import protomodel
from .base import Finding, parse_or_finding, read_text
from .py_model import module_int_constants

#: swtrace event type the protocol channel rides (EV_PROTO <-> kEvProto).
PROTO_EV = "proto"

LIVE_STATES = ("hello-sent", "estab", "suspended")
TERMINAL_STATES = ("down", "expired")

#: Lifecycle inputs (everything else arrives as rx:<FRAME>).
LIFECYCLE_INPUTS = ("lost", "resume", "expire")

#: Frame-name vocabulary = protomodel's inputs minus the lifecycle.
FRAME_INPUTS = frozenset(protomodel.KNOWN_INPUTS) - frozenset(LIFECYCLE_INPUTS)

#: Documented monitor extensions -- transitions real engines take that the
#: extracted machine does not carry as a dispatch arm.  (estab, expire):
#: the server's stale-epoch registration and the one-sided-resume
#: supersede path expire sessions that never suspended, and the model
#: already admits estab -> expired through the T_BYE arm; the
#: (suspended, expire) model row keeps pinning grace expiry.  Keep this
#: list minimal: every entry here is surface the model checkers cannot
#: see (DESIGN.md §22).
MONITOR_EXTRA = {
    ("estab", "expire"): frozenset({"expired"}),
}

#: Transitions the corpus (or a justified waiver here) must witness; a
#: waiver naming a transition the model no longer contains is itself a
#: finding (stale waiver).  Empty today: every extracted arm is
#: exercisable by a pinned event sequence.
UNWITNESSED_WAIVERS: dict = {}

#: The LIVE runtime floor asserted by tests/test_swcheck.py: quick
#: scenarios (loopback pair + session kill/resume) on EACH engine must
#: witness at least these transitions through real rings -- the
#: corpus-side coverage above proves the monitor can see every arm, this
#: floor proves the taps actually fire in running engines.
COVERAGE_FLOOR = (
    ("hello-sent", "HELLO_ACK"),
    ("estab", "HELLO"),
    ("estab", "DATA"),
    ("estab", "FLUSH"),
    ("estab", "FLUSH_ACK"),
    ("estab", "PING"),
    ("estab", "PONG"),
    ("estab", "SEQ"),
    ("estab", "ACK"),
    ("estab", "lost"),
    ("suspended", "resume"),
)

#: Divergence classes the monitor reports (and the corpus pins).
VIOLATION_CLASSES = ("no-transition", "state-decl", "event-after-terminal",
                     "bad-event")

#: Regression-corpus floor: the gate replays >= this many checked-in
#: sequences or the corpus itself became the regression.
CORPUS_FLOOR = 24


# ------------------------------------------------------------ the monitor


@dataclass
class Violation:
    label: str          # worker/ring label (or corpus case name)
    conn: int
    index: int          # ordinal of the failing event within the conn
    cls: str            # one of VIOLATION_CLASSES
    message: str
    context: list = field(default_factory=list)  # trailing events incl. failing

    def render(self) -> str:
        ctx = " ".join(self.context)
        where = f"{self.label or 'ring'} conn {self.conn} event {self.index}"
        return f"[{self.cls}] {where}: {self.message} [... {ctx}]"


class ConnMonitor:
    """Tracks the set of model states one conn may occupy and steps it
    per protocol event.  ``step`` returns ``(cls, message)`` on the first
    divergence (the caller stops feeding this conn) or None."""

    __slots__ = ("mon", "states", "witnessed")

    def __init__(self, mon: "Monitor"):
        self.mon = mon
        self.states: Optional[frozenset] = None  # None until first event
        self.witnessed: set = set()

    def _init_states(self) -> frozenset:
        # Mid-stream replay (bounded ring lost the conn's birth): any
        # live state is possible.
        return frozenset(LIVE_STATES)

    def step(self, event: str):
        if event == "down":
            # Spontaneous transport death is enabled under every state
            # and terminal (idempotent -- expiry teardown may follow it).
            self.states = frozenset({"down"})
            return None
        if event.startswith("st:"):
            declared = event[3:]
            if declared not in LIVE_STATES:
                return ("bad-event", f"unknown state declaration {event!r}")
            if self.states is None:
                self.states = frozenset({declared})
                return None
            if declared in self.states:
                self.states = frozenset({declared})
                return None
            return ("state-decl",
                    f"engine declared state {declared!r} but the monitor "
                    f"tracks {sorted(self.states)}")
        if event.startswith("tx:"):
            # Context only: the model is the *dispatch* machine; sends
            # are checked at the peer as its rx events.
            name = event[3:]
            if name not in FRAME_INPUTS:
                return ("bad-event", f"unknown tx frame name {event!r}")
            return None
        if event.startswith("rx:"):
            inp = event[3:]
            if inp not in FRAME_INPUTS:
                return ("bad-event", f"unknown rx frame name {event!r}")
        elif event in LIFECYCLE_INPUTS:
            inp = event
        else:
            return ("bad-event", f"event {event!r} outside the canonical "
                                 "vocabulary")
        if self.states is None:
            self.states = self._init_states()
        live = [s for s in self.states if s in LIVE_STATES]
        if not live:
            return ("event-after-terminal",
                    f"event {event!r} dispatched after the conn reached "
                    f"terminal state(s) {sorted(self.states)}")
        nexts: set = set()
        took = []
        for s in live:
            arm = self.mon.transitions.get((s, inp))
            if arm is None:
                arm = MONITOR_EXTRA.get((s, inp))
                if arm is not None:
                    nexts |= arm
                continue
            took.append((s, inp))
            nexts |= arm
        if not nexts:
            return ("no-transition",
                    f"no model transition accepts {event!r} from "
                    f"{sorted(live)} (model states: the engines' own "
                    "extracted machines -- drifted model or drifted code)")
        self.witnessed.update(took)
        self.states = frozenset(nexts)
        return None


class Monitor:
    """The compiled automaton: ``{(state, input): frozenset(next)}`` from
    the union of both engines' extracted machines (protomodel diffs them
    transition-by-transition separately)."""

    def __init__(self, transitions: dict):
        self.transitions = {k: frozenset(v) for k, v in transitions.items()}

    def new_conn(self) -> ConnMonitor:
        return ConnMonitor(self)

    def replay(self, events, label: str = ""):
        """Replay one ring's swtrace events (7-tuples or JSON lists).
        Returns ``(violations, witnessed)``; each conn stops at its first
        divergence, other conns keep replaying."""
        conns: dict = {}
        dead: set = set()
        viols: list = []
        witnessed: set = set()
        trail: dict = {}
        seen_n: dict = {}
        for e in events:
            if len(e) < 6 or e[1] != PROTO_EV:
                continue
            conn_id, event = int(e[3]), str(e[5])
            if conn_id in dead:
                continue
            cm = conns.get(conn_id)
            if cm is None:
                cm = conns[conn_id] = self.new_conn()
                trail[conn_id] = []
                seen_n[conn_id] = 0
            tr = trail[conn_id]
            tr.append(event)
            del tr[:-10]
            seen_n[conn_id] += 1
            res = cm.step(event)
            if res is not None:
                cls, msg = res
                viols.append(Violation(label, conn_id, seen_n[conn_id], cls,
                                       msg, list(tr)))
                dead.add(conn_id)
        for cm in conns.values():
            witnessed |= cm.witnessed
        return viols, witnessed


def compile_monitor(root=None, runtime: bool = False):
    """Compile the monitor from the tree's extracted machines.  Returns
    ``(Monitor | None, problems: list[str])``.  With ``runtime=True``
    (core/monitor.py) the root defaults to the running package's own
    tree and a missing native source is tolerated (installed wheels ship
    no native/ -- the Python machine alone still checks both engines'
    rings, the vocabulary being shared)."""
    problems: list = []
    if root is None:
        root = Path(__file__).resolve().parents[2]
    root = Path(root)
    py, py_f = protomodel.extract_py_machine(root)
    trans: dict = {k: set(v[0]) for k, v in py.transitions.items()}
    cpp_path = root / "native" / "sw_engine.cpp"
    if cpp_path.is_file() or not runtime:
        cpp, cpp_f = protomodel.extract_cpp_machine(root)
        for k, (nexts, _f, _l) in cpp.transitions.items():
            trans.setdefault(k, set()).update(nexts)
        problems += [f.render() for f in cpp_f]
    problems += [f.render() for f in py_f]
    if not trans:
        problems.append("no transitions extracted -- monitor would be "
                        "vacuous")
        return None, problems
    return Monitor(trans), problems


# -------------------------------------------------- frame-name vocabulary


def _py_frame_names(root: Path, out: list):
    """frames.py FRAME_NAMES dict literal -> ({T_* name: event name}, line)."""
    rel = "starway_tpu/core/frames.py"
    path = root / rel
    if not path.is_file():
        out.append(Finding(rel, 1, "refine", "frames.py missing -- cannot "
                           "extract the protocol-event name table"))
        return None
    tree, err = parse_or_finding(path, rel)
    if tree is None:
        out.append(err)
        return None
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "FRAME_NAMES" \
                and isinstance(node.value, ast.Dict):
            table = {}
            for k, v in zip(node.value.keys, node.value.values):
                kname = ""
                if isinstance(k, ast.Name):
                    kname = k.id
                elif isinstance(k, ast.Attribute):
                    kname = k.attr
                if kname.startswith("T_") and isinstance(v, ast.Constant) \
                        and isinstance(v.value, str):
                    table[kname] = v.value
            return table, node.lineno
    out.append(Finding(rel, 1, "refine",
                       "FRAME_NAMES table not found in frames.py -- the "
                       "protocol-event channel has no canonical vocabulary "
                       "(DESIGN.md §22)"))
    return None


_CPP_CASE_RE = re.compile(r'case\s+(T_\w+)\s*:\s*return\s+"(\w+)"\s*;')


def _cpp_frame_names(root: Path, out: list):
    """The native proto_frame_name() switch -> ({T_* name: name}, line)."""
    rel = "native/sw_engine.cpp"
    path = root / rel
    if not path.is_file():
        out.append(Finding(rel, 1, "refine", "native engine source missing "
                           "-- cannot extract proto_frame_name()"))
        return None
    lines = read_text(path).splitlines()
    start = None
    for i, line in enumerate(lines):
        if "proto_frame_name" in line and "(" in line and ";" not in line:
            start = i
            break
    if start is None:
        out.append(Finding(rel, 1, "refine",
                           "proto_frame_name() not found in the native "
                           "engine -- the protocol-event channel has no "
                           "frame-name table there (DESIGN.md §22)"))
        return None
    table: dict = {}
    for i in range(start, min(start + 80, len(lines))):
        m = _CPP_CASE_RE.search(lines[i])
        if m:
            table[m.group(1)] = m.group(2)
        if lines[i].startswith("}"):
            break
    if not table:
        out.append(Finding(rel, start + 1, "refine",
                           "proto_frame_name() carries no case arms -- "
                           "vacuous frame-name table"))
        return None
    return table, start + 1


def _check_vocabulary(root: Path, out: list) -> None:
    f_frames = "starway_tpu/core/frames.py"
    f_cpp = "native/sw_engine.cpp"
    py_rec = _py_frame_names(root, out)
    cpp_rec = _cpp_frame_names(root, out)
    frames_path = root / f_frames
    t_consts = {}
    if frames_path.is_file():
        tree, _ = parse_or_finding(frames_path, f_frames)
        if tree is not None:
            t_consts = {k: v for k, v in module_int_constants(tree).items()
                        if k.startswith("T_")}
    if py_rec is None or cpp_rec is None:
        return
    py_tbl, py_line = py_rec
    cpp_tbl, cpp_line = cpp_rec
    for side, tbl, f, line in (("frames.py FRAME_NAMES", py_tbl, f_frames,
                                py_line),
                               ("proto_frame_name()", cpp_tbl, f_cpp,
                                cpp_line)):
        for tname, (val, tline) in sorted(t_consts.items()):
            if tname not in tbl:
                out.append(Finding(
                    f, line, "refine",
                    f"frame constant {tname} (= {val}) has no entry in "
                    f"{side} -- its frames would monitor as OTHER "
                    "(unknown-frame conn death in the model)"))
        for tname, name in sorted(tbl.items()):
            if tname not in t_consts:
                out.append(Finding(
                    f, line, "refine",
                    f"{side} maps {tname} which is not a frame constant "
                    "(stale table entry)"))
            if name != tname[2:]:
                out.append(Finding(
                    f, line, "refine",
                    f"{side} maps {tname} -> {name!r}; the canonical "
                    f"event name is the T_ suffix ({tname[2:]!r})"))
            if name not in protomodel.KNOWN_INPUTS:
                out.append(Finding(
                    f, line, "refine",
                    f"{side} name {name!r} is outside the protomodel "
                    "input vocabulary -- the monitor would reject it as "
                    "bad-event"))
    for tname in sorted(set(py_tbl) | set(cpp_tbl)):
        if py_tbl.get(tname) != cpp_tbl.get(tname):
            out.append(Finding(
                f_frames, py_line, "refine",
                f"frame-name tables disagree on {tname}: frames.py has "
                f"{py_tbl.get(tname)!r}, {f_cpp}:{cpp_line} has "
                f"{cpp_tbl.get(tname)!r} (two engines, one event "
                "vocabulary)"))
    # Tap-presence guard: the channel exists only if both engines still
    # emit it -- an engine that loses its taps makes every replay
    # vacuously green.
    conn_rel = "starway_tpu/core/conn.py"
    conn_path = root / conn_rel
    if conn_path.is_file() and "EV_PROTO" not in read_text(conn_path):
        out.append(Finding(conn_rel, 1, "refine",
                           "core/conn.py never emits EV_PROTO -- the "
                           "Python engine's protocol-event taps are gone "
                           "(replay would pass vacuously)"))
    cpp_path = root / f_cpp
    if cpp_path.is_file():
        text = read_text(cpp_path)
        if text.count("kEvProto") < 2:
            out.append(Finding(f_cpp, 1, "refine",
                               "sw_engine.cpp defines but never records "
                               "kEvProto -- the native engine's protocol-"
                               "event taps are gone"))


# --------------------------------------------------------------- corpus


def corpus_path(root: Optional[Path] = None) -> Path:
    """The tree-under-check's corpus when it carries one (so seeded
    trees can shadow it), else the installed package's own."""
    if root is not None:
        cand = root / "starway_tpu" / "analysis" / "refine_corpus.txt"
        if cand.is_file():
            return cand
    return Path(__file__).resolve().parent / "refine_corpus.txt"


def load_corpus(out: list, root: Optional[Path] = None) -> list:
    """[(name, expect, [events], lineno)] from the checked-in corpus.
    Format errors and a shrunken corpus are findings, never silent
    skips."""
    path = corpus_path(root)
    rel = "starway_tpu/analysis/refine_corpus.txt"
    if not path.is_file():
        out.append(Finding(rel, 1, "refine",
                           "event regression corpus missing -- the gate "
                           "would replay nothing (DESIGN.md §22)"))
        return []
    cases: list = []
    for i, raw in enumerate(read_text(path).splitlines(), 1):
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        parts = [p.strip() for p in line.split("|")]
        if len(parts) != 3 or not parts[0] or not parts[2]:
            out.append(Finding(rel, i, "refine",
                               f"malformed corpus line: {raw[:60]!r} "
                               "(want `name | ok|violation:<class> | "
                               "ev ev ...`)"))
            continue
        name, expect, evs = parts
        if expect != "ok" and not (expect.startswith("violation:")
                                   and expect[10:] in VIOLATION_CLASSES):
            out.append(Finding(rel, i, "refine",
                               f"corpus case {name!r} expects {expect!r} "
                               f"-- not `ok` or a known violation class "
                               f"{list(VIOLATION_CLASSES)}"))
            continue
        cases.append((name, expect, evs.split(), i))
    if len(cases) < CORPUS_FLOOR:
        out.append(Finding(rel, 1, "refine",
                           f"corpus replays only {len(cases)} cases -- "
                           f"below the {CORPUS_FLOOR}-case floor (pinned "
                           "sequences must not silently shrink)"))
    return cases


def _replay_case(mon: Monitor, events: list):
    """One corpus sequence through one fresh conn monitor.  Returns
    ``(outcome, witnessed)`` with outcome `ok` or `violation:<class>`."""
    cm = mon.new_conn()
    for ev in events:
        res = cm.step(ev)
        if res is not None:
            return f"violation:{res[0]}", cm.witnessed
    return "ok", cm.witnessed


# ------------------------------------------------------- ring-dump replay


def replay_dump(path, root=None) -> list:
    """Replay a swtrace ring dump (swtrace.write_ring_dump shape) or a
    flight-recorder dump through the monitor; returns Violations.  The
    ``refine --replay`` CLI surface (DESIGN.md §22)."""
    mon, problems = compile_monitor(root, runtime=True)
    if mon is None:
        raise SystemExit("refine: cannot compile the monitor: "
                         + "; ".join(problems))
    doc = json.loads(Path(path).read_text())
    rings = []
    if isinstance(doc, dict) and "workers" in doc:
        rings = [(w.get("worker", "?"), w.get("events", []))
                 for w in doc["workers"]]
    elif isinstance(doc, dict) and "events" in doc:
        rings = [(doc.get("worker", "?"), doc["events"])]
    else:
        raise SystemExit(f"refine: {path} is not a ring or flight dump "
                         "(want a `workers` or `events` key)")
    out: list = []
    for label, events in rings:
        viols, _ = mon.replay(events, label=label)
        out.extend(viols)
    return out


# ------------------------------------------------------------------ pass


def run(root: Path) -> list:
    out: list = []
    _check_vocabulary(root, out)
    mon, problems = compile_monitor(root)
    corpus_rel = "starway_tpu/analysis/refine_corpus.txt"
    if mon is None:
        # protomodel's own vacuity findings cover the empty-machine case;
        # refine must still refuse to pass standalone.
        out.append(Finding("starway_tpu/core/conn.py", 1, "refine",
                           "monitor compilation produced no transitions -- "
                           "conformance checking would be vacuous"))
        return out
    cases = load_corpus(out, root)
    witnessed: set = set()
    expected_hit: set = set()
    for name, expect, events, lineno in cases:
        outcome, seen = _replay_case(mon, events)
        witnessed |= seen
        if expect.startswith("violation:"):
            expected_hit.add(expect[10:])
        if outcome != expect:
            out.append(Finding(
                corpus_rel, lineno, "refine",
                f"corpus case {name!r}: expected {expect} but the monitor "
                f"answered {outcome} -- the model and its pinned event "
                "history disagree (engine transition changed? update the "
                "model AND the corpus together, DESIGN.md §22)"))
    # Every divergence class must stay detectable: a class no corpus case
    # pins (or that stopped firing, caught above) is a soft monitor.
    if cases:
        for cls in VIOLATION_CLASSES:
            if cls not in expected_hit:
                out.append(Finding(
                    corpus_rel, 1, "refine",
                    f"no corpus case pins divergence class `{cls}` -- the "
                    "monitor's detection of it is unregressable"))
    # Transition coverage: the corpus (plus justified waivers) must
    # witness every model arm.
    for key, why in sorted(UNWITNESSED_WAIVERS.items()):
        if key not in mon.transitions:
            out.append(Finding(
                corpus_rel, 1, "monitor-coverage",
                f"waiver for transition {key} names no model transition "
                "(stale waiver -- the arm is gone, drop the entry)"))
        if not str(why).strip():
            out.append(Finding(
                corpus_rel, 1, "monitor-coverage",
                f"waiver for transition {key} has no justification"))
    if cases:
        missing = [k for k in sorted(mon.transitions)
                   if k not in witnessed and k not in UNWITNESSED_WAIVERS]
        if missing:
            fmt = ", ".join(f"({s}, {i})" for s, i in missing)
            out.append(Finding(
                corpus_rel, 1, "monitor-coverage",
                f"model transition(s) never witnessed by the corpus and "
                f"not waived: {fmt} -- stale model arm, dead code, or a "
                "coverage gap (pin a traced sequence or add a justified "
                "UNWITNESSED_WAIVERS entry, DESIGN.md §22)"))
    return out
