"""Pass 1: the cross-engine contract checker.

Diffs the machine-readable contract surface between the Python engine
(core/frames.py, core/shmring.py, core/conn.py, core/native.py, errors.py,
core/engine.py) and the C++ engine (native/sw_engine.cpp, native/
sw_engine.h): frame-type constants, the 17-byte wire header, the shm ring
layout, doorbell bytes, the exported C ABI (incl. per-op ``timeout_s``),
stable failure-reason strings, negotiated handshake keys, and the engine
version string.  "Two engines, one contract" (CLAUDE.md) -- this pass is
what turns that sentence from a review checklist into a merge gate.
"""

from __future__ import annotations

import re
import struct
from pathlib import Path

from .base import Finding
from .cpp_model import CppModel, extract_cpp
from .py_model import PyModel, extract_py

# Python-side shm layout name -> C++ engine name (same segment bytes).
_SHM_PAIRS = [
    ("MAGIC", "SM_MAGIC"),
    ("GLOBAL_HDR", "SM_GLOBAL_HDR"),
    ("RING_HDR", "SM_RING_HDR"),
    ("DATA_OFF", "SM_DATA_OFF"),
    ("OFF_TAIL", "SM_OFF_TAIL"),
    ("OFF_HEAD", "SM_OFF_HEAD"),
    # §19 integrity slot-record header (len u32 + crc u32): the trailer
    # layout both engines frame ring writes with once "csum" negotiates.
    ("REC_HDR", "SM_REC_HDR"),
]

# errors.py constant -> (C++ literal name, stable keyword pinned by tests).
_REASON_PAIRS = [
    ("REASON_CANCELLED", "kCancelled", "cancel"),
    ("REASON_NOT_CONNECTED", "kNotConnected", "not connected"),
    ("REASON_TRUNCATED", "kTruncated", "truncated"),
    ("REASON_TIMEOUT", "kTimedOut", "timed out"),
    ("REASON_SESSION_EXPIRED", "kSessionExpired", "session expired"),
    ("REASON_CORRUPT", "kCorrupt", "corrupt"),
]

# Negotiated handshake keys: offered in HELLO, confirmed in HELLO_ACK.
# "sess" is the resilient-session negotiation (DESIGN.md §14; carries the
# sess_id/sess_epoch/sess_ack triple alongside it); "tr" is the swscope
# end-to-end trace-conn id (DESIGN.md §15); "rails"/"rail_of" are the
# multi-rail striping negotiation and the secondary-lane attach key
# (DESIGN.md §17); "fc" is the receiver-driven flow-control window
# advertisement (DESIGN.md §18); "csum" is the end-to-end integrity
# negotiation (DESIGN.md §19).
_HANDSHAKE_KEYS = ["ka", "sm", "devpull", "sess", "tr", "rails", "rail_of",
                   "fc", "csum"]

# Normalised C type -> acceptable canonical ctypes spellings.
_C2CTYPES = {
    "void*": {"c_void_p"},
    "char*": {"c_char_p", "POINTER(c_char)"},
    "uint64_t": {"c_uint64"},
    "uint64_t*": {"POINTER(c_uint64)"},
    "uint8_t": {"c_uint8"},
    "uint32_t": {"c_uint32"},
    "int": {"c_int"},
    "double": {"c_double"},
}

_C2RESTYPE = {
    "char*": "c_char_p",
    "void*": "c_void_p",
    "uint64_t": "c_uint64",
    "uint32_t": "c_uint32",
}


def _cb_pyname(typedef: str) -> str:
    # sw_done_cb -> _DONE_CB, sw_devpull_claim_cb -> _DEVPULL_CLAIM_CB
    return "_" + typedef[3:-3].upper() + "_CB"


def _expected_ctypes(ctype: str, callbacks: dict) -> set:
    if ctype in callbacks:
        return {_cb_pyname(ctype)}
    return _C2CTYPES.get(ctype, {ctype})


def _check_frames(py: PyModel, cpp: CppModel, out: list) -> None:
    f_frames = py.files["frames"]
    cpp_t = {k: v for k, v in cpp.constants.items() if re.fullmatch(r"T_\w+", k)}
    for name, (val, line) in sorted(py.frames.items()):
        if name not in cpp_t:
            out.append(Finding(f_frames, line, "contract-frames",
                               f"{name} = {val} has no counterpart in {cpp.cpp_file}"))
        elif cpp_t[name][0] != val:
            out.append(Finding(
                f_frames, line, "contract-frames",
                f"{name} = {val} but {cpp.cpp_file}:{cpp_t[name][1]} has "
                f"{name} = {cpp_t[name][0]} (two engines, one wire format)"))
    for name, (val, line) in sorted(cpp_t.items()):
        if name not in py.frames:
            out.append(Finding(cpp.cpp_file, line, "contract-frames",
                               f"{name} = {val} has no counterpart in {f_frames}"))

    if py.header_fmt is not None:
        fmt, line = py.header_fmt
        try:
            py_size = struct.calcsize(fmt)
        except struct.error:
            py_size = -1
        cpp_size = cpp.constants.get("HEADER_SIZE")
        if cpp_size is None:
            out.append(Finding(cpp.cpp_file, 1, "contract-header",
                               "HEADER_SIZE constexpr not found"))
        elif cpp_size[0] != py_size:
            out.append(Finding(
                f_frames, line, "contract-header",
                f"struct.Struct({fmt!r}) packs {py_size} bytes but "
                f"{cpp.cpp_file}:{cpp_size[1]} has HEADER_SIZE = {cpp_size[0]}"))
    else:
        out.append(Finding(f_frames, 1, "contract-header",
                           "HEADER = struct.Struct(...) not found"))

    # Striped-DATA sub-header layout (DESIGN.md §17): the SDATA_SUB pack
    # size must equal the C++ SDATA_SUB_SIZE constexpr.
    if py.sdata_sub_fmt is not None:
        fmt, line = py.sdata_sub_fmt
        try:
            py_size = struct.calcsize(fmt)
        except struct.error:
            py_size = -1
        cpp_size = cpp.constants.get("SDATA_SUB_SIZE")
        if cpp_size is None:
            out.append(Finding(cpp.cpp_file, 1, "contract-header",
                               "SDATA_SUB_SIZE constexpr not found"))
        elif cpp_size[0] != py_size:
            out.append(Finding(
                f_frames, line, "contract-header",
                f"SDATA_SUB struct.Struct({fmt!r}) packs {py_size} bytes but "
                f"{cpp.cpp_file}:{cpp_size[1]} has SDATA_SUB_SIZE = "
                f"{cpp_size[0]} (two engines, one stripe sub-header)"))
    else:
        out.append(Finding(f_frames, 1, "contract-header",
                           "SDATA_SUB = struct.Struct(...) not found"))


def _check_shm(py: PyModel, cpp: CppModel, out: list) -> None:
    f_shm = py.files["shmring"]
    for py_name, cpp_name in _SHM_PAIRS:
        if py_name not in py.shm:
            out.append(Finding(f_shm, 1, "contract-shm",
                               f"{py_name} layout constant not found"))
            continue
        val, line = py.shm[py_name]
        if cpp_name not in cpp.constants:
            out.append(Finding(cpp.cpp_file, 1, "contract-shm",
                               f"{cpp_name} constexpr not found"))
        elif cpp.constants[cpp_name][0] != val:
            cval, cline = cpp.constants[cpp_name]
            out.append(Finding(
                f_shm, line, "contract-shm",
                f"{py_name} = {val:#x} but {cpp.cpp_file}:{cline} has "
                f"{cpp_name} = {cval:#x} (same mapped segment on both engines)"))
    f_conn = py.files["conn"]
    for name in ("DB_DATA", "DB_STARVING"):
        if name not in py.doorbell:
            out.append(Finding(f_conn, 1, "contract-doorbell",
                               f"{name} constant not found"))
        elif name not in cpp.constants:
            out.append(Finding(cpp.cpp_file, 1, "contract-doorbell",
                               f"{name} constexpr not found"))
        elif cpp.constants[name][0] != py.doorbell[name][0]:
            val, line = py.doorbell[name]
            cval, cline = cpp.constants[name]
            out.append(Finding(
                f_conn, line, "contract-doorbell",
                f"{name} = {val} but {cpp.cpp_file}:{cline} has {cval}"))


def _check_abi(py: PyModel, cpp: CppModel, out: list) -> None:
    f_native = py.files["native"]
    for name, fn in sorted(cpp.functions.items()):
        if fn.args and name not in py.argtypes:
            out.append(Finding(
                cpp.h_file, fn.line, "contract-abi",
                f"{name} declared in {cpp.h_file} but {f_native} load() "
                "declares no argtypes for it"))
            continue
        if name in py.argtypes:
            got, line = py.argtypes[name]
            if len(got) != len(fn.args):
                out.append(Finding(
                    f_native, line, "contract-abi",
                    f"{name}: {len(got)} argtypes but {cpp.h_file}:{fn.line} "
                    f"declares {len(fn.args)} parameters "
                    f"({', '.join(fn.args) or 'void'})"))
            else:
                for i, (ctype, pytype) in enumerate(zip(fn.args, got)):
                    if pytype not in _expected_ctypes(ctype, cpp.callbacks):
                        out.append(Finding(
                            f_native, line, "contract-abi",
                            f"{name} arg {i}: {pytype} does not match C type "
                            f"`{ctype}` ({cpp.h_file}:{fn.line})"))
        want_res = _C2RESTYPE.get(fn.ret)
        have_res = py.restype.get(name)
        if want_res is not None:
            if have_res is None:
                out.append(Finding(
                    cpp.h_file, fn.line, "contract-abi",
                    f"{name} returns `{fn.ret}` but {f_native} declares no "
                    f"restype (ctypes default int truncates pointers)"))
            elif have_res[0] != want_res:
                out.append(Finding(
                    f_native, have_res[1], "contract-abi",
                    f"{name}: restype {have_res[0]} but C return type is "
                    f"`{fn.ret}` ({cpp.h_file}:{fn.line})"))
    for name, (_, line) in sorted(py.argtypes.items()):
        if name not in cpp.functions:
            out.append(Finding(
                f_native, line, "contract-abi",
                f"{name} has argtypes but is not declared in {cpp.h_file} "
                "(stale binding)"))
    for typedef, sig in sorted(cpp.callbacks.items()):
        pyname = _cb_pyname(typedef)
        if pyname not in py.cfunctypes:
            out.append(Finding(
                cpp.h_file, sig.line, "contract-abi",
                f"callback typedef {typedef} has no {pyname} CFUNCTYPE in "
                f"{f_native}"))
            continue
        got, line = py.cfunctypes[pyname]
        if len(got) != len(sig.args) + 1:  # CFUNCTYPE arg 0 is the restype
            out.append(Finding(
                f_native, line, "contract-abi",
                f"{pyname}: {len(got) - 1} args but {typedef} "
                f"({cpp.h_file}:{sig.line}) declares {len(sig.args)}"))
            continue
        # The return maps through the same C->ctypes table as the args
        # (void -> None), so a future non-void callback checks correctly.
        ret_ok = (got[0] == "None") if sig.ret == "void" \
            else got[0] in _expected_ctypes(sig.ret, cpp.callbacks)
        if not ret_ok:
            out.append(Finding(
                f_native, line, "contract-abi",
                f"{pyname}: return {got[0]} but {typedef} returns {sig.ret}"))
        for i, (ctype, pytype) in enumerate(zip(sig.args, got[1:])):
            if pytype not in _expected_ctypes(ctype, cpp.callbacks):
                out.append(Finding(
                    f_native, line, "contract-abi",
                    f"{pyname} arg {i}: {pytype} does not match C type "
                    f"`{ctype}` ({typedef}, {cpp.h_file}:{sig.line})"))


def _check_reasons(py: PyModel, cpp: CppModel, out: list) -> None:
    f_err = py.files["errors"]
    for py_name, cpp_name, keyword in _REASON_PAIRS:
        if py_name not in py.reasons:
            out.append(Finding(f_err, 1, "contract-reason",
                               f"{py_name} not found"))
            continue
        val, line = py.reasons[py_name]
        if keyword not in val.lower():
            out.append(Finding(
                f_err, line, "contract-reason",
                f"{py_name} = {val!r} lost its stable keyword {keyword!r} "
                "(pinned by tests/test_basic.py fail-callback matching)"))
        if cpp_name not in cpp.reasons:
            out.append(Finding(cpp.cpp_file, 1, "contract-reason",
                               f"{cpp_name} reason literal not found"))
        elif cpp.reasons[cpp_name][0] != val:
            cval, cline = cpp.reasons[cpp_name]
            out.append(Finding(
                f_err, line, "contract-reason",
                f"{py_name} = {val!r} but {cpp.cpp_file}:{cline} has "
                f"{cpp_name} = {cval!r} (engines must report identical reasons)"))


def _check_handshake(py: PyModel, cpp: CppModel, out: list) -> None:
    # Code-only surfaces on both sides: a key surviving in a comment or
    # docstring after the negotiation lines were deleted must still fail.
    f_engine = py.files["engine"]
    for key in _HANDSHAKE_KEYS:
        if key not in py.engine_strings:
            out.append(Finding(f_engine, 1, "contract-handshake",
                               f"handshake key \"{key}\" not referenced in "
                               "code by the Python engine"))
        if f'"{key}"' not in cpp.cpp_code:
            out.append(Finding(cpp.cpp_file, 1, "contract-handshake",
                               f"handshake key \"{key}\" not referenced in "
                               "code by the C++ engine"))


def _ev_cpp_name(py_name: str) -> str:
    """EV_SEND_POST -> kEvSendPost (the mechanical cross-engine mapping)."""
    return "kEv" + "".join(
        part.capitalize() for part in py_name[3:].lower().split("_"))


def _check_trace(py: PyModel, cpp: CppModel, out: list) -> None:
    """swtrace vocabulary parity (ISSUE 4): trace event-type constants and
    the counter-name vocabulary must exist, identically, in both engines --
    a counter or event added to one engine only is a finding."""
    f_sw = py.files["swtrace"]
    claimed = set()
    for name, (val, line) in sorted(py.trace_events.items()):
        cname = _ev_cpp_name(name)
        claimed.add(cname)
        if cname not in cpp.trace_events:
            out.append(Finding(
                f_sw, line, "contract-trace",
                f"{name} = {val!r} has no {cname} counterpart in "
                f"{cpp.cpp_file} (two engines, one trace vocabulary)"))
        elif cpp.trace_events[cname][0] != val:
            cval, cline = cpp.trace_events[cname]
            out.append(Finding(
                f_sw, line, "contract-trace",
                f"{name} = {val!r} but {cpp.cpp_file}:{cline} has "
                f"{cname} = {cval!r}"))
    for cname, (cval, cline) in sorted(cpp.trace_events.items()):
        if cname not in claimed:
            out.append(Finding(
                cpp.cpp_file, cline, "contract-trace",
                f"{cname} = {cval!r} has no EV_* counterpart in {f_sw}"))
    if py.counter_names is None:
        out.append(Finding(f_sw, 1, "contract-trace",
                           "COUNTER_NAMES tuple not found"))
        return
    if cpp.counter_names is None:
        out.append(Finding(cpp.cpp_file, 1, "contract-trace",
                           "kCounterNames[] array not found"))
        return
    py_names, py_line = py.counter_names
    cpp_names, cpp_line = cpp.counter_names
    for name in py_names:
        if name not in cpp_names:
            out.append(Finding(
                f_sw, py_line, "contract-trace",
                f"counter {name!r} is declared in COUNTER_NAMES only -- "
                f"{cpp.cpp_file}:{cpp_line} kCounterNames[] lacks it "
                "(a counter added to one engine only)"))
    for name in cpp_names:
        if name not in py_names:
            out.append(Finding(
                cpp.cpp_file, cpp_line, "contract-trace",
                f"counter {name!r} is declared in kCounterNames[] only -- "
                f"{f_sw}:{py_line} COUNTER_NAMES lacks it "
                "(a counter added to one engine only)"))
    # swscope gauge vocabulary (ISSUE 6): GAUGE_NAMES <-> kGaugeNames[],
    # vacuity-guarded like the counter pair above.
    f_tel = py.files["telemetry"]
    if py.gauge_names is None:
        out.append(Finding(f_tel, 1, "contract-trace",
                           "GAUGE_NAMES tuple not found"))
        return
    if cpp.gauge_names is None:
        out.append(Finding(cpp.cpp_file, 1, "contract-trace",
                           "kGaugeNames[] array not found"))
        return
    pg_names, pg_line = py.gauge_names
    cg_names, cg_line = cpp.gauge_names
    for name in pg_names:
        if name not in cg_names:
            out.append(Finding(
                f_tel, pg_line, "contract-trace",
                f"gauge {name!r} is declared in GAUGE_NAMES only -- "
                f"{cpp.cpp_file}:{cg_line} kGaugeNames[] lacks it "
                "(a gauge added to one engine only)"))
    for name in cg_names:
        if name not in pg_names:
            out.append(Finding(
                cpp.cpp_file, cg_line, "contract-trace",
                f"gauge {name!r} is declared in kGaugeNames[] only -- "
                f"{f_tel}:{pg_line} GAUGE_NAMES lacks it "
                "(a gauge added to one engine only)"))


def _check_pulse(py: PyModel, cpp: CppModel, out: list) -> None:
    """swpulse vocabulary parity (DESIGN.md §25): the histogram name
    vocabulary (HIST_NAMES <-> kHistNames[], ORDER included -- it is the
    sw_hists row order), the bucket resolution (HIST_BUCKETS <->
    kHistBuckets) and the stall sentinel reasons (STALL_REASONS <->
    kStallReasons[]) must exist, identically, in both engines.  Vacuity
    guarded: a missing vocabulary is a finding, never a silent pass."""
    f_sw = py.files["swtrace"]
    if py.hist_names is None:
        out.append(Finding(f_sw, 1, "contract-pulse",
                           "HIST_NAMES tuple not found"))
        return
    if cpp.hist_names is None:
        out.append(Finding(cpp.cpp_file, 1, "contract-pulse",
                           "kHistNames[] array not found"))
        return
    ph_names, ph_line = py.hist_names
    ch_names, ch_line = cpp.hist_names
    for name in ph_names:
        if name not in ch_names:
            out.append(Finding(
                f_sw, ph_line, "contract-pulse",
                f"histogram {name!r} is declared in HIST_NAMES only -- "
                f"{cpp.cpp_file}:{ch_line} kHistNames[] lacks it "
                "(a histogram added to one engine only)"))
    for name in ch_names:
        if name not in ph_names:
            out.append(Finding(
                cpp.cpp_file, ch_line, "contract-pulse",
                f"histogram {name!r} is declared in kHistNames[] only -- "
                f"{f_sw}:{ph_line} HIST_NAMES lacks it "
                "(a histogram added to one engine only)"))
    if set(ph_names) == set(ch_names) and ph_names != ch_names:
        out.append(Finding(
            cpp.cpp_file, ch_line, "contract-pulse",
            f"kHistNames[] order {ch_names} differs from "
            f"{f_sw}:{ph_line} HIST_NAMES {ph_names} -- the order is the "
            "sw_hists row order and must match"))
    if py.hist_buckets is None:
        out.append(Finding(f_sw, 1, "contract-pulse",
                           "HIST_BUCKETS constant not found"))
    elif "kHistBuckets" not in cpp.constants:
        out.append(Finding(cpp.cpp_file, 1, "contract-pulse",
                           "kHistBuckets constexpr not found"))
    elif cpp.constants["kHistBuckets"][0] != py.hist_buckets[0]:
        cval, cline = cpp.constants["kHistBuckets"]
        out.append(Finding(
            f_sw, py.hist_buckets[1], "contract-pulse",
            f"HIST_BUCKETS = {py.hist_buckets[0]} but "
            f"{cpp.cpp_file}:{cline} has kHistBuckets = {cval} "
            "(the bucket boundaries must be identical in both engines)"))
    if py.stall_reasons is None:
        out.append(Finding(f_sw, 1, "contract-pulse",
                           "STALL_REASONS tuple not found"))
        return
    if cpp.stall_reasons is None:
        out.append(Finding(cpp.cpp_file, 1, "contract-pulse",
                           "kStallReasons[] array not found"))
        return
    ps_names, ps_line = py.stall_reasons
    cs_names, cs_line = cpp.stall_reasons
    if ps_names != cs_names:
        out.append(Finding(
            cpp.cpp_file, cs_line, "contract-pulse",
            f"kStallReasons[] {cs_names} differs from {f_sw}:{ps_line} "
            f"STALL_REASONS {ps_names} -- stall reports must carry the "
            "same reason strings from both engines"))


def _check_version(cpp: CppModel, out: list) -> None:
    if cpp.version is None:
        out.append(Finding(cpp.cpp_file, 1, "contract-version",
                           "sw_version() string literal not found"))
        return
    if cpp.header_version is None:
        out.append(Finding(
            cpp.h_file, 1, "contract-version",
            'sw_engine.h is missing its `swcheck: engine-version "..."` '
            "annotation next to sw_version()"))
    elif cpp.header_version[0] != cpp.version[0]:
        out.append(Finding(
            cpp.h_file, cpp.header_version[1], "contract-version",
            f"header documents engine version {cpp.header_version[0]!r} but "
            f"{cpp.cpp_file}:{cpp.version[1]} returns {cpp.version[0]!r} "
            "(bump both when the protocol changes)"))


def _check_doctable(py: PyModel, out: list) -> None:
    """The frames.py docstring frame table must list exactly the T_*
    constants, with every row keeping to the table's column grid -- the
    doc can then never drift from the code (ISSUE 2 satellite)."""
    f_frames = py.files["frames"]
    doc = py.frames_doc
    if not doc:
        out.append(Finding(f_frames, 1, "contract-doctable",
                           "frames.py module docstring not found"))
        return
    lines = doc.splitlines()
    seps = [i for i, ln in enumerate(lines)
            if re.fullmatch(r"=+( +=+)+ *", ln)]
    if len(seps) < 3:
        out.append(Finding(f_frames, 1, "contract-doctable",
                           "frame table (reST grid with 3 `=== ===` rules) "
                           "not found in the module docstring"))
        return
    grid = lines[seps[0]]
    gaps = [i for i, ch in enumerate(grid) if ch == " "]
    want = {name[2:] for name in py.frames}
    seen = set()
    for i in range(seps[1] + 1, seps[2]):
        row = lines[i]
        if not row.strip():
            continue
        lineno = i + 1  # docstring starts on file line 1
        name = row.split()[0]
        bad_grid = [g for g in gaps if g < len(row) and row[g] != " "]
        if name not in want:
            out.append(Finding(
                f_frames, lineno, "contract-doctable",
                f"table row {name!r} matches no T_* frame constant "
                "(garbled row or stale docs)"))
        elif bad_grid:
            seen.add(name)
            out.append(Finding(
                f_frames, lineno, "contract-doctable",
                f"table row {name!r} overruns its column at offset(s) "
                f"{bad_grid} (row no longer aligns with the `===` grid)"))
        else:
            seen.add(name)
    for name in sorted(want - seen):
        out.append(Finding(
            f_frames, seps[1] + 1, "contract-doctable",
            f"frame type T_{name} is missing from the docstring table"))


def run(root: Path) -> list:
    py = extract_py(root)
    cpp = extract_cpp(root)
    out: list = []
    # Vacuity guard: an extractor that silently comes up empty would turn
    # the whole gate into a no-op.  Empty models are findings, not passes.
    for ok, where, what in [
        (py.frames, py.files["frames"], "T_* frame constants"),
        (py.argtypes, py.files["native"], "lib.*.argtypes declarations"),
        (py.trace_events, py.files["swtrace"], "EV_* trace event constants"),
        (cpp.constants, cpp.cpp_file, "constexpr constants"),
        (cpp.functions, cpp.h_file, "sw_* ABI declarations"),
    ]:
        if not ok:
            out.append(Finding(where, 1, "contract-abi",
                               f"extractor found no {what} -- contract "
                               "checking would be vacuous (file moved or "
                               "extraction surface changed?)"))
    if any(f.message.startswith("extractor found no") for f in out):
        return out
    _check_frames(py, cpp, out)
    _check_shm(py, cpp, out)
    _check_abi(py, cpp, out)
    _check_reasons(py, cpp, out)
    _check_handshake(py, cpp, out)
    _check_trace(py, cpp, out)
    _check_pulse(py, cpp, out)
    _check_version(cpp, out)
    _check_doctable(py, out)
    return out
