"""Pass 4: pytest ``slow``-marker guard over tests/.

Tier-1 CI runs ``pytest -m 'not slow'`` inside an 870 s budget; the soak
tests that move multi-GiB payloads live behind the ``slow`` marker
(registered in pyproject.toml).  This pass flags any test function that
folds a >= 2 GiB byte count out of literals without carrying the marker,
so a new soak cannot silently land inside the tier-1 budget.  (The
existing 1 GiB in-flight buffers in test_basic.py/test_sm.py are below
the threshold by design -- they are the reference-pinned contract tests.)
"""

from __future__ import annotations

import ast
from pathlib import Path

from .base import Finding, parse_or_finding, rel, test_files
from .py_model import _const_eval, module_int_constants

_THRESHOLD = 2 << 30  # 2 GiB: "multi-GiB" starts here
#: Ints at/above this are not byte counts: 64-bit tag masks
#: (0xFFFFFFFFFFFFFFFF wildcards) and probe-tag constants live up there.
_CEILING = 1 << 40


def _has_slow_mark(decorators) -> bool:
    for dec in decorators:
        for node in ast.walk(dec):
            if isinstance(node, ast.Attribute) and node.attr == "slow":
                return True
    return False


def _module_slow(tree: ast.Module) -> bool:
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "pytestmark"
            for t in node.targets
        ):
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Attribute) and sub.attr == "slow":
                    return True
    return False


def _max_folded(node: ast.AST, env: dict) -> int:
    """Largest integer any (sub)expression in ``node`` folds to."""
    best = 0
    for sub in ast.walk(node):
        if isinstance(sub, (ast.BinOp, ast.Constant, ast.Name)):
            v = _const_eval(sub, env)
            if v is not None and best < v < _CEILING:
                best = v
    return best


def run(root: Path) -> list:
    out: list = []
    for path in test_files(root):
        relpath = rel(root, path)
        tree, err = parse_or_finding(path, relpath)
        if tree is None:
            out.append(err)
            continue
        if _module_slow(tree):
            continue
        env = {k: v for k, (v, _) in module_int_constants(tree).items()}
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not node.name.startswith("test_"):
                continue
            if _has_slow_mark(node.decorator_list):
                continue
            # Decorators count too: a parametrized payload size
            # (@pytest.mark.parametrize("size", [4 << 30])) is the house
            # style for soaks and must not evade the guard.
            biggest = max(
                [_max_folded(stmt, env) for stmt in node.body]
                + [_max_folded(dec, env) for dec in node.decorator_list],
                default=0)
            if biggest >= _THRESHOLD:
                out.append(Finding(
                    relpath, node.lineno, "marker-slow",
                    f"{node.name} folds a {biggest / (1 << 30):.1f} GiB "
                    "constant but carries no @pytest.mark.slow -- multi-GiB "
                    "payload tests must stay out of the tier-1 870 s budget"))
    return out
