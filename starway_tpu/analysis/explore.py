"""Pass: explore -- bounded exhaustive model checking of the session layer.

scripts/session_chaos.py *samples* fault schedules against the live
engines; this pass makes the same oracle **total** over an abstract model
of the §14 resilient-session machine: two peers, a bounded workload
(two data sends and a flush), and every interleaving of the FaultProxy
fault vocabulary -- connection kills, duplicated sequenced units,
adjacent reorders, and a peer restart (epoch bump) -- enumerated
exhaustively instead of sampled.  The model is deliberately small enough
to exhaust (a few thousand states, >1k complete schedules) and
deliberately faithful to DESIGN.md §14's load-bearing rules:

* frames are sequenced at submit and journaled until the peer's
  cumulative ACK covers them; replay resends whole frames in order from
  the journal past the ACK carried by the resume handshake;
* the receiver drops any frame whose seq it has already processed
  (exactly-once across replay overlap) and resets on a seq gap;
* FLUSH_ACK is itself sequenced/journaled (a barrier ACK lost with the
  conn must replay, modeled as the receiver re-offering it on resume);
* a resume dial answered with a different epoch expires the session;
  grace expiry is terminal and fails everything with a stable reason.

**Invariants** (each backed by a seeded model mutation in
tests/test_swcheck.py that makes it fire):

=================  =====================================================
exactly-once       no data payload is delivered twice (``no-dedup``)
journal-trim       ACK-driven trim never drops an unacked frame, and
                   every frame the receiver may still need is
                   replayable (``trim-overshoot``)
flush-order        a completed flush barrier proves every data frame
                   submitted before it was delivered (``ack-overclaim``)
epoch              sessions never resume across an epoch change, and
                   epochs never regress (``resume-ignores-epoch``)
quiescence         from every reachable state the run ends -- every op
                   completes or fails with a stable reason; no silent
                   deadlock states (``no-replay``)
credit-conservation
                   the §18 flow-control window is never permanently
                   lost across kill/resume schedules: at clean
                   quiescence the sender's credits equal the advertised
                   window.  Grants lost in flight are healed by the
                   resume-time full-window reset; replayed frames
                   re-debit and their (possibly duplicate) deliveries
                   re-grant, clamped at the window (``credit-leak``:
                   a resume that carries stale credits across the
                   incarnation leaks the in-flight grants forever)
=================  =====================================================

The pass also refuses to run vacuously: the Python engine's extracted
state machine (analysis/protomodel.py) must still contain the session
transitions this model abstracts ((estab, SEQ), (estab, lost),
(suspended, resume), (suspended, expire)); if extraction lost them, the
model no longer describes the code and that is a finding, not a pass.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from pathlib import Path
from typing import Optional

from .base import Finding
from . import protomodel

#: Model bounds: 2 data ops + 1 flush, <=3 in-flight sequenced frames,
#: and per-schedule fault budgets (2 kills, 1 dup, 1 reorder, 1 restart,
#: 2 gap-resets before grace gives up).  Small enough to exhaust on the
#: 1-core box inside the gate budget, large enough that every invariant
#: has room to break (a replay overlapping live frames needs 2 kills).
OPS = ("d1", "d2", "flush")
DATA_OPS = tuple(o for o in OPS if o != "flush")
MAX_INFLIGHT = 3
BUDGET_KILLS = 2
BUDGET_DUPS = 1
BUDGET_REORDERS = 1
BUDGET_RESTARTS = 1
BUDGET_RESETS = 2

#: Seeded model mutations -> the invariant each must trip (the
#: "assert the checker can actually see each failure" table).
MUTATIONS = {
    "no-dedup": "exactly-once",
    "trim-overshoot": "journal-trim",
    "ack-overclaim": "flush-order",
    "resume-ignores-epoch": "epoch",
    "no-replay": "quiescence",
    "credit-leak": "credit-conservation",
}

INVARIANTS = ("exactly-once", "journal-trim", "flush-order", "epoch",
              "quiescence", "credit-conservation")

#: §18 flow-control window in abstract units (each data op debits one).
FC_W = 2


@dataclass(frozen=True)
class _State:
    ops_left: tuple = OPS
    tx_seq: int = 0
    journal: tuple = ()          # ((seq, kind), ...) unacked, seq order
    peer_acked: int = 0
    c2s: tuple = ()              # in-flight sequenced frames (seq, kind)
    s2c: tuple = ()              # in-flight ("ack", cum) / ("fack",)
    rx_cum: int = 0
    acked_sent: int = 0
    delivered: tuple = ()        # data kinds, delivery order
    r_fack_owed: bool = False    # receiver's journaled barrier ACK
    flush_state: str = "none"    # none | sent | done | failed
    credits: int = FC_W            # §18 sender window remainder
    suspended: bool = False
    expired: bool = False
    epoch_s: int = 0
    epoch_r: int = 0
    kills: int = BUDGET_KILLS
    dups: int = BUDGET_DUPS
    reorders: int = BUDGET_REORDERS
    restarts: int = BUDGET_RESTARTS
    resets: int = BUDGET_RESETS


def _is_terminal(s: _State) -> bool:
    if s.suspended:
        return False  # resume/expire/restart always enabled
    if s.expired:
        return True   # channels cleared at expiry; ops failed stably
    return (not s.ops_left and not s.c2s and not s.s2c
            and s.flush_state != "sent")


@dataclass
class _Run:
    mutation: Optional[str] = None
    schedules: int = 0
    states: int = 0
    violations: list = field(default_factory=list)  # (invariant, msg, trace)
    _seen_viol: set = field(default_factory=set)

    def violate(self, invariant: str, msg: str, trace: tuple) -> None:
        if invariant not in self._seen_viol:
            self._seen_viol.add(invariant)
            self.violations.append((invariant, msg, trace))


def _gap_reset(s: _State, run: _Run, trace: tuple) -> _State:
    """The receiver saw an unrepairable seq gap: reset the conn.  With
    grace budget left this is a suspend (replay heals it); exhausted,
    the session expires -- the model's grace-window abstraction."""
    if s.resets > 0:
        return replace(s, suspended=True, c2s=(), s2c=(),
                       resets=s.resets - 1)
    return _expire(s)


def _expire(s: _State) -> _State:
    return replace(s, expired=True, suspended=False, c2s=(), s2c=(),
                   ops_left=(),
                   flush_state="failed" if s.flush_state == "sent"
                   else s.flush_state)


def _enabled(s: _State) -> list:
    acts = []
    if s.expired:
        return acts
    if s.suspended:
        acts.append("resume")
        acts.append("expire")
        if s.restarts > 0:
            acts.append("restart")
        return acts
    if s.ops_left and len(s.c2s) < MAX_INFLIGHT:
        # §18 gate: data submits park (are disabled) with the window dry;
        # grants, or the resume-time reset, re-enable them.
        if s.ops_left[0] == "flush" or s.credits > 0:
            acts.append("submit")
    if s.c2s:
        acts.append("deliver")
    if s.s2c:
        acts.append("deliver_ack")
    if s.kills > 0:
        acts.append("kill")
    if s.dups > 0 and s.c2s:
        acts.append("dup")
    if s.reorders > 0 and len(s.c2s) >= 2:
        acts.append("reorder")
    return acts


def _apply(s: _State, act: str, run: _Run, trace: tuple) -> _State:
    mut = run.mutation
    if act == "submit":
        kind = s.ops_left[0]
        seq = s.tx_seq + 1
        return replace(
            s, ops_left=s.ops_left[1:], tx_seq=seq,
            journal=s.journal + ((seq, kind),),
            c2s=s.c2s + ((seq, kind),),
            credits=s.credits - (0 if kind == "flush" else 1),
            flush_state="sent" if kind == "flush" else s.flush_state)
    if act == "deliver":
        (seq, kind), rest = s.c2s[0], s.c2s[1:]
        if seq <= s.rx_cum and mut != "no-dedup":
            # Dup: drained and dropped -- but its (re-)debited window
            # still returns (§18 credit conservation).
            s2c = s.s2c
            if kind != "flush":
                s2c = s2c + (("credit",),)
            return replace(s, c2s=rest, s2c=s2c)
        if seq <= s.rx_cum or seq == s.rx_cum + 1:
            # In-order (or, under no-dedup, a replayed duplicate).
            new_cum = max(s.rx_cum, seq)
            delivered = s.delivered
            fack_owed = s.r_fack_owed
            s2c = s.s2c
            if kind != "flush":
                if kind in delivered:
                    run.violate(
                        "exactly-once",
                        f"data op {kind!r} (seq {seq}) delivered twice",
                        trace + (act,))
                delivered = delivered + (kind,)
                # Matched/drained: the window grant goes back (never
                # inflight-capped -- grants are deltas, dropping one
                # would leak window forever).
                s2c = s2c + (("credit",),)
            else:
                fack_owed = True
                # Journaled barrier ACK: it retries from the receiver's
                # tx queue in the real engine, so the model must never
                # silently drop it (credit entries would otherwise starve
                # its inflight slot forever once kills are exhausted).
                s2c = s2c + (("fack",),)
            if new_cum > s.acked_sent and len(s2c) < MAX_INFLIGHT:
                s2c = s2c + (("ack", new_cum),)
            return replace(s, c2s=rest, rx_cum=new_cum, delivered=delivered,
                           r_fack_owed=fack_owed, s2c=s2c,
                           acked_sent=max(s.acked_sent, new_cum))
        return _gap_reset(replace(s, c2s=rest), run, trace)
    if act == "deliver_ack":
        msg, rest = s.s2c[0], s.s2c[1:]
        if msg[0] == "credit":
            # Clamped at the window: a wire-duplicated grant (the dup
            # fault hitting a credit-bearing schedule) must never mint
            # credit -- the engines clamp identically.
            return replace(s, s2c=rest,
                           credits=min(FC_W, s.credits + 1))
        if msg[0] == "ack":
            cum = msg[1]
            if mut == "trim-overshoot":
                cum += 1
            kept = tuple(e for e in s.journal if e[0] > cum)
            for e in s.journal:
                if e[0] <= cum and e[0] > msg[1]:
                    run.violate(
                        "journal-trim",
                        f"trim for cumulative ACK {msg[1]} dropped "
                        f"unacked frame seq {e[0]} ({e[1]!r})",
                        trace + (act,))
            return replace(s, s2c=rest, journal=kept,
                           peer_acked=max(s.peer_acked, msg[1]))
        # flush ack: the barrier completed -- every data op submitted
        # before the flush must already have been delivered.
        missing = [o for o in DATA_OPS
                   if o not in s.ops_left and o not in s.delivered]
        if s.flush_state == "sent" and missing:
            run.violate(
                "flush-order",
                f"flush barrier completed with data op(s) {missing} "
                "never delivered",
                trace + (act,))
        return replace(s, s2c=rest,
                       flush_state="done" if s.flush_state == "sent"
                       else s.flush_state)
    if act == "kill":
        return replace(s, suspended=True, c2s=(), s2c=(), kills=s.kills - 1)
    if act == "dup":
        # FaultProxy `duplicate`: a sequenced unit rides the wire twice,
        # adjacently -- the replay-overlap shape seq dedup must absorb.
        return replace(s, c2s=(s.c2s[0],) + s.c2s, dups=s.dups - 1)
    if act == "reorder":
        # FaultProxy `reorder`: one adjacent pair swapped; the receiver
        # sees an unrepairable gap and resets (replay heals it).
        return replace(s, c2s=(s.c2s[1], s.c2s[0]) + s.c2s[2:],
                       reorders=s.reorders - 1)
    if act == "resume":
        if s.epoch_s != s.epoch_r:
            if mut == "resume-ignores-epoch":
                run.violate(
                    "epoch",
                    f"session resumed across an epoch change "
                    f"({s.epoch_s} != {s.epoch_r})",
                    trace + (act,))
                # Fall through: the buggy engine resumes anyway (and the
                # wiped receiver state now double-delivers downstream).
            else:
                return _expire(s)
        reported = s.rx_cum
        rx_cum = s.rx_cum
        if mut == "ack-overclaim":
            # The resume handshake claims one frame it never processed.
            reported += 1
            rx_cum += 1
        kept = tuple(e for e in s.journal if e[0] > reported)
        if mut != "ack-overclaim":
            for e in s.journal:
                if e[0] <= reported and e[0] > s.peer_acked \
                        and e[0] > s.rx_cum:
                    run.violate(
                        "journal-trim",
                        f"resume trim dropped frame seq {e[0]} the "
                        "receiver never processed",
                        trace + (act,))
        replay = kept
        if mut == "no-replay":
            replay = ()
        s2c = ()
        if s.r_fack_owed:
            # The receiver's journaled barrier ACK rides the new
            # incarnation (FLUSH_ACK is a sequenced session frame).
            s2c = (("fack",),)
        # §18: fresh window per incarnation -- stale debits and in-flight
        # grants (wiped with s2c at the kill) are healed by resetting to
        # the full window minus the re-debited replay frames.  The
        # credit-leak mutation carries the old remainder across instead,
        # leaking every grant the kill swallowed.
        replay_debit = sum(1 for e in replay if e[1] != "flush")
        credits = (s.credits if mut == "credit-leak"
                   else FC_W - replay_debit)
        return replace(s, suspended=False, journal=kept, c2s=replay,
                       s2c=s2c, rx_cum=rx_cum, acked_sent=rx_cum,
                       credits=credits,
                       peer_acked=max(s.peer_acked, reported))
    if act == "restart":
        # The acceptor process restarted: new epoch, session state gone.
        new_r = s.epoch_r + 1
        if new_r < s.epoch_r:
            run.violate("epoch", "epoch regressed", trace + (act,))
        return replace(s, restarts=s.restarts - 1, epoch_r=new_r,
                       rx_cum=0, acked_sent=0, r_fack_owed=False)
    if act == "expire":
        return _expire(s)
    raise AssertionError(f"unknown action {act}")


def check(mutation: Optional[str] = None, max_states: int = 200_000) -> dict:
    """Exhaust the model under ``mutation`` (None = faithful §14 model).
    Returns ``{"schedules", "states", "violations"}``; ``schedules`` is
    the number of distinct complete fault schedules (root-to-terminal
    action sequences, counted by DP over the memoized state graph)."""
    if mutation is not None and mutation not in MUTATIONS:
        raise ValueError(f"unknown mutation {mutation!r} "
                         f"(choose from {sorted(MUTATIONS)})")
    run = _Run(mutation=mutation)
    paths: dict = {}

    def visit(s: _State, trace: tuple, depth: int) -> int:
        if s in paths:
            return paths[s]
        if depth > 400 or len(paths) > max_states:
            # Far beyond any faithful-model bound: a mutation introduced
            # unbounded behavior -- the no-silent-deadlock oracle owns it.
            run.violate("quiescence",
                        "state space exploded past the model bound "
                        "(runaway replay/reset loop)", trace)
            paths[s] = 0
            return 0
        if _is_terminal(s):
            paths[s] = 1
            return 1
        acts = _enabled(s)
        if not acts:
            run.violate(
                "quiescence",
                "deadlock: ops pending but no action enabled "
                f"(flush_state={s.flush_state!r}, journal={s.journal})",
                trace)
            paths[s] = 0
            return 0
        paths[s] = 0  # cycle guard: a revisit mid-expansion counts 0 paths
        total = 0
        for act in acts:
            total += visit(_apply(s, act, run, trace), trace + (act,),
                           depth + 1)
        paths[s] = total
        return total

    init = _State()
    schedules = visit(init, (), 0)
    # Completeness at clean quiescence: every terminal non-expired state
    # must have delivered each data op exactly once and completed the
    # flush -- a lost frame that deadlocks nothing still fails here.
    for s in list(paths):
        if _is_terminal(s) and not s.expired:
            if tuple(sorted(s.delivered)) != tuple(sorted(DATA_OPS)):
                run.violate(
                    "exactly-once",
                    f"clean quiescence with delivered={s.delivered!r} "
                    f"(want each of {DATA_OPS} exactly once)", ())
            if s.flush_state != "done":
                run.violate(
                    "quiescence",
                    "clean quiescence with the flush barrier never "
                    "completed", ())
            if s.credits != FC_W:
                run.violate(
                    "credit-conservation",
                    f"clean quiescence with credits={s.credits} -- the "
                    f"§18 window ({FC_W}) was permanently lost across "
                    "the schedule", ())
    return {"schedules": schedules, "states": len(paths),
            "violations": run.violations}


#: Session transitions the model abstracts; their disappearance from the
#: extracted machine means the model no longer describes the code.
_REQUIRED_TRANSITIONS = (
    ("estab", "SEQ"), ("estab", "ACK"), ("estab", "lost"),
    ("suspended", "resume"), ("suspended", "expire"),
)


def run(root: Path) -> list:
    out: list = []
    machine, extract_findings = protomodel.extract_py_machine(root)
    # Extraction failures are protomodel's findings; here they only gate
    # vacuity (don't double-report).
    missing = [key for key in _REQUIRED_TRANSITIONS
               if key not in machine.transitions]
    if missing and not extract_findings:
        out.append(Finding(
            "starway_tpu/core/session.py", 1, "proto-explore",
            f"the session model's transitions {missing} are no longer "
            "extracted from the engine -- the model checker would verify "
            "a machine the code does not implement (update the model or "
            "the extraction grammar, DESIGN.md §16)"))
        return out
    result = check(None)
    for invariant, msg, trace in result["violations"]:
        out.append(Finding(
            "starway_tpu/core/session.py", 1, "proto-explore",
            f"invariant `{invariant}` violated: {msg} "
            f"[schedule: {' -> '.join(trace) or '<initial>'}]"))
    if result["schedules"] < 1000:
        out.append(Finding(
            "starway_tpu/core/session.py", 1, "proto-explore",
            f"only {result['schedules']} fault schedules enumerated -- "
            "the bounded exploration lost coverage (model bounds "
            "shrunk?)"))
    return out
