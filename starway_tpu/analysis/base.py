"""Shared plumbing for the swcheck passes: findings, waivers, repo layout.

Everything in starway_tpu/analysis is stdlib-only (ast/re/struct/pathlib):
the checker must run in a bare CI venv and inside release_smoke.sh before
any dependency is installed, and it must be runnable against a *copy* of
the tree (tests/test_swcheck.py seeds violations into tmpdir mutations),
so no pass may import the modules it checks -- sources are parsed, never
executed.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Iterable, Optional

#: Every rule a finding may carry (and a waiver may name).  Kept in one
#: place so --rules output, waiver validation, and the docs stay in sync.
RULES = {
    "contract-frames": "frame-type constants differ between engines",
    "contract-header": "wire header pack size differs between engines",
    "contract-shm": "shared-memory ring layout differs between engines",
    "contract-doorbell": "doorbell byte values differ between engines",
    "contract-abi": "sw_engine.h ABI vs core/native.py ctypes signatures",
    "contract-reason": "stable failure-reason strings drifted",
    "contract-handshake": "negotiated handshake key missing on one side",
    "contract-version": "native engine version string drifted",
    "contract-doctable": "frames.py docstring frame table drifted",
    "contract-trace": "swtrace event/counter vocabulary differs between engines",
    "contract-pulse": "swpulse histogram/stall vocabulary or bucket "
                      "resolution differs between engines",
    "callback-under-lock": "user callback invoked while holding a worker lock",
    "blocking-call": "blocking call reachable on the engine thread",
    "reachable-blocking": "blocking call reachable while a worker lock is held",
    "lock-order": "lock acquisition order forms a cycle (deadlock risk)",
    "duck-attr": "attribute read unsatisfied by a duck-typed protocol member",
    "lint-coverage": "runtime module outside the swcheck lint surface",
    "proto-state": "protocol state machines of the two engines disagree",
    "proto-explore": "session-model invariant violated under a fault schedule",
    "proto-compose": "composed-plane invariant (sessions x striping x fc x "
                     "integrity) violated under a fault schedule",
    "wire-diff": "frame/record decoders diverge between the engines (or "
                 "from the contract-derived oracle) on identical bytes",
    "taint-integrity": "payload bytes can reach a user buffer or callback "
                       "before the §19 CRC verify dominates them",
    "refine": "model<->code conformance broken: protocol-event vocabulary "
              "drifted, or a pinned event history diverges from the "
              "monitor compiled from the engines' own state machines",
    "monitor-coverage": "a protocol-model transition no pinned run ever "
                        "witnesses (stale model arm or dead code)",
    "cost-budget": "hot-path cost vector drifted from its "
                   "analysis/cost_budgets.txt pin (over = regression; "
                   "under = lower the pin to ratchet it in)",
    "cost-model": "swcost extraction stale: an anchor function, rx arm, "
                  "ledger row, or runtime-twin counter site is gone",
    "cost-site": "hot-path syscall/copy/alloc/lock site excluded from the "
                 "swcost ledger (waiver target; counted otherwise)",
    "layering-jax": "jax imported under core/ (device.py owns that boundary)",
    "layering-reshard": "reshard/-above-core/ boundary crossed (core/ "
                        "imports reshard, or jax bound outside reshard/api.py)",
    "marker-slow": "multi-GiB test payload without a `slow` marker",
    "hotpath-copy": "full-payload bytes()/.tobytes() copy on a core/ data path",
    "bad-waiver": "swcheck waiver without a justification string",
    "parse-error": "a scanned Python file does not parse",
}


@dataclass
class Finding:
    file: str  # repo-relative, /-separated
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.file}:{self.line}: [{self.rule}] {self.message}"


def find_root(start: Optional[str] = None) -> Path:
    """Resolve the repo root: --root wins, else cwd or the tree this
    installed/checked-out package lives in (parent of starway_tpu/)."""
    if start is not None:
        return Path(start).resolve()
    candidates = [Path.cwd(), Path(__file__).resolve().parents[2]]
    for p in candidates:
        if (p / "starway_tpu").is_dir() and (p / "native").is_dir():
            return p
    raise SystemExit(
        "swcheck: cannot locate the repo root (need starway_tpu/ and "
        "native/ side by side); pass --root"
    )


def rel(root: Path, path: Path) -> str:
    try:
        return path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return path.as_posix()


# Parse-once cache, cleared per run_all invocation: the gate runs many
# passes over the same small file set, and before this cache every pass
# re-read and re-parsed each source (the `explore` pass put the repeated
# cost over budget on the 1-core box).  Keyed by resolved path; safe
# because passes only *walk* trees, never mutate them.
_TEXT_CACHE: dict = {}
_TREE_CACHE: dict = {}


def clear_caches() -> None:
    _TEXT_CACHE.clear()
    _TREE_CACHE.clear()


def read_text(path: Path) -> str:
    key = str(path)
    if key not in _TEXT_CACHE:
        _TEXT_CACHE[key] = path.read_text(encoding="utf-8", errors="replace")
    return _TEXT_CACHE[key]


# --------------------------------------------------------------- waivers

_WAIVER_RE = re.compile(
    r"(?:#|//|/\*)\s*swcheck:\s*allow\(([\w\-, ]+)\)(?::\s*(.*?))?\s*(?:\*/\s*)?$"
)


def _waivers_on_line(text_lines: list[str], lineno: int) -> list[tuple[set, str, int]]:
    """Waiver comments attached to ``lineno`` (1-based): the line itself or
    the line directly above it.  Yields (rules, justification, waiver_line)."""
    out = []
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(text_lines):
            m = _WAIVER_RE.search(text_lines[ln - 1])
            if m:
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                out.append((rules, (m.group(2) or "").strip(), ln))
    return out


def apply_waivers(root: Path, findings: Iterable[Finding]) -> list[Finding]:
    """Suppress findings carrying an explicit justified waiver.  A waiver
    naming the rule but missing the ``: why`` justification does NOT
    suppress -- it turns into a bad-waiver finding (the policy: every
    exception is written down)."""
    out: list[Finding] = []
    cache: dict[str, list[str]] = {}
    for f in findings:
        path = root / f.file
        if f.file not in cache:
            try:
                cache[f.file] = read_text(path).splitlines()
            except OSError:
                cache[f.file] = []
        waived = False
        for rules, why, waiver_line in _waivers_on_line(cache[f.file], f.line):
            if f.rule in rules:
                if why:
                    waived = True
                else:
                    # Anchored at the WAIVER's line with scan_bad_waivers'
                    # exact wording, so run_all's dedupe collapses the pair
                    # into one finding per bad waiver.
                    out.append(Finding(
                        f.file, waiver_line, "bad-waiver",
                        "waiver has no justification "
                        "(use `# swcheck: allow(rule): why`)",
                    ))
                    waived = True  # the original is replaced, not doubled
                break
        if not waived:
            out.append(f)
    return out


def scan_bad_waivers(root: Path, files: Iterable[Path]) -> list[Finding]:
    """Any waiver comment anywhere in the scanned set with an unknown rule
    name or an empty justification is itself a finding: waivers are part
    of the contract surface and must stay auditable."""
    out: list[Finding] = []
    for path in files:
        try:
            lines = read_text(path).splitlines()
        except OSError:
            continue
        for i, line in enumerate(lines, 1):
            m = _WAIVER_RE.search(line)
            if m is None:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            why = (m.group(2) or "").strip()
            unknown = rules - set(RULES)
            if unknown:
                out.append(Finding(
                    rel(root, path), i, "bad-waiver",
                    f"waiver names unknown rule(s) {sorted(unknown)}",
                ))
            elif not why:
                out.append(Finding(
                    rel(root, path), i, "bad-waiver",
                    "waiver has no justification "
                    "(use `# swcheck: allow(rule): why`)",
                ))
    return out


def parse_or_finding(path: Path, relpath: str):
    """(ast.Module, None) or (None, Finding): every lint pass reports an
    unparseable file under the shared ``parse-error`` rule with identical
    wording, so a pass run standalone cannot skip the file vacuously and
    run_all's dedupe collapses the cross-pass copies into one finding."""
    key = str(path)
    if key not in _TREE_CACHE:
        try:
            _TREE_CACHE[key] = (ast.parse(read_text(path)), None)
        except SyntaxError as e:
            _TREE_CACHE[key] = (None, Finding(
                relpath, e.lineno or 1, "parse-error",
                f"file does not parse: {e.msg}"))
    return _TREE_CACHE[key]


def core_py_files(root: Path) -> list[Path]:
    core = root / "starway_tpu" / "core"
    if not core.is_dir():
        return []
    return sorted(p for p in core.rglob("*.py") if "__pycache__" not in p.parts)


#: Runtime modules OUTSIDE core/ that the concurrency/hotpath lints must
#: still police (they run threads or tail sockets next to the engine).
#: The `lint-coverage` check (analysis/concurrency.py) flags a top-level
#: module that grows a policed primitive without joining this list --
#: the gap core/session.py-era passes had for starway_tpu/metrics.py.
LINT_EXTRA_FILES = ("starway_tpu/metrics.py",)


def lint_py_files(root: Path) -> list[Path]:
    """The full lint surface: every core/ module plus the declared
    extras.  A declared extra that is missing on disk is reported by the
    `lint-coverage` check, not silently skipped."""
    return core_py_files(root) + [
        root / rel_ for rel_ in LINT_EXTRA_FILES if (root / rel_).is_file()
    ]


def waiver_audit_files(root: Path) -> list[Path]:
    """Every file a finding can anchor to (so every file a waiver is
    honoured in): core/, tests/, plus the contract surface outside core/
    -- errors.py and the native sources.  A bad waiver anywhere in this
    set must be reported, not silently ignored."""
    extra = [
        root / "starway_tpu" / "errors.py",
        root / "native" / "sw_engine.h",
        root / "native" / "sw_engine.cpp",
        # The swcost ledger carries in-place cost-budget waivers.
        root / "starway_tpu" / "analysis" / "cost_budgets.txt",
    ]
    extra += [root / rel_ for rel_ in LINT_EXTRA_FILES]
    extra += sorted((root / "starway_tpu").glob("*.py"))
    # reshard/ carries the layering-reshard rule, so its waivers must be
    # auditable too (rglob: nested modules are lint surface like core/'s).
    extra += sorted(p for p in (root / "starway_tpu" / "reshard").rglob("*.py")
                    if "__pycache__" not in p.parts)
    seen: set = set()
    out = []
    for p in core_py_files(root) + test_files(root) + [p for p in extra
                                                       if p.is_file()]:
        if str(p) not in seen:
            seen.add(str(p))
            out.append(p)
    return out


def test_files(root: Path) -> list[Path]:
    tests = root / "tests"
    if not tests.is_dir():
        return []
    return sorted(tests.glob("test_*.py"))
