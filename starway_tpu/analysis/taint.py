"""Pass: taint -- the §19 unverified-byte taint lint (DESIGN.md §21).

The integrity plane's central promise is a *dominance* property: on a
``csum``-negotiated conn, no payload byte may complete a receive or
reach a user callback unless the CRC verify that covers it ran first
and the mismatch arm aborted delivery (CLAUDE.md: "corrupt bytes must
never complete a receive or be delivered to user code").  The promise
is easy to break one refactor at a time -- move a completion above its
gate, drop one accumulation on one rx state, soften a mismatch arm
from poison to a counter bump -- and every one of those edits is
locally plausible.  This pass proves the discipline statically, in
BOTH engines, the way analysis/concurrency.py proves the
callback-under-lock rule: sources are parsed (ast / comment-stripped
text), never executed, so seeded mutations in tests/test_swcheck.py
are honoured.

Three checks per engine, table-driven off the rx structure:

1. **accumulate** -- every payload read site in the frame pump
   (``_rx_read`` / ``stream_read``) is followed, within its rx-state
   branch, by the guarded CRC accumulation (``if csum_pend: accum =
   crc32c(...)``).  A read that skips accumulation makes the eventual
   verify blind to those bytes.
2. **dominate** -- every delivery sink (the matcher completion, the
   striped-chunk record, the sub-header resolve, the ctl-body JSON
   dispatch) is preceded, within its branch, by a verify gate: an
   ``if`` on the armed checksum that compares the accumulator against
   the announced CRC -- and the mismatch arm must ABORT delivery
   (poison / SNACK-and-continue / return), never fall through.
3. **sm dequeue** -- the shared-memory ring's slot-record checksum
   failure is surfaced as the stable "corrupt" poison before any slot
   byte is parsed (SmCorrupt -> poison_reason in the Python transport
   read; ``read_into < 0`` -> ``conn_corrupt`` in the native one).

Extraction losing the pump function or the sink table is itself a
``taint-integrity`` finding (the explore/compose vacuity convention):
a lint that silently stopped seeing the delivery surface would pass
forever.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Optional

from .base import Finding, parse_or_finding, read_text
from .cpp_model import _strip_comments

F_CONN = "starway_tpu/core/conn.py"
F_SHM = "starway_tpu/core/shmring.py"
F_CPP = "native/sw_engine.cpp"

#: Delivery sinks in conn.py's ``_pump_frames``: attribute-call names
#: whose invocation hands (or commits to handing) frame bytes onward.
PY_SINKS = ("on_message_complete", "chunk_done", "chunk_start",
            "unpack_json_body")

#: Their native twins inside ``pump_frames`` (call-site tokens).
CPP_SINKS = ("matcher.on_complete(", "stripe_rx_chunk_done(",
             "stripe_rx_resolve(", "on_hello(")

#: The five rx-state arms of the native pump; each sink's verify region
#: runs from its nearest preceding arm guard to the sink itself.
CPP_ARMS = ("if (c->rx_skip)", "if (c->sdata_active)", "if (c->rx_stripe)",
            "if (c->rx_msg)", "if (c->ctl_need)")

_CPP_ACCUM_RE = re.compile(r"csum_accum\s*=\s*crc32c\(")
_CPP_COMPARE_RE = re.compile(r"csum_accum\s*!=\s*c->csum_[fh]")
_CPP_ABORT_RE = re.compile(r"conn_corrupt\(|T_SNACK|return;|continue;")


# ------------------------------------------------------------ python


def _mentions(node: ast.AST, attr: str) -> bool:
    return any(isinstance(n, ast.Attribute) and n.attr == attr
               for n in ast.walk(node))


def _calls(node: ast.AST, name: str) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            f = n.func
            if (isinstance(f, ast.Attribute) and f.attr == name) or \
                    (isinstance(f, ast.Name) and f.id == name):
                return True
    return False


def _aborts(stmts: list) -> bool:
    """Does this mismatch arm stop delivery?  Poison (``_corrupt``),
    retransmit-and-skip (``continue``), or any return/raise counts; a
    counter bump alone does not."""
    for s in stmts:
        for n in ast.walk(s):
            if isinstance(n, (ast.Return, ast.Continue, ast.Raise)):
                return True
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "_corrupt":
                return True
    return False


def _compare_on_accum(node: ast.AST) -> bool:
    return any(isinstance(n, ast.Compare) and (
        _mentions(n.left, "_csum_accum")
        or any(_mentions(c, "_csum_accum") for c in n.comparators))
        for n in ast.walk(node))


def _gate_verdict(stmt: ast.stmt) -> Optional[bool]:
    """Is ``stmt`` (or a statement nested in it) a §19 verify gate?
    Returns None (no gate), True (gate whose mismatch arm aborts), or
    False (gate that falls through -- the taint bug).  Two shapes:

    * the routing gate carries the compare in its own test
      (``if pend is not None and accum != pend[1]: poison``) -- pend
      stays armed for the payload that follows;
    * the consuming gate takes the pend pair down and compares inside
      (``pend, _csum_pend = _csum_pend, None; if accum != pend[0]:``),
      covering both the body-completion gates and the header-dispatch
      gate (which captures pend into a local first)."""
    for node in ast.walk(stmt):
        if isinstance(node, ast.If) and _mentions(node.test, "_csum_pend") \
                and _compare_on_accum(node.test):
            return _aborts(node.body)
    assigns_pend = any(
        isinstance(n, (ast.Assign, ast.AnnAssign)) and any(
            _mentions(t, "_csum_pend")
            for t in (n.targets if isinstance(n, ast.Assign)
                      else [n.target]))
        for n in ast.walk(stmt))
    inner = next((n for n in ast.walk(stmt)
                  if isinstance(n, ast.If)
                  and _compare_on_accum(n.test)), None)
    if assigns_pend and inner is not None:
        return _aborts(inner.body)
    return None


def _stmt_paths(func: ast.FunctionDef) -> dict:
    """id(stmt) -> [(suite, idx), ...] outermost-to-innermost, for every
    statement in the function (suites: body/orelse/finalbody/handlers)."""
    paths: dict = {}

    def visit(stmts: list, prefix: list) -> None:
        for i, s in enumerate(stmts):
            here = prefix + [(stmts, i)]
            paths[id(s)] = here
            for attr in ("body", "orelse", "finalbody"):
                visit(getattr(s, attr, []) or [], here)
            for h in getattr(s, "handlers", []) or []:
                visit(h.body, here)

    visit(func.body, [])
    return paths


def _containing_stmt(paths: dict, func: ast.FunctionDef,
                     target: ast.AST) -> Optional[list]:
    """The statement path whose innermost statement contains ``target``
    (innermost containing statement wins)."""
    best = None
    for sid, path in paths.items():
        suite, idx = path[-1]
        stmt = suite[idx]
        if any(n is target for n in ast.walk(stmt)):
            if best is None or len(path) > len(best):
                best = path
    return best


def _check_python(root: Path, out: list) -> None:
    tree, err = parse_or_finding(root / F_CONN, F_CONN)
    if tree is None:
        out.append(err)
        return
    pump = next((n for n in ast.walk(tree)
                 if isinstance(n, ast.FunctionDef)
                 and n.name == "_pump_frames"), None)
    if pump is None:
        out.append(Finding(
            F_CONN, 1, "taint-integrity",
            "_pump_frames not found -- the rx pump the taint lint proves "
            "the §19 verify-before-deliver discipline over is gone "
            "(update the extraction table, DESIGN.md §21)"))
        return
    paths = _stmt_paths(pump)
    loop = next((n for n in pump.body if isinstance(n, ast.While)), None)
    loop_suite = loop.body if loop is not None else pump.body

    # -- check 1: every read site accumulates under the armed checksum
    reads = [n for n in ast.walk(pump)
             if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
             and n.func.attr == "_rx_read"]
    if not reads:
        out.append(Finding(
            F_CONN, pump.lineno, "taint-integrity",
            "_pump_frames has no _rx_read sites -- the taint lint's read "
            "table no longer matches the code (DESIGN.md §21)"))
    for call in reads:
        path = _containing_stmt(paths, pump, call)
        if path is None:
            continue
        # The rx-state branch: the loop-body-level statement holding the
        # read.  Accumulation must follow the read inside that branch --
        # or, for the header read (its try sits at loop-body level
        # directly), among the following loop-body statements UP TO the
        # next read (a later branch's accumulate covers different
        # bytes, so it is a barrier here exactly as in the sink scan).
        branch_idx = next((i for i, (suite, _) in enumerate(path)
                           if suite is loop_suite), None)
        if branch_idx is not None:
            suite, idx = path[branch_idx]
            scope = [suite[idx]]
            for later in suite[idx + 1:]:
                if any(isinstance(n, ast.Call)
                       and isinstance(n.func, ast.Attribute)
                       and n.func.attr == "_rx_read"
                       for n in ast.walk(later)):
                    break
                scope.append(later)
        else:
            scope = [path[0][0][path[0][1]]]
        ok = False
        for s in scope:
            for n in ast.walk(s):
                if isinstance(n, ast.If) and n.lineno > call.lineno \
                        and _mentions(n.test, "_csum_pend") \
                        and _calls(n, "crc32c"):
                    ok = True
                    break
            if ok:
                break
        if not ok:
            out.append(Finding(
                F_CONN, call.lineno, "taint-integrity",
                "payload bytes read here never reach the §19 CRC "
                "accumulator (no guarded crc32c follows this _rx_read in "
                "its rx-state branch): the eventual verify is blind to "
                "them and corrupt bytes pass as good (DESIGN.md §21)"))

    # -- check 2: every delivery sink is dominated by an aborting gate
    found_sinks: set = set()
    for call in ast.walk(pump):
        if not (isinstance(call, ast.Call)
                and isinstance(call.func, ast.Attribute)
                and call.func.attr in PY_SINKS):
            continue
        found_sinks.add(call.func.attr)
        path = _containing_stmt(paths, pump, call)
        if path is None:
            continue
        verdict: Optional[bool] = None
        # Innermost-out, nearest-first.  At the loop-body level a
        # statement containing another _rx_read is a hard barrier: the
        # bytes beyond it belong to a different frame (a sibling
        # rx-state branch), so a gate there proves nothing about THIS
        # sink -- but the header-dispatch gate between the header read
        # and the dispatch chain is legitimately visible (it is what
        # dominates the zero-length immediate completion).
        for suite, idx in reversed(path):
            at_loop = suite is loop_suite
            for prev in reversed(suite[:idx]):
                if at_loop and any(
                        isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr == "_rx_read"
                        for n in ast.walk(prev)):
                    break  # barrier: a different frame's bytes
                verdict = _gate_verdict(prev)
                if verdict is not None:
                    break
            if verdict is not None or at_loop:
                break
        if verdict is None:
            out.append(Finding(
                F_CONN, call.lineno, "taint-integrity",
                f"delivery sink {call.func.attr}() is not dominated by a "
                "§19 verify gate: on an integrity conn these bytes reach "
                "user-visible state without their CRC ever being checked "
                "(DESIGN.md §21)"))
        elif verdict is False:
            out.append(Finding(
                F_CONN, call.lineno, "taint-integrity",
                f"the verify gate before {call.func.attr}() does not abort "
                "on mismatch: a failed CRC falls through and corrupt bytes "
                "complete the delivery (poison / SNACK / return -- never "
                "a counter bump alone; DESIGN.md §21)"))
    missing = [s for s in PY_SINKS if s not in found_sinks]
    if missing:
        out.append(Finding(
            F_CONN, pump.lineno, "taint-integrity",
            f"delivery sink(s) {missing} no longer found in _pump_frames "
            "-- the taint lint's sink table drifted from the code and the "
            "dominance proof is vacuous (DESIGN.md §21)"))

    # -- check 3: the sm dequeue poisons on a corrupt slot record
    rx_read = next((n for n in ast.walk(tree)
                    if isinstance(n, ast.FunctionDef)
                    and n.name == "_rx_read"), None)
    if rx_read is None:
        out.append(Finding(
            F_CONN, 1, "taint-integrity",
            "_rx_read not found -- cannot prove the sm slot-record "
            "corruption path poisons before parse (DESIGN.md §21)"))
    else:
        handler = next(
            (h for n in ast.walk(rx_read) if isinstance(n, ast.Try)
             for h in n.handlers
             if h.type is not None and "SmCorrupt" in ast.dump(h.type)),
            None)
        ok = handler is not None and any(
            isinstance(n, (ast.Assign, ast.AnnAssign))
            and any(_mentions(t, "poison_reason")
                    for t in (n.targets if isinstance(n, ast.Assign)
                              else [n.target]))
            for s in handler.body for n in ast.walk(s)) and any(
            isinstance(n, ast.Raise)
            for s in handler.body for n in ast.walk(s))
        if not ok:
            out.append(Finding(
                F_CONN, rx_read.lineno, "taint-integrity",
                "_rx_read does not convert SmCorrupt into the stable "
                "\"corrupt\" poison (set poison_reason, re-raise): a torn "
                "sm slot record would surface as a generic conn break -- "
                "or worse, parse (DESIGN.md §19/§21)"))
    shm_tree, shm_err = parse_or_finding(root / F_SHM, F_SHM)
    if shm_tree is None:
        out.append(shm_err)
    else:
        ri = next((n for n in ast.walk(shm_tree)
                   if isinstance(n, ast.FunctionDef)
                   and n.name == "read_into"), None)
        if ri is None or not any(isinstance(n, ast.Raise)
                                 and n.exc is not None
                                 and "SmCorrupt" in ast.dump(n.exc)
                                 for n in ast.walk(ri)):
            out.append(Finding(
                F_SHM, 1 if ri is None else ri.lineno, "taint-integrity",
                "Ring.read_into no longer raises SmCorrupt at a slot-record "
                "checksum mismatch: torn/stale ring bytes would parse as "
                "frames (DESIGN.md §19/§21)"))


# --------------------------------------------------------------- c++


def _cpp_func_body(code: str, signature: str) -> Optional[tuple]:
    """(body_text, start_offset) of the brace-matched function body
    following ``signature`` in comment-stripped code (string literals
    skipped so braces inside them cannot desync the match)."""
    at = code.find(signature)
    if at < 0:
        return None
    brace = code.find("{", at)
    if brace < 0:
        return None
    depth = 0
    i = brace
    n = len(code)
    while i < n:
        ch = code[i]
        if ch in "\"'":
            q = ch
            i += 1
            while i < n and code[i] != q:
                i += 2 if code[i] == "\\" else 1
        elif ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                return code[brace + 1:i], brace + 1
        i += 1
    return None


def _check_cpp(root: Path, out: list) -> None:
    path = root / F_CPP
    if not path.is_file():
        return
    code = _strip_comments(read_text(path))

    def line_of(off: int) -> int:
        return code.count("\n", 0, off) + 1

    got = _cpp_func_body(code, "void pump_frames(")
    if got is None:
        out.append(Finding(
            F_CPP, 1, "taint-integrity",
            "pump_frames not found in the native engine -- the taint "
            "lint's rx surface is gone (DESIGN.md §21)"))
        return
    body, base = got
    for token in CPP_SINKS:
        pos = body.find(token)
        if pos < 0:
            out.append(Finding(
                F_CPP, line_of(base), "taint-integrity",
                f"delivery sink `{token.rstrip('(')}` no longer found in "
                "pump_frames -- the taint lint's sink table drifted from "
                "the native engine (DESIGN.md §21)"))
            continue
        guard = max((body.rfind(g, 0, pos) for g in CPP_ARMS), default=-1)
        if guard < 0:
            out.append(Finding(
                F_CPP, line_of(base + pos), "taint-integrity",
                f"sink `{token.rstrip('(')}` has no preceding rx-state "
                "guard -- pump_frames restructured past the taint lint's "
                "arm table (DESIGN.md §21)"))
            continue
        region = body[guard:pos]
        sink_line = line_of(base + pos)
        if "stream_read(" not in region:
            out.append(Finding(
                F_CPP, sink_line, "taint-integrity",
                f"no stream_read in the rx arm feeding "
                f"`{token.rstrip('(')}` -- the arm/sink pairing drifted "
                "(DESIGN.md §21)"))
            continue
        if not _CPP_ACCUM_RE.search(region):
            out.append(Finding(
                F_CPP, sink_line, "taint-integrity",
                "payload bytes read in this rx arm never reach the §19 "
                "CRC accumulator (no `csum_accum = crc32c(...)` before "
                f"`{token.rstrip('(')}`): the verify is blind to them "
                "(DESIGN.md §21)"))
        cmp_m = None
        for m in _CPP_COMPARE_RE.finditer(region):
            cmp_m = m
        if cmp_m is None:
            out.append(Finding(
                F_CPP, sink_line, "taint-integrity",
                f"delivery sink `{token.rstrip('(')}` is not dominated by "
                "a §19 verify gate (no accumulator-vs-announced-CRC "
                "compare in its rx arm): unverified bytes reach "
                "user-visible state (DESIGN.md §21)"))
        elif not _CPP_ABORT_RE.search(region[cmp_m.end():]):
            out.append(Finding(
                F_CPP, sink_line, "taint-integrity",
                f"the verify gate before `{token.rstrip('(')}` does not "
                "abort on mismatch: a failed CRC falls through to the "
                "delivery (conn_corrupt / T_SNACK / return -- never a "
                "counter bump alone; DESIGN.md §21)"))

    sr = _cpp_func_body(code, "ssize_t stream_read(")
    if sr is None:
        out.append(Finding(
            F_CPP, 1, "taint-integrity",
            "stream_read not found in the native engine -- cannot prove "
            "the sm dequeue poisons on a corrupt slot record "
            "(DESIGN.md §21)"))
        return
    sbody, sbase = sr
    ri = sbody.find("read_into(")
    if ri < 0:
        out.append(Finding(
            F_CPP, line_of(sbase), "taint-integrity",
            "stream_read no longer dequeues via SmRing::read_into -- the "
            "sm taint check lost its anchor (DESIGN.md §21)"))
    elif 'conn_corrupt(c, "sm slot record"' not in sbody:
        out.append(Finding(
            F_CPP, line_of(sbase + ri), "taint-integrity",
            "a corrupt sm slot record (read_into < 0) is not poisoned "
            "with the stable \"sm slot record\" conn_corrupt before its "
            "bytes could parse (DESIGN.md §19/§21)"))


def run(root: Path) -> list:
    out: list = []
    _check_python(root, out)
    _check_cpp(root, out)
    return out
