"""CLI entry: ``python -m starway_tpu.analysis [--root DIR] [pass ...]``.

Exit status 0 = contract holds; 1 = findings (printed one per line as
``file:line: [rule] message``); 2 = usage error.  Stdlib-only.
"""

from __future__ import annotations

import argparse
import sys

from . import PASSES, RULES, find_root, run_all


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m starway_tpu.analysis",
        description="swcheck: cross-engine contract checker + concurrency "
                    "lint (see DESIGN.md §11)",
    )
    parser.add_argument("passes", nargs="*", metavar="pass",
                        help=f"subset of passes to run ({', '.join(PASSES)}); "
                             "default: all")
    parser.add_argument("--root", default=None,
                        help="repo root (default: autodetect from cwd or the "
                             "package location)")
    parser.add_argument("--rules", action="store_true",
                        help="list every rule name (waiver targets) and exit")
    args = parser.parse_args(argv)

    if args.rules:
        for name, desc in sorted(RULES.items()):
            print(f"{name:22s} {desc}")
        return 0

    unknown = [p for p in args.passes if p not in PASSES]
    if unknown:
        parser.error(f"unknown pass(es) {unknown}; choose from "
                     f"{', '.join(PASSES)}")

    root = find_root(args.root)
    findings = run_all(root, args.passes or None)
    for f in findings:
        print(f.render())
    ran = ", ".join(args.passes or PASSES)
    if findings:
        print(f"swcheck: {len(findings)} finding(s) [{ran}] in {root}",
              file=sys.stderr)
        return 1
    print(f"swcheck: OK [{ran}] in {root}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
