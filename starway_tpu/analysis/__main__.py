"""CLI entry: ``python -m starway_tpu.analysis [--root DIR] [pass ...]``.

Exit status 0 = contract holds; 1 = findings (printed one per line as
``file:line: [rule] message`` -- the shape .github/swcheck-matcher.json
turns into PR diff annotations); 2 = usage error.  ``--json`` emits one
machine-readable document instead (findings + per-pass timings);
``--timings`` prints per-pass wall time to stderr.  Stdlib-only.
"""

from __future__ import annotations

import argparse
import json
import sys

from . import PASSES, RULES, find_root, run_all


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m starway_tpu.analysis",
        description="swcheck/swproof: cross-engine contract checker, "
                    "concurrency lint, protocol state-machine diff, and "
                    "session model checking (DESIGN.md §11, §16)",
    )
    parser.add_argument("passes", nargs="*", metavar="pass",
                        help=f"subset of passes to run ({', '.join(PASSES)}); "
                             "default: all")
    parser.add_argument("--root", default=None,
                        help="repo root (default: autodetect from cwd or the "
                             "package location)")
    parser.add_argument("--rules", action="store_true",
                        help="list every rule name (waiver targets) and exit")
    parser.add_argument("--replay", metavar="DUMP", default=None,
                        help="swrefine: replay a swtrace ring dump "
                             "(swtrace.write_ring_dump) or flight-recorder "
                             "JSON through the protocol monitor and report "
                             "divergences (DESIGN.md §22); implies the "
                             "refine pass only")
    parser.add_argument("--write-budgets", action="store_true",
                        help="swcost: re-pin analysis/cost_budgets.txt from "
                             "the current extraction (the ratchet update "
                             "step; DESIGN.md §23) and exit")
    parser.add_argument("--minimize", action="store_true",
                        help="wirefuzz: dedup the regression corpus by "
                             "canonical-outcome signature (keeps every "
                             "pinned hex case and the corpus floor), "
                             "rewrite it in place, and exit")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit findings + timings as one JSON document "
                             "on stdout (exit status semantics unchanged)")
    parser.add_argument("--timings", action="store_true",
                        help="print per-pass wall time to stderr")
    args = parser.parse_args(argv)

    if args.rules:
        for name, desc in sorted(RULES.items()):
            print(f"{name:22s} {desc}")
        return 0

    if args.replay is not None:
        from . import refine

        viols = refine.replay_dump(args.replay,
                                   find_root(args.root) if args.root else None)
        if args.as_json:
            print(json.dumps({
                "dump": args.replay,
                "violations": [
                    {"label": v.label, "conn": v.conn, "index": v.index,
                     "class": v.cls, "message": v.message,
                     "context": v.context}
                    for v in viols
                ],
                "ok": not viols,
            }, indent=1))
        else:
            for v in viols:
                print(v.render())
        if viols:
            print(f"refine: {len(viols)} divergence(s) in {args.replay}",
                  file=sys.stderr)
            return 1
        print(f"refine: OK (replayed {args.replay})", file=sys.stderr)
        return 0

    unknown = [p for p in args.passes if p not in PASSES]
    if unknown:
        parser.error(f"unknown pass(es) {unknown}; choose from "
                     f"{', '.join(PASSES)}")

    if args.write_budgets:
        from . import cost

        root = find_root(args.root)
        vectors, vac = cost.extract(root)
        if vac:
            for f in vac:
                print(f.render())
            print("swcost: extraction is not clean; fix the anchors "
                  "before re-pinning", file=sys.stderr)
            return 1
        path = root / cost.LEDGER_REL
        path.write_text(cost.render_ledger(vectors))
        print(f"swcost: wrote {path} ({len(vectors)} rows)", file=sys.stderr)
        return 0

    if args.minimize:
        from . import wirefuzz

        root = find_root(args.root)
        report = wirefuzz.minimize_corpus(root)
        print("wirefuzz: corpus {path}: {before} -> {after} case(s) "
              "({hex_kept} pinned hex case(s) kept, floor {floor})"
              .format(**report), file=sys.stderr)
        return 0

    root = find_root(args.root)
    timings: dict = {}
    findings = run_all(root, args.passes or None, timings=timings)
    ran = ", ".join(args.passes or PASSES)
    if args.as_json:
        print(json.dumps({
            "root": str(root),
            "passes": list(args.passes or PASSES),
            "findings": [
                {"file": f.file, "line": f.line, "rule": f.rule,
                 "message": f.message}
                for f in findings
            ],
            "timings_s": {k: round(v, 4) for k, v in timings.items()},
            "ok": not findings,
        }, indent=1))
    else:
        for f in findings:
            print(f.render())
    if args.timings:
        total = sum(timings.values())
        for name, secs in timings.items():
            print(f"swcheck: pass {name:12s} {secs * 1000:8.1f} ms",
                  file=sys.stderr)
        print(f"swcheck: total {total * 1000:.1f} ms", file=sys.stderr)
    if findings:
        print(f"swcheck: {len(findings)} finding(s) [{ran}] in {root}",
              file=sys.stderr)
        return 1
    print(f"swcheck: OK [{ran}] in {root}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
