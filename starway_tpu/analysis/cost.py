"""Pass: cost -- swcost hot-path cost certification (DESIGN.md §23).

ROADMAP item 2 (io_uring batching, MSG_ZEROCOPY, bounded busy-poll) is a
story about *eliminating syscalls and copies*, but nothing in the gate
could verify such a claim or catch its regression: the bench is too
noisy on the 1-core box to resolve a one-syscall delta, and
``hotpath-copy`` is a single-idiom Python lint.  swcost pins the claim
the way swrefine (§22) pins protocol behaviour -- statically, in BOTH
engines, against a checked-in ledger:

1. **Extraction** -- a declared-call-graph walk of the per-op hot paths:
   C++ from the tx chokepoints and ``pump_frames`` rx arms of
   ``native/sw_engine.cpp`` (comment-stripped text, the §21 taint
   machinery's style), Python ``ast`` from the matching methods of
   ``core/conn.py`` / ``core/shmring.py`` / ``core/lane.py``.  Each
   contract path (eager tx/rx, rndv tx/rx, striped chunk tx/rx, sm
   enqueue/dequeue, per-frame dispatch) gets a cost vector
   ``{syscalls, copies, allocs, locks}`` counting *static sites*, the
   things a refactor adds or removes -- not dynamic executions.
2. **Ratcheted ledger** -- ``analysis/cost_budgets.txt`` pins one row
   per (engine, path, metric).  Exceeding a pin is a finding
   (regression); *beating* one is ALSO a finding until the pin is
   lowered, so improvements land as ledger diffs, and cross-engine
   asymmetries (python eager-tx paying sites native does not) are
   documented rows instead of folklore.
3. **Runtime twin** -- both engines carry unconditional ``io_syscalls``
   / ``hot_copies`` counters at the extracted syscall/copy sites
   (tests/test_cost.py drives a canonical op sequence over all four
   engine pairings and checks the deltas against this module's own
   extraction, so the tables cannot go stale silently).  This pass
   statically checks the instrumentation is alive.

A site is excluded from the count by the ordinary waiver discipline on
its own line (``# swcheck: allow(cost-site): why`` / the ``//`` form in
C++); a ledger row is waived in place in cost_budgets.txt.  Extraction
losing an anchor function, an rx arm, or the instrumentation is itself
a ``cost-model`` finding (the explore/compose vacuity convention): a
cost gate that silently stopped seeing the hot path would pass forever.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Optional

from .base import Finding, _waivers_on_line, parse_or_finding, read_text
from .cpp_model import _strip_comments
from .taint import _cpp_func_body

F_CONN = "starway_tpu/core/conn.py"
F_SHM = "starway_tpu/core/shmring.py"
F_LANE = "starway_tpu/core/lane.py"
F_CPP = "native/sw_engine.cpp"

METRICS = ("syscalls", "copies", "allocs", "locks")

#: Hot-path components: the unit of extraction.  Each is a declared
#: call-graph slice -- (file, [function defs]) on the Python side,
#: [signatures] (taint-style brace-matched bodies) on the native side.
#: ``arm:<name>`` components are carved out of the frame pumps below.
COMPONENTS = {
    "tx_pump":       {"py": (F_CONN, ["kick_tx"]),
                      # tcp_tx_account is kick_tx's budget loop, extracted
                      # so both event cores share it (§24) -- same slice,
                      # zero sites of its own, ledger-neutral.
                      "cpp": ["void kick_tx(", "void tcp_tx_account("]},
    "tx_gather":     {"py": (F_CONN, ["_gather_tx"]),
                      "cpp": ["ssize_t tcp_tx_gather("]},
    "tx_write":      {"py": (F_CONN, ["_tx_write"]),
                      "cpp": ["ssize_t conn_tx_write("]},
    "doorbell":      {"py": (F_CONN, ["_doorbell", "on_writable"]),
                      "cpp": ["void doorbell(", "void conn_writable("]},
    "ctl_send":      {"py": (F_CONN, ["send_ctl"]),
                      "cpp": ["void conn_send_ctl("]},
    "rndv_announce": {"py": (F_CONN, ["_fc_rts_announce"]),
                      "cpp": ["void fc_rts_announce("]},
    "rndv_grant":    {"py": (F_CONN, ["_on_cts"]),
                      "cpp": ["void fc_on_cts("]},
    "rx_read":       {"py": (F_CONN, ["_rx_read"]),
                      "cpp": ["ssize_t stream_read("]},
    "rx_socket":     {"py": (F_CONN, ["on_readable"]),
                      "cpp": ["void conn_readable("]},
    "sm_write":      {"py": (F_SHM, ["write", "_put"]),
                      "cpp": ["size_t write(const uint8_t* src, size_t len)"]},
    "sm_read":       {"py": (F_SHM, ["read_into", "_take"]),
                      "cpp": ["ssize_t read_into(uint8_t* dst, size_t len)"]},
    "stripe_feed":   {"py": (F_LANE, ["_claim"]),
                      "cpp": ["bool stripe_claim("]},
    # §24 swfast components are native-only ("py": None): the Python
    # engine declares the counter vocabulary but has no submission ring
    # or zerocopy machinery, so its rows for these paths pin at 0.
    "uring_pump":    {"py": None,
                      "cpp": ["void uring_service("]},
    "uring_collect": {"py": None,
                      "cpp": ["bool uring_tx_collect("]},
    "uring_finish":  {"py": None,
                      "cpp": ["void uring_op_finish("]},
    "uring_submit":  {"py": None,
                      "cpp": ["int uring_submit_wait("]},
    "zc_send":       {"py": None,
                      "cpp": ["ssize_t zc_tx_send("]},
    "zc_notify":     {"py": None,
                      "cpp": ["void zc_drain_errqueue("]},
}

#: The five rx-state arms of the frame pumps (taint.py's CPP_ARMS order)
#: plus the header/dispatch remainder.  Python arms are keyed by the
#: state attribute their marker statement mentions.
ARM_ORDER = ("skip", "sdata", "stripe", "msg", "ctl")
PY_ARM_ATTRS = {"skip": "_rx_skip", "sdata": "_sdata", "stripe": "_rx_stripe",
                "msg": "_rx_msg", "ctl": "_ctl"}
CPP_ARM_TOKENS = {"skip": "if (c->rx_skip)", "sdata": "if (c->sdata_active)",
                  "stripe": "if (c->rx_stripe)", "msg": "if (c->rx_msg)",
                  "ctl": "if (c->ctl_need)"}

#: Contract paths -> owning components.  Each component belongs to ONE
#: path, so a ledger row moving identifies the code that moved it.
PATHS = {
    "eager_tx":   ["tx_pump", "tx_gather"],
    "eager_rx":   ["arm:msg"],
    "rndv_tx":    ["ctl_send", "rndv_announce", "rndv_grant"],
    "rndv_rx":    ["arm:ctl"],
    "stripe_tx":  ["stripe_feed"],
    "stripe_rx":  ["arm:sdata", "arm:stripe"],
    "sm_enqueue": ["tx_write", "sm_write", "doorbell"],
    "sm_dequeue": ["rx_socket", "sm_read"],
    "dispatch":   ["arm:dispatch", "arm:skip", "rx_read"],
    # §24 swfast (STARWAY_IOURING=1): the per-conn TX pass under the
    # uring core.  Eager AND rndv payload bytes both ride this collector
    # (the rndv_tx path above is the RTS/CTS ctl plane, already at 0
    # syscalls) -- its per-pass site count is STRICTLY LOWER than
    # eager_tx's because the one sendmsg moved into uring_flush, where a
    # single io_uring_enter lands every ready conn's batch.
    "eager_tx_uring": ["uring_pump", "uring_collect", "uring_finish"],
    "uring_flush":    ["uring_submit"],
    # §24 (STARWAY_ZEROCOPY=1): the MSG_ZEROCOPY payload pass (two
    # sendmsg sites: the zerocopy send + the documented ENOBUFS copying
    # fallback) and the errqueue completion drain.  The eliminated cost
    # is the KERNEL-side payload copy -- not a static site here -- so
    # these rows pin the added notification machinery instead.
    "zc_tx":          ["zc_send"],
    "zc_notify":      ["zc_notify"],
}

# ------------------------------------------------------- site tables

#: Native site tables, matched over comment-stripped text.  Syscall
#: wrappers are the ``::``-qualified libc calls plus the epoll verbs;
#: copies are the explicit byte movers; allocs are the heap/growth
#: idioms (push_back onto a reserved vector is amortised, not counted).
CPP_SITE_RES = {
    "syscalls": re.compile(r"::send\(|::sendmsg\(|::recv\(|::recvmsg\(|"
                           r"::writev\(|\bepoll_wait\(|\bepoll_ctl\(|"
                           r"\bio_uring_enter\(|\bio_uring_setup\("),
    "copies":   re.compile(r"\bmemcpy\(|std::copy\(|\bmemmove\(|\.assign\("),
    "allocs":   re.compile(r"\bnew\s|\bmalloc\(|\.resize\(|\.reserve\(|"
                           r"make_shared<"),
    "locks":    re.compile(r"\block_guard\b|\bunique_lock\b|\.lock\(\)"),
}

PY_SYSCALL_ATTRS = {"send", "sendall", "sendmsg", "recv", "recv_into",
                    "recvmsg"}


def _mentions(node: ast.AST, attr: str) -> bool:
    return any(isinstance(n, ast.Attribute) and n.attr == attr
               for n in ast.walk(node))


def _mentions_sock(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and "sock" in n.attr:
            return True
        if isinstance(n, ast.Name) and "sock" in n.id:
            return True
    return False


def _py_sites(stmts: list) -> list:
    """(metric, lineno) static cost sites in a Python statement list.

    * syscalls -- ``*.sock.send/sendall/sendmsg/recv/recv_into/recvmsg``
    * copies   -- ``bytes(x)`` / ``.tobytes()`` / ``.join(...)`` and
      slice-assignment into a buffer (the shmring put/take idiom)
    * allocs   -- ``bytearray(...)`` with arguments
    * locks    -- ``with <...lock...>:`` items and ``.acquire()``
    """
    out = []
    for s in stmts:
        for n in ast.walk(s):
            if isinstance(n, ast.Call):
                f = n.func
                if isinstance(f, ast.Attribute):
                    if f.attr in PY_SYSCALL_ATTRS and _mentions_sock(f.value):
                        out.append(("syscalls", n.lineno))
                    elif f.attr in ("tobytes", "join"):
                        out.append(("copies", n.lineno))
                    elif f.attr == "acquire":
                        out.append(("locks", n.lineno))
                elif isinstance(f, ast.Name):
                    if f.id == "bytes" and n.args:
                        out.append(("copies", n.lineno))
                    elif f.id == "bytearray" and n.args:
                        out.append(("allocs", n.lineno))
            elif isinstance(n, ast.Assign):
                if any(isinstance(t, ast.Subscript)
                       and isinstance(t.slice, ast.Slice)
                       for t in n.targets):
                    out.append(("copies", n.lineno))
            elif isinstance(n, (ast.With, ast.AsyncWith)):
                for item in n.items:
                    expr = item.context_expr
                    if any((isinstance(x, ast.Attribute) and "lock" in x.attr)
                           or (isinstance(x, ast.Name) and "lock" in x.id)
                           for x in ast.walk(expr)):
                        out.append(("locks", n.lineno))
    return out


def _cpp_sites(region: str, base_off: int, code: str) -> list:
    """(metric, lineno) sites in a comment-stripped native text region
    (``base_off`` is the region's offset into ``code`` for line math)."""
    out = []
    for metric, rx in CPP_SITE_RES.items():
        for m in rx.finditer(region):
            line = code.count("\n", 0, base_off + m.start()) + 1
            out.append((metric, line))
    return out


def _unwaived(sites: list, file_lines: list) -> dict:
    """Fold sites into a {metric: count} vector, dropping sites whose
    own line (or the line above) carries a justified ``cost-site``
    waiver -- the standard discipline, honoured at extraction time so
    the ledger never pins a waived site."""
    vec = {m: 0 for m in METRICS}
    for metric, line in sites:
        waived = any("cost-site" in rules and why
                     for rules, why, _ in _waivers_on_line(file_lines, line))
        if not waived:
            vec[metric] += 1
    return vec


# ------------------------------------------------------- python side


def _py_functions(tree: ast.Module) -> dict:
    return {n.name: n for n in ast.walk(tree)
            if isinstance(n, ast.FunctionDef)}


def _py_pump_arms(pump: ast.FunctionDef) -> Optional[dict]:
    """Split ``_pump_frames``' top-level loop statements into the five
    rx-state arms + the dispatch remainder.  Arms are delimited by their
    marker statements in ARM_ORDER (the statement mentioning the state
    attribute); everything after the ``ctl`` marker -- and any loop
    prelude before the first marker -- is the dispatch region."""
    loop = next((n for n in pump.body if isinstance(n, ast.While)), None)
    if loop is None:
        return None
    arms: dict = {name: [] for name in ARM_ORDER}
    arms["dispatch"] = []
    pending = list(ARM_ORDER)
    current = "dispatch"
    for stmt in loop.body:
        if pending and _mentions(stmt, PY_ARM_ATTRS[pending[0]]):
            current = pending.pop(0)
        elif current == "ctl":
            # The ctl arm is its single marker statement; the header
            # parse + frame dispatch chain follows it.
            current = "dispatch"
        arms[current].append(stmt)
    if pending:
        return None  # an arm marker vanished: pump restructured
    return arms


def _extract_python(root: Path, vectors: dict, out: list) -> None:
    trees: dict = {}
    lines: dict = {}
    for f in (F_CONN, F_SHM, F_LANE):
        tree, err = parse_or_finding(root / f, f)
        if tree is None:
            out.append(err)
            return
        trees[f] = tree
        lines[f] = read_text(root / f).splitlines()

    comp_vecs: dict = {}
    for name, spec in COMPONENTS.items():
        if spec["py"] is None:
            continue  # native-only §24 component: the py rows pin at 0
        f, funcs = spec["py"]
        defs = _py_functions(trees[f])
        sites: list = []
        for fn in funcs:
            node = defs.get(fn)
            if node is None:
                out.append(Finding(
                    f, 1, "cost-model",
                    f"swcost anchor `{fn}` (component {name}) not found -- "
                    "the extraction table drifted from the code; update "
                    "COMPONENTS and re-pin the ledger (DESIGN.md §23)"))
                continue
            sites.extend(_py_sites(node.body))
        comp_vecs[name] = _unwaived(sites, lines[f])

    pump = _py_functions(trees[F_CONN]).get("_pump_frames")
    arms = _py_pump_arms(pump) if pump is not None else None
    if arms is None:
        out.append(Finding(
            F_CONN, 1 if pump is None else pump.lineno, "cost-model",
            "_pump_frames rx arms not extractable (function or an arm "
            "marker statement is gone): the per-frame cost vectors are "
            "unmeasurable -- update the arm table (DESIGN.md §23)"))
    else:
        for arm, stmts in arms.items():
            comp_vecs[f"arm:{arm}"] = _unwaived(
                _py_sites(stmts), lines[F_CONN])

    _fold_paths("py", comp_vecs, vectors)


# ------------------------------------------------------- native side


def _cpp_arm_regions(body: str, base: int) -> Optional[list]:
    """[(arm, region_text, region_offset)] for the native pump: each
    arm's brace-matched block, with the leftover text (loop head + the
    header/dispatch chain) as the ``dispatch`` region."""
    spans = []
    pos = 0
    for arm in ARM_ORDER:
        at = body.find(CPP_ARM_TOKENS[arm], pos)
        if at < 0:
            return None
        # _cpp_func_body finds the FIRST occurrence; arms appear in
        # order, so search from `at` by slicing.
        got = _cpp_func_body(body[at:], CPP_ARM_TOKENS[arm])
        if got is None:
            return None
        block, boff = got
        spans.append((arm, at, at + boff + len(block) + 1))
        pos = at + boff + len(block)
    regions = [(arm, body[a:b], base + a) for arm, a, b in spans]
    rest = []
    prev = 0
    for _, a, b in spans:
        rest.append((body[prev:a], base + prev))
        prev = b
    rest.append((body[prev:], base + prev))
    return regions, rest


def _extract_cpp(root: Path, vectors: dict, out: list) -> None:
    path = root / F_CPP
    if not path.is_file():
        out.append(Finding(
            F_CPP, 1, "cost-model",
            "native engine source missing -- the swcost ledger cannot "
            "certify the native hot paths (DESIGN.md §23)"))
        return
    raw = read_text(path)
    code = _strip_comments(raw)
    raw_lines = raw.splitlines()

    comp_vecs: dict = {}
    for name, spec in COMPONENTS.items():
        sites: list = []
        for sig in spec["cpp"]:
            got = _cpp_func_body(code, sig)
            if got is None:
                out.append(Finding(
                    F_CPP, 1, "cost-model",
                    f"swcost anchor `{sig.strip()}` (component {name}) not "
                    "found -- the extraction table drifted from the native "
                    "engine; update COMPONENTS and re-pin the ledger "
                    "(DESIGN.md §23)"))
                continue
            body, off = got
            sites.extend(_cpp_sites(body, off, code))
        comp_vecs[name] = _unwaived(sites, raw_lines)

    got = _cpp_func_body(code, "void pump_frames(")
    arms = _cpp_arm_regions(*got) if got is not None else None
    if arms is None:
        out.append(Finding(
            F_CPP, 1, "cost-model",
            "pump_frames rx arms not extractable from the native engine "
            "(function or an arm guard token is gone): update the arm "
            "table (DESIGN.md §23)"))
    else:
        regions, rest = arms
        for arm, text, off in regions:
            comp_vecs[f"arm:{arm}"] = _unwaived(
                _cpp_sites(text, off, code), raw_lines)
        dsites: list = []
        for text, off in rest:
            dsites.extend(_cpp_sites(text, off, code))
        comp_vecs["arm:dispatch"] = _unwaived(dsites, raw_lines)

    _fold_paths("cpp", comp_vecs, vectors)


def _fold_paths(engine: str, comp_vecs: dict, vectors: dict) -> None:
    for pname, comps in PATHS.items():
        for metric in METRICS:
            vectors[(engine, pname, metric)] = sum(
                comp_vecs.get(c, {}).get(metric, 0) for c in comps)


def extract(root: Path):
    """((engine, path, metric) -> site count, [vacuity findings])."""
    vectors: dict = {}
    out: list = []
    _extract_python(root, vectors, out)
    _extract_cpp(root, vectors, out)
    return vectors, out


# ----------------------------------------------------------- ledger

LEDGER_REL = "starway_tpu/analysis/cost_budgets.txt"

_ROW_RE = re.compile(r"^(\w+)\s+(\w+)\s+(\w+)\s+(\d+)\s*(?:#.*)?$")


def ledger_path(root: Path) -> Path:
    """The checked-in ledger, tree-shadowed like wirefuzz's corpus: a
    tmpdir copy of the tree (tests/test_swcheck.py) carries its own."""
    cand = root / LEDGER_REL
    if cand.is_file():
        return cand
    return Path(__file__).resolve().parent / "cost_budgets.txt"


def load_ledger(root: Path):
    """({(engine, path, metric) -> (pin, line)} or None when the ledger
    file itself is gone, [findings])."""
    path = ledger_path(root)
    pins: dict = {}
    out: list = []
    relp = LEDGER_REL
    try:
        text = read_text(path)
    except OSError:
        out.append(Finding(
            relp, 1, "cost-model",
            "cost_budgets.txt missing -- the swcost gate has no pins "
            "(regenerate with `python -m starway_tpu.analysis cost "
            "--write-budgets`; DESIGN.md §23)"))
        return None, out
    for i, line in enumerate(text.splitlines(), 1):
        s = line.strip()
        if not s or s.startswith("#"):
            continue
        m = _ROW_RE.match(s)
        if m is None:
            out.append(Finding(
                relp, i, "cost-model",
                f"malformed ledger row {s!r} (want `engine path metric "
                "value`; DESIGN.md §23)"))
            continue
        engine, pname, metric, value = m.groups()
        key = (engine, pname, metric)
        if engine not in ("py", "cpp") or pname not in PATHS \
                or metric not in METRICS:
            out.append(Finding(
                relp, i, "cost-model",
                f"ledger row pins unknown surface {key} -- stale row or "
                "a renamed path; re-pin the ledger (DESIGN.md §23)"))
            continue
        if key in pins:
            out.append(Finding(
                relp, i, "cost-model",
                f"duplicate ledger row for {key} (DESIGN.md §23)"))
            continue
        pins[key] = (int(value), i)
    return pins, out


def render_ledger(vectors: dict) -> str:
    """The canonical cost_budgets.txt text for an extraction result."""
    lines = [
        "# swcost ledger (DESIGN.md §23): static hot-path cost pins, one",
        "# row per (engine, path, metric) counting SITES, not executions.",
        "# The gate is a ratchet: a row exceeded is a regression; a row",
        "# beaten stays red until the pin here is lowered to match.",
        "# Regenerate: python -m starway_tpu.analysis cost --write-budgets",
        "# Waive a row in place: # swcheck: allow(cost-budget): why",
    ]
    for pname in PATHS:
        lines.append("")
        lines.append(f"# -- {pname}: {' + '.join(PATHS[pname])}")
        for engine in ("py", "cpp"):
            for metric in METRICS:
                v = vectors.get((engine, pname, metric), 0)
                lines.append(f"{engine:<4}{pname:<16}{metric:<10}{v}")
    return "\n".join(lines) + "\n"


# -------------------------------------------------------------- pass


def _check_instrumentation(root: Path, out: list) -> None:
    """The §23 runtime twin must stay alive in both engines: the
    conformance test (tests/test_cost.py) checks deltas only if the
    counters move at all, so a silently-removed increment would leave
    the dynamic side vacuous.  Static liveness closes that hole."""
    checks = (
        (F_CONN, ("io_syscalls += 1", "hot_copies += 1"),
         "self._ctr.<counter> += 1"),
        (F_CPP, ("bump(counters.io_syscalls", "bump(counters.hot_copies"),
         "bump(counters.<counter>)"),
    )
    for f, tokens, idiom in checks:
        path = root / f
        if not path.is_file():
            continue
        text = read_text(path)
        for tok in tokens:
            if tok not in text:
                out.append(Finding(
                    f, 1, "cost-model",
                    f"§23 runtime cost twin dark: no `{tok}` site left in "
                    f"this engine ({idiom} at the hot-path syscall/copy "
                    "sites) -- the dynamic conformance check is vacuous "
                    "(DESIGN.md §23)"))


def run(root: Path) -> list:
    out: list = []
    vectors, vac = extract(root)
    out.extend(vac)

    # Staleness: an engine whose extraction sees ZERO sites for a whole
    # metric class no longer matches the code (every class has known
    # sites at head) -- the ledger would ratify an empty measurement.
    for engine, f in (("py", F_CONN), ("cpp", F_CPP)):
        for metric in METRICS:
            total = sum(v for (e, _, m), v in vectors.items()
                        if e == engine and m == metric)
            if vectors and total == 0:
                out.append(Finding(
                    f, 1, "cost-model",
                    f"swcost extraction stale: zero {metric} sites across "
                    f"every {engine} hot path (the site table no longer "
                    "matches the code; DESIGN.md §23)"))

    pins, lfind = load_ledger(root)
    out.extend(lfind)
    relp = LEDGER_REL
    have_ledger = pins is not None
    pins = pins or {}
    for key, actual in sorted(vectors.items()):
        engine, pname, metric = key
        pinned = pins.pop(key, None)
        if pinned is None:
            if have_ledger:
                out.append(Finding(
                    relp, 1, "cost-model",
                    f"no ledger row for {engine} {pname} {metric} "
                    f"(measured {actual}) -- add the pin (DESIGN.md §23)"))
            continue
        pin, line = pinned
        if actual > pin:
            out.append(Finding(
                relp, line, "cost-budget",
                f"{engine} {pname} {metric}: {actual} sites exceeds the "
                f"pinned budget {pin} -- a hot-path cost regression "
                "(raise the pin only with a ledger-reviewed justification; "
                "DESIGN.md §23)"))
        elif actual < pin:
            out.append(Finding(
                relp, line, "cost-budget",
                f"{engine} {pname} {metric}: {actual} sites beats the "
                f"pinned budget {pin} -- lower the pin to ratchet the "
                "improvement in (DESIGN.md §23)"))
    for key, (pin, line) in sorted(pins.items()):
        out.append(Finding(
            relp, line, "cost-model",
            f"ledger row {' '.join(key)} has no measured twin -- the "
            "extraction no longer produces this vector (DESIGN.md §23)"))

    _check_instrumentation(root, out)
    return out
