"""Pass: protomodel -- cross-engine protocol state-machine extraction.

swcheck's `contract` pass (DESIGN.md §11) diffs *constants*; this pass
diffs *behavior*: the frame-dispatch / session state machine is extracted
from BOTH engines and compared transition-by-transition, so an engine
that grows, drops, or reroutes a dispatch arm without the twin change in
the other engine fails the gate -- the class of drift that shipped the
T_SEQ late-delivery and `:sup`-marker bugs past the constant diff.

**The shared machine** (DESIGN.md §16).  States:

* ``hello-sent`` -- connector blocked in the handshake (HELLO on the
  wire, HELLO_ACK awaited);
* ``estab``      -- framed-stream dispatch (the `_pump_frames` /
  `pump_stream` parser; the server's pre-HELLO accept state is folded in
  -- the same parser object handles both, gated by ``handshaken``);
* ``suspended``  -- session transport lost, resumable (§14).

Inputs are frame names (``DATA`` ... ``BYE``, ``OTHER`` for the
unknown-frame arm) plus the session lifecycle events ``lost`` / ``resume``
/ ``expire``.  Next-states may be sets (``estab|down``): a dispatch arm
that conditionally tears the conn down has both outcomes.

**Python extraction** is syntactic (ast, sources never imported):

* every ``ftype == frames.T_X`` / ``ftype in (frames.T_A, ...)``
  comparison in ``core/conn.py`` is a dispatch arm in ``estab``; the arm's
  next-states are ``down`` when it (or a ``self._x`` helper it calls, one
  level deep) reaches ``_conn_broken``/``raise``, ``expired`` when it
  assigns ``.expired = True``, plus ``estab`` unless the teardown is
  unconditional.  A trailing ``else`` arm contributes the ``OTHER``
  transition only when it tears the conn down.
* ``ftype != frames.T_X`` guarding a ``raise`` in ``core/engine.py`` is
  the connector's blocking handshake: ``(hello-sent, X) -> estab`` and
  ``(hello-sent, OTHER) -> down``.
* the session lifecycle comes from ``core/engine.py``'s ``_sess_*``
  bodies: ``_sess_suspend`` calling ``.suspend()`` is ``(estab, lost) ->
  suspended``; a ``.resume()`` call inside ``_sess_redial``/``_sess_hello``
  is ``(suspended, resume) -> estab``; ``_sess_expire`` assigning
  ``.expired = True`` is ``(suspended, expire) -> expired``.

**Native extraction** is annotation-anchored (the `swcheck:
engine-version` precedent): every dispatch site in ``native/sw_engine.cpp``
carries ``// swcheck: state(<state>, <frame>, <next>[|<next>...])``.
Both extractions are vacuity-guarded -- an empty machine is a finding,
never a pass -- and every diff finding is waiver-able at its anchor line.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Optional

from .base import Finding, parse_or_finding, read_text

#: Annotation vocabulary -- unknown tokens are malformed-annotation
#: findings, so a typo'd state can never vacuously "agree".
KNOWN_STATES = {"hello-sent", "estab", "suspended"}
KNOWN_INPUTS = {
    "HELLO", "HELLO_ACK", "DATA", "FLUSH", "FLUSH_ACK", "DEVPULL",
    "PING", "PONG", "SEQ", "ACK", "BYE", "SDATA", "SACK", "OTHER",
    "CREDIT", "RTS", "CTS", "CSUM", "SNACK",
    "lost", "resume", "expire",
}
KNOWN_NEXTS = {"estab", "down", "expired", "suspended"}

_CPP_STATE_RE = re.compile(r"swcheck:\s*state\(([^)]*)\)")


def _terminal(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def _frame_name(node: ast.AST) -> Optional[str]:
    """frames.T_DATA / T_DATA -> "DATA" (None when not a frame const)."""
    name = _terminal(node)
    if name.startswith("T_"):
        return name[2:]
    return None


def _self_method_calls(body: list) -> set:
    """Terminal names of ``self._x(...)`` calls in ``body`` (the one-level
    inline set for next-state inference)."""
    out = set()
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "self":
                out.add(node.func.attr)
    return out


def _scan_effects(nodes: list) -> tuple[bool, bool]:
    """(tears_down, sets_expired) anywhere in ``nodes``."""
    down = expired = False
    for stmt in nodes:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) \
                    and _terminal(node.func) in ("_conn_broken", "conn_broken"):
                down = True
            elif isinstance(node, ast.Raise):
                down = True
            elif isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) and tgt.attr == "expired" \
                            and isinstance(node.value, ast.Constant) \
                            and node.value.value is True:
                        expired = True
    return down, expired


def _unconditional_down(body: list) -> bool:
    """True when a statement DIRECTLY in ``body`` (not nested under a
    conditional) tears the conn down -- the unknown-frame arm shape."""
    for stmt in body:
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call) \
                and _terminal(stmt.value.func) in ("_conn_broken",
                                                   "conn_broken"):
            return True
        if isinstance(stmt, ast.Raise):
            return True
    return False


def _branch_nexts(body: list, class_methods: dict) -> set:
    """Next-state set for one dispatch arm: the arm's own statements plus
    the bodies of same-class ``self._x()`` helpers it calls (one level --
    `_on_seq`-style dispatch helpers, not the whole transitive engine)."""
    down, expired = _scan_effects(body)
    for name in _self_method_calls(body):
        helper = class_methods.get(name)
        if helper is not None:
            hd, he = _scan_effects(helper.body)
            down = down or hd
            expired = expired or he
    nexts = set()
    if down:
        nexts.add("down")
    if expired:
        nexts.add("expired")
    if not _unconditional_down(body):
        nexts.add("estab")
    return nexts


class _Machine:
    """{(state, input): (next-state set, file, line)} with set-union merge
    (the same arm reached through two dispatch shapes stays one row)."""

    def __init__(self) -> None:
        self.transitions: dict = {}

    def add(self, state: str, inp: str, nexts: set, file: str, line: int) -> None:
        key = (state, inp)
        if key in self.transitions:
            old, f, ln = self.transitions[key]
            self.transitions[key] = (old | set(nexts), f, ln)
        else:
            self.transitions[key] = (set(nexts), file, line)


def _walk_ftype_dispatch(tree: ast.Module, relfile: str,
                         machine: _Machine) -> None:
    """Collect `estab` dispatch arms from every ``ftype`` comparison chain
    in the conn parser."""
    # class -> {method name: FunctionDef} for one-level helper inlining.
    class_methods: dict = {}
    method_class: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            methods = {n.name: n for n in node.body
                       if isinstance(n, ast.FunctionDef)}
            class_methods[node.name] = methods
            for name in methods:
                method_class.setdefault(name, node.name)

    def methods_for(fn_name: str) -> dict:
        cls = method_class.get(fn_name)
        return class_methods.get(cls, {}) if cls else {}

    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        helpers = methods_for(fn.name)
        for node in ast.walk(fn):
            if not isinstance(node, ast.If):
                continue
            frames_hit = _frames_in_test(node.test)
            if frames_hit:
                nexts = _branch_nexts(node.body, helpers)
                for name in frames_hit:
                    machine.add("estab", name, nexts, relfile, node.lineno)
                # A terminal `else` arm is the unknown-frame transition --
                # but only when it tears the conn down (a non-tearing else
                # is a dispatch fallthrough, e.g. the ctl-completion
                # default routing to the HELLO_ACK hook).
                tail = node.orelse
                if tail and not (len(tail) == 1 and isinstance(tail[0], ast.If)):
                    if _unconditional_down(tail):
                        machine.add("estab", "OTHER", {"down"}, relfile,
                                    tail[0].lineno)


def _frames_in_test(test: ast.AST) -> list:
    """Frame names dispatched by an If test on ``ftype`` (Eq and
    membership shapes; extra conjuncts like ``and self._sess_drop`` are
    allowed)."""
    out = []
    for node in ast.walk(test):
        if not isinstance(node, ast.Compare) or len(node.ops) != 1:
            continue
        if _terminal(node.left) != "ftype":
            continue
        op = node.ops[0]
        if isinstance(op, ast.Eq):
            name = _frame_name(node.comparators[0])
            if name:
                out.append(name)
        elif isinstance(op, ast.In) and isinstance(node.comparators[0],
                                                   (ast.Tuple, ast.List)):
            for elt in node.comparators[0].elts:
                name = _frame_name(elt)
                if name:
                    out.append(name)
    return out


def _walk_handshake(tree: ast.Module, relfile: str, machine: _Machine) -> None:
    """``if ftype != frames.T_X: raise`` in the connector's blocking
    handshake: (hello-sent, X) -> estab and (hello-sent, OTHER) -> down."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.If) or not isinstance(node.test, ast.Compare):
            continue
        test = node.test
        if len(test.ops) != 1 or not isinstance(test.ops[0], ast.NotEq):
            continue
        if _terminal(test.left) != "ftype":
            continue
        name = _frame_name(test.comparators[0])
        if name and any(isinstance(n, ast.Raise) for stmt in node.body
                        for n in ast.walk(stmt)):
            machine.add("hello-sent", name, {"estab"}, relfile, node.lineno)
            machine.add("hello-sent", "OTHER", {"down"}, relfile, node.lineno)


def _walk_lifecycle(tree: ast.Module, relfile: str, machine: _Machine) -> None:
    """Session lifecycle transitions from the engine's `_sess_*` family
    (the §14 machine: suspend on transport loss, resume replay, terminal
    expiry)."""
    for fn in ast.walk(tree):
        if not isinstance(fn, ast.FunctionDef):
            continue
        if fn.name == "_sess_suspend":
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) \
                        and _terminal(node.func) == "suspend":
                    machine.add("estab", "lost", {"suspended"}, relfile,
                                node.lineno)
                    break
        elif fn.name in ("_sess_redial", "_sess_hello"):
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "resume":
                    machine.add("suspended", "resume", {"estab"}, relfile,
                                node.lineno)
                    break
        elif fn.name == "_sess_expire":
            _, expired = _scan_effects(fn.body)
            if expired:
                machine.add("suspended", "expire", {"expired"}, relfile,
                            fn.lineno)


def extract_py_machine(root: Path) -> tuple[_Machine, list]:
    machine = _Machine()
    findings: list = []
    conn_rel = "starway_tpu/core/conn.py"
    engine_rel = "starway_tpu/core/engine.py"
    for relfile, walkers in (
        (conn_rel, (_walk_ftype_dispatch,)),
        (engine_rel, (_walk_handshake, _walk_lifecycle)),
    ):
        path = root / relfile
        if not path.is_file():
            findings.append(Finding(relfile, 1, "proto-state",
                                    "engine source missing -- cannot extract "
                                    "the protocol state machine"))
            continue
        tree, err = parse_or_finding(path, relfile)
        if tree is None:
            findings.append(err)
            continue
        for walk in walkers:
            walk(tree, relfile, machine)
    return machine, findings


def extract_cpp_machine(root: Path) -> tuple[_Machine, list]:
    machine = _Machine()
    findings: list = []
    relfile = "native/sw_engine.cpp"
    path = root / relfile
    if not path.is_file():
        return machine, [Finding(relfile, 1, "proto-state",
                                 "native engine source missing -- cannot "
                                 "extract the protocol state machine")]
    text = read_text(path)
    for i, line in enumerate(text.splitlines(), 1):
        m = _CPP_STATE_RE.search(line)
        if m is None:
            continue
        parts = [p.strip() for p in m.group(1).split(",")]
        if len(parts) != 3:
            findings.append(Finding(
                relfile, i, "proto-state",
                f"malformed state annotation `state({m.group(1)})` -- "
                "expected state(<state>, <input>, <next>[|<next>...])"))
            continue
        state, inp, nexts_raw = parts
        nexts = {n.strip() for n in nexts_raw.split("|") if n.strip()}
        bad = ([state] if state not in KNOWN_STATES else []) \
            + ([inp] if inp not in KNOWN_INPUTS else []) \
            + sorted(nexts - KNOWN_NEXTS)
        if bad:
            findings.append(Finding(
                relfile, i, "proto-state",
                f"state annotation uses unknown token(s) {bad} "
                "(see DESIGN.md §16 for the vocabulary)"))
            continue
        machine.add(state, inp, nexts, relfile, i)
    return machine, findings


def _fmt(nexts: set) -> str:
    return "|".join(sorted(nexts))


def run(root: Path) -> list:
    py, out = extract_py_machine(root)
    cpp, cpp_findings = extract_cpp_machine(root)
    out.extend(cpp_findings)
    # Vacuity guard: an extractor that silently comes up empty would make
    # the whole diff a no-op.  Empty machines are findings, not passes.
    if not py.transitions:
        out.append(Finding(
            "starway_tpu/core/conn.py", 1, "proto-state",
            "extracted no transitions from the Python engine -- state "
            "machine checking would be vacuous (dispatch reshaped past the "
            "extraction grammar? see DESIGN.md §16)"))
    if not cpp.transitions:
        out.append(Finding(
            "native/sw_engine.cpp", 1, "proto-state",
            "found no `swcheck: state(...)` annotations in the native "
            "engine -- state machine checking would be vacuous (annotate "
            "dispatch sites; see DESIGN.md §16)"))
    if not py.transitions or not cpp.transitions:
        return out
    for key in sorted(set(py.transitions) | set(cpp.transitions)):
        state, inp = key
        if key not in cpp.transitions:
            nexts, f, ln = py.transitions[key]
            out.append(Finding(
                f, ln, "proto-state",
                f"transition ({state}, {inp}) -> {_fmt(nexts)} extracted "
                "from the Python engine has no `swcheck: state(...)` "
                "annotation in native/sw_engine.cpp (two engines, one "
                "protocol machine)"))
        elif key not in py.transitions:
            nexts, f, ln = cpp.transitions[key]
            out.append(Finding(
                f, ln, "proto-state",
                f"annotated transition ({state}, {inp}) -> {_fmt(nexts)} "
                "has no counterpart in the Python engine's dispatch "
                "(stale annotation, or a dispatch arm removed on one side)"))
        else:
            pn, pf, pl = py.transitions[key]
            cn, cf, cl = cpp.transitions[key]
            if pn != cn:
                out.append(Finding(
                    pf, pl, "proto-state",
                    f"transition ({state}, {inp}): Python engine -> "
                    f"{_fmt(pn)} but {cf}:{cl} annotates -> {_fmt(cn)} "
                    "(the engines disagree on the outcome of this input)"))
    return out
