"""Pass 5: hot-path copy hygiene over starway_tpu/core/.

The data plane is zero-copy by design (DESIGN.md §12): payload bytes move
from the user's buffer to the transport (and back) through memoryview
slices, never through intermediate materialisations.  A stray ``bytes(buf)``
or ``buf.tobytes()`` on a core/ data path silently reintroduces a
full-payload copy -- exactly the class of regression this PR removed from
the JSON control parsers (core/conn.py, core/engine.py).

Flagged (rule ``hotpath-copy``):

* ``bytes(x)`` where ``x`` is a name/attribute/call/subscript -- i.e. a
  buffer being copied.  Literal constructions (``bytes([val])``,
  ``bytes(17)``, ``bytes()``) are allocation, not copying, and are skipped.
* any ``x.tobytes()`` call.

Scanned: the full lint surface (every ``core/*.py`` plus
``base.LINT_EXTRA_FILES``) except ``frames.py`` -- the control-frame
codec builds/parses small bounded JSON bodies, and its one documented
``tobytes`` (the memoryview escape hatch in ``unpack_json_body``) is not a
payload path.  Genuinely-needed copies elsewhere take an explicit waiver:
``# swcheck: allow(hotpath-copy): why``.
"""

from __future__ import annotations

import ast
from pathlib import Path

from .base import Finding, lint_py_files, parse_or_finding, rel


def _is_literal_arg(node: ast.AST) -> bool:
    """bytes(...) arguments that allocate rather than copy."""
    return isinstance(node, (ast.Constant, ast.List, ast.Tuple, ast.ListComp,
                             ast.GeneratorExp, ast.Starred))


class _CopyLint(ast.NodeVisitor):
    def __init__(self, relpath: str):
        self.relpath = relpath
        self.findings: list = []

    def visit_Call(self, node):               # noqa: N802
        func = node.func
        if (isinstance(func, ast.Name) and func.id == "bytes"
                and len(node.args) == 1 and not node.keywords
                and not _is_literal_arg(node.args[0])):
            self.findings.append(Finding(
                self.relpath, node.lineno, "hotpath-copy",
                "bytes(...) materialises a full copy of its buffer on a "
                "core/ data path -- slice the memoryview (or pass the "
                "buffer straight to the consumer) instead"))
        elif (isinstance(func, ast.Attribute) and func.attr == "tobytes"):
            self.findings.append(Finding(
                self.relpath, node.lineno, "hotpath-copy",
                ".tobytes() materialises a full copy on a core/ data path "
                "-- keep the memoryview"))
        self.generic_visit(node)


def run(root: Path) -> list:
    out: list = []
    for path in lint_py_files(root):
        if path.name == "frames.py":
            continue  # control-frame codec: small bounded bodies (docstring)
        relpath = rel(root, path)
        tree, err = parse_or_finding(path, relpath)
        if tree is None:
            out.append(err)
            continue
        lint = _CopyLint(relpath)
        lint.visit(tree)
        out.extend(lint.findings)
    return out
