"""Pass 2: concurrency lint over starway_tpu/core/.

Two invariants from DESIGN.md §2 (the FireList discipline):

* ``callback-under-lock`` -- user callbacks are NEVER invoked while a
  worker lock is held.  Inside a ``with <x>.lock:`` (or ``*_lock``) block
  the only allowed pattern is *deferral*: append the callback (usually a
  lambda) to a ``fires`` list and run it after the lock is released via
  ``_run_fires``.  Flagged: any call to ``_run_fires`` inside a lock
  block, and any direct invocation of a callback-shaped name (``done``,
  ``fail``, ``cb`` ...).  Lambdas and nested defs are deferred execution
  and are skipped.

* ``blocking-call`` -- the engine thread is a shared event loop (one per
  worker, zero CPU when idle); a blocking call wedges every connection on
  it.  Flagged: ``time.sleep``, ``socket.create_connection`` without a
  ``timeout=``, ``sock.settimeout(None)``, ``sock.setblocking(True)``.
"""

from __future__ import annotations

import ast
from pathlib import Path

from .base import Finding, core_py_files, parse_or_finding, rel

#: Names that, when *called* under a lock, are overwhelmingly user
#: callbacks (the worker protocol's done/fail/recv/accept/close hooks).
_CALLBACK_NAMES = {
    "done", "fail", "cb", "callback", "user_done", "accept_cb", "close_cb",
    "done_cb", "fail_cb", "on_done", "on_fail",
}


def _terminal_name(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def _is_lock_expr(node: ast.AST) -> bool:
    name = _terminal_name(node)
    return name == "lock" or name.endswith("_lock")


class _LockLint(ast.NodeVisitor):
    def __init__(self, relpath: str):
        self.relpath = relpath
        self.lock_depth = 0
        self.findings: list = []

    # Function/lambda bodies are deferred execution: a callback *defined*
    # under a lock runs later, outside it (that is the allowed pattern).
    def _visit_deferred(self, node: ast.AST) -> None:
        saved, self.lock_depth = self.lock_depth, 0
        self.generic_visit(node)
        self.lock_depth = saved

    def visit_FunctionDef(self, node):        # noqa: N802
        self._visit_deferred(node)

    def visit_AsyncFunctionDef(self, node):   # noqa: N802
        self._visit_deferred(node)

    def visit_Lambda(self, node):             # noqa: N802
        self._visit_deferred(node)

    def visit_With(self, node):               # noqa: N802
        is_lock = any(_is_lock_expr(item.context_expr) for item in node.items)
        for item in node.items:
            self.visit(item.context_expr)
        if is_lock:
            self.lock_depth += 1
        for stmt in node.body:
            self.visit(stmt)
        if is_lock:
            self.lock_depth -= 1

    def visit_Call(self, node):               # noqa: N802
        if self.lock_depth > 0:
            name = _terminal_name(node.func)
            if name == "_run_fires":
                self.findings.append(Finding(
                    self.relpath, node.lineno, "callback-under-lock",
                    "_run_fires invoked inside a `with ...lock:` block -- "
                    "collect into `fires` and run after release "
                    "(DESIGN.md §2: callbacks never fire under a worker lock)"))
            elif name in _CALLBACK_NAMES:
                self.findings.append(Finding(
                    self.relpath, node.lineno, "callback-under-lock",
                    f"callback `{name}(...)` invoked inside a `with ...lock:` "
                    "block -- defer it via `fires.append(...)` instead"))
        self.generic_visit(node)


class _BlockingLint(ast.NodeVisitor):
    def __init__(self, relpath: str):
        self.relpath = relpath
        self.findings: list = []

    def visit_Call(self, node):               # noqa: N802
        func = node.func
        name = _terminal_name(func)
        if name == "sleep" and isinstance(func, ast.Attribute) \
                and _terminal_name(func.value) == "time":
            self.findings.append(Finding(
                self.relpath, node.lineno, "blocking-call",
                "time.sleep under core/ -- the engine thread is an event "
                "loop; use a deadline timer (Worker._add_timer) instead"))
        elif name == "create_connection" \
                and not any(kw.arg == "timeout" for kw in node.keywords) \
                and len(node.args) < 2:  # timeout is the 2nd positional
            self.findings.append(Finding(
                self.relpath, node.lineno, "blocking-call",
                "socket.create_connection without timeout= can block the "
                "engine thread indefinitely (STARWAY_CONNECT_TIMEOUT exists "
                "for this)"))
        elif name == "settimeout" and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and node.args[0].value is None:
            self.findings.append(Finding(
                self.relpath, node.lineno, "blocking-call",
                "settimeout(None) makes the socket blocking on the engine "
                "thread"))
        elif name == "setblocking" and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and node.args[0].value is True:
            self.findings.append(Finding(
                self.relpath, node.lineno, "blocking-call",
                "setblocking(True) on an engine-thread socket"))
        self.generic_visit(node)


def run(root: Path) -> list:
    out: list = []
    for path in core_py_files(root):
        relpath = rel(root, path)
        tree, err = parse_or_finding(path, relpath)
        if tree is None:
            out.append(err)
            continue
        for lint_cls in (_LockLint, _BlockingLint):
            lint = lint_cls(relpath)
            lint.visit(tree)
            out.extend(lint.findings)
    return out
