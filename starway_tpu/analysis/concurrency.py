"""Pass 2 (v2): concurrency discipline over the lint surface.

The v1 pass was a single-function syntactic lint; it missed everything
that crossed a call boundary (the PR-6 review crop: a sampler thread
blocking inside an accept under the sample lock, a ``TxCtl`` crashing an
engine-thread attribute read).  v2 keeps the two direct lints and adds
four interprocedural analyses over a call graph of the lint surface
(``core/`` + the declared extras, base.LINT_EXTRA_FILES):

* ``callback-under-lock`` -- direct (v1 shape: ``_run_fires`` or a
  callback-shaped name invoked lexically inside ``with ...lock:``) and
  now *reachable*: a call made while a worker lock is held whose callee
  (transitively, deferred lambda/def bodies excluded) invokes a user
  callback.  DESIGN.md §2: callbacks never fire under a worker lock.
* ``blocking-call`` -- v1 direct lint, unchanged: ``time.sleep``,
  ``create_connection`` without ``timeout=``, ``settimeout(None)``,
  ``setblocking(True)`` anywhere on the engine-thread surface.
* ``reachable-blocking`` -- a call made while a lock is held whose
  callee transitively reaches a blocking primitive (the sampler-accept
  class of bug: lexically clean, blocking one call down).
* ``lock-order`` -- a lock-acquisition graph spanning the Python locks
  (worker ``.lock``, telemetry ``_lock``/``_sample_lock``, swtrace
  ``_reg_lock``, fabric ``_lock``) and the native mutex sites
  (``lock_guard``/``unique_lock`` in sw_engine.cpp, brace-scoped);
  edges are lexical nesting plus lock-held call sites whose callees
  acquire; any cycle is a finding.
* ``duck-attr`` -- the TX-item protocol checker: values read from the
  shared tx/journal/waiting queues are duck-typed (TxData / TxDevpull /
  TxCtl, discovered as the conn.py classes defining ``sess_wrap``);
  every attribute touched on such a value must exist on EVERY concrete
  type unless narrowed by ``isinstance`` or defaulted via ``getattr`` --
  the exact class of the PR-6 ``TxCtl.counted`` engine-thread crash.
* ``lint-coverage`` -- a module directly under ``starway_tpu/`` that
  calls ``time.sleep`` without being part of the lint surface is a
  finding: new runtime modules must join base.LINT_EXTRA_FILES (or
  waive), so the pass file lists can never silently post-date the tree
  again (the gap that left starway_tpu/metrics.py unpoliced).

Name resolution is duck-typed like the code it checks: a call resolves
to every same-named function/method defined on the surface (capped at 4
candidates -- beyond that the name is too generic to mean anything).
That over-approximates edges, which is safe for cycle/reachability
detection and keeps the pass honest about what it can see.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Optional

from .base import (
    Finding,
    LINT_EXTRA_FILES,
    lint_py_files,
    parse_or_finding,
    read_text,
    rel,
)

#: Names that, when *called* under a lock, are overwhelmingly user
#: callbacks (the worker protocol's done/fail/recv/accept/close hooks).
_CALLBACK_NAMES = {
    "done", "fail", "cb", "callback", "user_done", "accept_cb", "close_cb",
    "done_cb", "fail_cb", "on_done", "on_fail",
}

#: Queue attributes whose elements are TX-item protocol values (the
#: seeding set for the duck-attr checker; core/conn.py's shared tx
#: queue, the session replay journal, and the backpressure park queue).
_ITEM_QUEUES = {"tx", "journal", "waiting"}

#: Beyond this many same-named definitions a call target is too generic
#: to resolve meaningfully (``close``, ``run``...).
_MAX_CANDIDATES = 4

_REACH_DEPTH = 8


def _terminal_name(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def _is_lock_expr(node: ast.AST) -> bool:
    name = _terminal_name(node)
    return name == "lock" or name.endswith("_lock")


def _lock_id(node: ast.AST, module: str) -> str:
    """Stable identity for a lock expression: module-level ``Name`` locks
    are per-module singletons (``telemetry._lock`` != ``fabric._lock``);
    attribute locks are an instance *class* keyed by attribute name
    (every ``x.lock`` is "the worker lock")."""
    if isinstance(node, ast.Name):
        return f"{module}.{node.id}"
    return f"*.{_terminal_name(node)}"


def _blocking_desc(node: ast.Call) -> Optional[str]:
    """Non-None when ``node`` is one of the blocking primitives."""
    func = node.func
    name = _terminal_name(func)
    if name == "sleep" and isinstance(func, ast.Attribute) \
            and _terminal_name(func.value) == "time":
        return "time.sleep"
    if name == "create_connection" \
            and not any(kw.arg == "timeout" for kw in node.keywords) \
            and len(node.args) < 2:  # timeout is the 2nd positional
        return "socket.create_connection without timeout="
    if name == "settimeout" and node.args \
            and isinstance(node.args[0], ast.Constant) \
            and node.args[0].value is None:
        return "settimeout(None)"
    if name == "setblocking" and node.args \
            and isinstance(node.args[0], ast.Constant) \
            and node.args[0].value is True:
        return "setblocking(True)"
    return None


# ------------------------------------------------------- direct lints (v1)


class _LockLint(ast.NodeVisitor):
    def __init__(self, relpath: str):
        self.relpath = relpath
        self.lock_depth = 0
        self.findings: list = []

    # Function/lambda bodies are deferred execution: a callback *defined*
    # under a lock runs later, outside it (that is the allowed pattern).
    def _visit_deferred(self, node: ast.AST) -> None:
        saved, self.lock_depth = self.lock_depth, 0
        self.generic_visit(node)
        self.lock_depth = saved

    def visit_FunctionDef(self, node):        # noqa: N802
        self._visit_deferred(node)

    def visit_AsyncFunctionDef(self, node):   # noqa: N802
        self._visit_deferred(node)

    def visit_Lambda(self, node):             # noqa: N802
        self._visit_deferred(node)

    def visit_With(self, node):               # noqa: N802
        is_lock = any(_is_lock_expr(item.context_expr) for item in node.items)
        for item in node.items:
            self.visit(item.context_expr)
        if is_lock:
            self.lock_depth += 1
        for stmt in node.body:
            self.visit(stmt)
        if is_lock:
            self.lock_depth -= 1

    def visit_Call(self, node):               # noqa: N802
        if self.lock_depth > 0:
            name = _terminal_name(node.func)
            if name == "_run_fires":
                self.findings.append(Finding(
                    self.relpath, node.lineno, "callback-under-lock",
                    "_run_fires invoked inside a `with ...lock:` block -- "
                    "collect into `fires` and run after release "
                    "(DESIGN.md §2: callbacks never fire under a worker lock)"))
            elif name in _CALLBACK_NAMES:
                self.findings.append(Finding(
                    self.relpath, node.lineno, "callback-under-lock",
                    f"callback `{name}(...)` invoked inside a `with ...lock:` "
                    "block -- defer it via `fires.append(...)` instead"))
        self.generic_visit(node)


class _BlockingLint(ast.NodeVisitor):
    def __init__(self, relpath: str):
        self.relpath = relpath
        self.findings: list = []

    def visit_Call(self, node):               # noqa: N802
        desc = _blocking_desc(node)
        if desc == "time.sleep":
            self.findings.append(Finding(
                self.relpath, node.lineno, "blocking-call",
                "time.sleep under the engine-thread surface -- use a "
                "deadline timer (Worker._add_timer) instead"))
        elif desc == "socket.create_connection without timeout=":
            self.findings.append(Finding(
                self.relpath, node.lineno, "blocking-call",
                "socket.create_connection without timeout= can block the "
                "engine thread indefinitely (STARWAY_CONNECT_TIMEOUT exists "
                "for this)"))
        elif desc == "settimeout(None)":
            self.findings.append(Finding(
                self.relpath, node.lineno, "blocking-call",
                "settimeout(None) makes the socket blocking on the engine "
                "thread"))
        elif desc == "setblocking(True)":
            self.findings.append(Finding(
                self.relpath, node.lineno, "blocking-call",
                "setblocking(True) on an engine-thread socket"))
        self.generic_visit(node)


# --------------------------------------------- interprocedural summaries


class _FuncInfo:
    __slots__ = ("name", "qualname", "relpath", "blocking", "callbacks",
                 "acquires", "calls")

    def __init__(self, name: str, qualname: str, relpath: str):
        self.name = name
        self.qualname = qualname
        self.relpath = relpath
        self.blocking: list = []    # (line, desc)
        self.callbacks: list = []   # (line, name)
        self.acquires: list = []    # (lock_id, line)
        self.calls: list = []       # (name, line, tuple(held lock ids))


class _Summarizer(ast.NodeVisitor):
    """One pass over a function body collecting its summary facts.
    Nested function/lambda bodies are deferred execution and excluded."""

    def __init__(self, info: _FuncInfo, module: str):
        self.info = info
        self.module = module
        self.held: list = []

    def visit_FunctionDef(self, node):        # noqa: N802
        pass  # deferred

    def visit_AsyncFunctionDef(self, node):   # noqa: N802
        pass

    def visit_Lambda(self, node):             # noqa: N802
        pass

    def visit_With(self, node):               # noqa: N802
        lock_ids = [_lock_id(item.context_expr, self.module)
                    for item in node.items
                    if _is_lock_expr(item.context_expr)]
        for item in node.items:
            self.visit(item.context_expr)
        for lid in lock_ids:
            self.info.acquires.append((lid, node.lineno))
            self.held.append(lid)
        for stmt in node.body:
            self.visit(stmt)
        for _ in lock_ids:
            self.held.pop()

    def visit_Call(self, node):               # noqa: N802
        desc = _blocking_desc(node)
        if desc is not None:
            self.info.blocking.append((node.lineno, desc))
        name = _terminal_name(node.func)
        if name in _CALLBACK_NAMES or name == "_run_fires":
            self.info.callbacks.append((node.lineno, name))
        if name:
            self.info.calls.append((name, node.lineno, tuple(self.held)))
        self.generic_visit(node)


def _index_functions(root: Path, files: list) -> tuple[dict, list]:
    """{terminal name: [_FuncInfo]} over the surface, plus parse
    findings.  Only top-level functions and class methods are indexed
    (nested defs are deferred bodies)."""
    index: dict = {}
    findings: list = []
    for path in files:
        relpath = rel(root, path)
        module = path.stem
        tree, err = parse_or_finding(path, relpath)
        if tree is None:
            findings.append(err)
            continue
        defs: list = []
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.append((node.name, node))
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        defs.append((f"{node.name}.{sub.name}", sub))
        for qualname, node in defs:
            info = _FuncInfo(node.name, qualname, relpath)
            summ = _Summarizer(info, module)
            for stmt in node.body:
                summ.visit(stmt)
            index.setdefault(node.name, []).append(info)
    return index, findings


def _resolve(index: dict, name: str) -> list:
    cands = index.get(name, [])
    return cands if 0 < len(cands) <= _MAX_CANDIDATES else []


#: Sentinel for "this exploration was cut short by the cycle guard or
#: the depth bound": such a None is NOT a proven absence and must never
#: be memoized, or the answer becomes query-order dependent (a cycle
#: member probed first would cache a false 'unreachable' that later
#: suppresses a real finding).
_TRUNCATED = ("__truncated__",)


def _reach_fact(index: dict, info: _FuncInfo, kind: str,
                _memo: dict, _stack: set, depth: int = 0):
    """First (chain, line, detail) through which ``info`` reaches a
    blocking primitive / callback invocation; None when proven absent;
    ``_TRUNCATED`` when the search was cut short (cycle / depth bound)
    and absence is therefore unproven.  ``kind`` is
    "blocking" | "callback"."""
    key = (id(info), kind)
    if key in _memo:
        return _memo[key]
    if key in _stack or depth > _REACH_DEPTH:
        return _TRUNCATED
    direct = info.blocking if kind == "blocking" else info.callbacks
    if direct:
        line, detail = direct[0]
        _memo[key] = ((info.qualname,), line, detail)
        return _memo[key]
    _stack.add(key)
    result = None
    truncated = False
    for name, line, _held in info.calls:
        for callee in _resolve(index, name):
            sub = _reach_fact(index, callee, kind, _memo, _stack, depth + 1)
            if sub is _TRUNCATED:
                truncated = True
                continue
            if sub is not None:
                result = ((info.qualname,) + sub[0], sub[1], sub[2])
                break
        if result is not None:
            break
    _stack.discard(key)
    if result is None and truncated:
        return _TRUNCATED  # unproven: recompute from the next query root
    _memo[key] = result
    return result


def _interproc_findings(index: dict) -> list:
    out: list = []
    memo: dict = {}
    for infos in index.values():
        for info in infos:
            for name, line, held in info.calls:
                if not held:
                    continue
                for callee in _resolve(index, name):
                    blk = _reach_fact(index, callee, "blocking", memo, set())
                    if blk is not None and blk is not _TRUNCATED:
                        chain = " -> ".join(blk[0])
                        out.append(Finding(
                            info.relpath, line, "reachable-blocking",
                            f"`{name}(...)` called while holding "
                            f"{held[-1]} reaches {blk[2]} "
                            f"({chain}, {callee.relpath}:{blk[1]}) -- "
                            "blocking while a worker lock is held "
                            "wedges every thread behind it"))
                        break
                for callee in _resolve(index, name):
                    cb = _reach_fact(index, callee, "callback", memo, set())
                    if cb is not None and cb is not _TRUNCATED:
                        chain = " -> ".join(cb[0])
                        out.append(Finding(
                            info.relpath, line, "callback-under-lock",
                            f"`{name}(...)` called while holding "
                            f"{held[-1]} reaches user callback "
                            f"`{cb[2]}` ({chain}, {callee.relpath}:{cb[1]}) "
                            "-- callbacks never fire under a worker lock "
                            "(DESIGN.md §2)"))
                        break
    return out


# --------------------------------------------------------- lock ordering


def _acquire_reach(index: dict, info: _FuncInfo, depth: int,
                   seen: set) -> list:
    """Locks acquired by ``info`` or its callees (depth-limited)."""
    if id(info) in seen or depth > 3:
        return []
    seen.add(id(info))
    out = [(lid, info.relpath, line) for lid, line in info.acquires]
    for name, _line, _held in info.calls:
        for callee in _resolve(index, name):
            out.extend(_acquire_reach(index, callee, depth + 1, seen))
    return out


_CPP_GUARD_RE = re.compile(
    r"std::(?:lock_guard|unique_lock|scoped_lock)\s*<[^>]*>\s*\w+\s*\(\s*"
    r"([\w.>\-]+)\s*[,)]")


def _cpp_lock_edges(root: Path) -> tuple[list, list]:
    """(edges, acquire sites) from the native engine: brace-scoped
    ``lock_guard``/``unique_lock`` declarations; a guard declared while
    another guard's scope is still open is an ordering edge."""
    path = root / "native" / "sw_engine.cpp"
    if not path.is_file():
        return [], []
    relpath = "native/sw_engine.cpp"
    text = read_text(path)
    edges: list = []
    sites: list = []
    depth = 0
    held: list = []  # (lock_id, depth)
    line = 1
    i = 0
    while i < len(text):
        ch = text[i]
        if ch == "\n":
            line += 1
            i += 1
            continue
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            while held and held[-1][1] > depth:
                held.pop()
        elif ch == "s":
            m = _CPP_GUARD_RE.match(text, i)
            if m:
                raw = m.group(1)
                lid = "native." + raw.split("->")[-1].split(".")[-1]
                sites.append((lid, relpath, line))
                for outer, _d in held:
                    if outer != lid:
                        edges.append((outer, lid, relpath, line))
                held.append((lid, depth))
                i = m.end()
                continue
        i += 1
    return edges, sites


class _LockNest(ast.NodeVisitor):
    """Collect lexical lock-nesting edges within one function."""

    def __init__(self, relpath: str, module: str):
        self.relpath = relpath
        self.module = module
        self.held: list = []
        self.edges: list = []

    def visit_FunctionDef(self, node):        # noqa: N802
        pass

    def visit_AsyncFunctionDef(self, node):   # noqa: N802
        pass

    def visit_Lambda(self, node):             # noqa: N802
        pass

    def visit_With(self, node):               # noqa: N802
        lock_ids = [_lock_id(item.context_expr, self.module)
                    for item in node.items
                    if _is_lock_expr(item.context_expr)]
        for lid in lock_ids:
            for outer in self.held:
                if outer != lid:
                    self.edges.append((outer, lid, self.relpath,
                                       node.lineno))
            self.held.append(lid)
        for stmt in node.body:
            self.visit(stmt)
        for _ in lock_ids:
            self.held.pop()


def _find_cycle(edges: list) -> Optional[list]:
    graph: dict = {}
    sites: dict = {}
    for a, b, f, ln in edges:
        graph.setdefault(a, set()).add(b)
        sites.setdefault((a, b), (f, ln))
    color: dict = {}
    stack: list = []

    def dfs(n) -> Optional[list]:
        color[n] = 1
        stack.append(n)
        for m in sorted(graph.get(n, ())):
            if color.get(m, 0) == 1:
                return stack[stack.index(m):] + [m]
            if color.get(m, 0) == 0:
                cyc = dfs(m)
                if cyc is not None:
                    return cyc
        stack.pop()
        color[n] = 2
        return None

    for n in sorted(graph):
        if color.get(n, 0) == 0:
            cyc = dfs(n)
            if cyc is not None:
                return cyc
    return None


def _lock_order(root: Path, files: list, index: dict) -> list:
    edges: list = []
    for path in files:
        relpath = rel(root, path)
        tree, _err = parse_or_finding(path, relpath)
        if tree is None:
            continue
        nest = _LockNest(relpath, path.stem)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for stmt in node.body:
                    nest.visit(stmt)
        edges.extend(nest.edges)
    # Interprocedural: a call made with lock A held whose callee
    # (transitively) acquires B is an A -> B edge.
    for infos in index.values():
        for info in infos:
            for name, line, held in info.calls:
                if not held:
                    continue
                for callee in _resolve(index, name):
                    for lid, f, ln in _acquire_reach(index, callee, 0, set()):
                        for outer in held:
                            if outer != lid:
                                edges.append((outer, lid, info.relpath,
                                              line))
    cpp_edges, _sites = _cpp_lock_edges(root)
    edges.extend(cpp_edges)
    cycle = _find_cycle(edges)
    if cycle is None:
        return []
    # Anchor at the edge closing the cycle (the last hop's site).
    a, b = cycle[-2], cycle[-1]
    site = next(((f, ln) for x, y, f, ln in edges if (x, y) == (a, b)),
                (None, 1))
    return [Finding(
        site[0] or "starway_tpu/core/engine.py", site[1], "lock-order",
        "lock acquisition cycle " + " -> ".join(cycle) + " -- two threads "
        "taking these locks in opposite orders deadlock (DESIGN.md §16)")]


# -------------------------------------------------- duck-type attributes


def _protocol_classes(root: Path) -> dict:
    """{class name: attribute set} for the TX-item protocol: the conn.py
    classes defining ``sess_wrap`` (TxData / TxDevpull / TxCtl today;
    discovery keeps a 4th item kind honest automatically)."""
    path = root / "starway_tpu" / "core" / "conn.py"
    if not path.is_file():
        return {}
    tree, _err = parse_or_finding(path, "starway_tpu/core/conn.py")
    if tree is None:
        return {}
    out: dict = {}
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        methods = {n.name for n in node.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        if "sess_wrap" not in methods:
            continue
        attrs = set(methods)
        for sub in node.body:
            if isinstance(sub, ast.Assign):
                for tgt in sub.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == "__slots__" \
                            and isinstance(sub.value, (ast.Tuple, ast.List)):
                        attrs |= {e.value for e in sub.value.elts
                                  if isinstance(e, ast.Constant)
                                  and isinstance(e.value, str)}
        attrs.discard("__weakref__")
        out[node.name] = attrs
    return out


def _queue_expr(node: ast.AST) -> bool:
    """True for an expression denoting a TX-item queue (``self.tx``,
    ``sess.journal``, ``self.sess.waiting``...)."""
    return isinstance(node, ast.Attribute) and node.attr in _ITEM_QUEUES


class _DuckLint:
    """Flow-lite duck-type attribute checker for one function."""

    def __init__(self, relpath: str, classes: dict):
        self.relpath = relpath
        self.classes = classes
        self.all_types = frozenset(classes)
        self.findings: list = []

    def check(self, fn: ast.AST) -> None:
        self._body(fn.body, {}, set())

    # env: var name -> frozenset of possible protocol class names
    # colls: names bound to list(queue) style protocol collections
    def _body(self, stmts: list, env: dict, colls: set) -> None:
        for stmt in stmts:
            self._stmt(stmt, env, colls)

    def _seed_source(self, value: ast.AST, env: dict, colls: set) -> bool:
        """Does ``value`` yield a protocol item?  (queue[0], queue.popleft(),
        next(iter(queue))...)"""
        if isinstance(value, ast.Subscript) and _queue_expr(value.value):
            return True
        if isinstance(value, ast.Call) \
                and isinstance(value.func, ast.Attribute) \
                and value.func.attr in ("popleft", "pop") \
                and _queue_expr(value.func.value):
            return True
        return False

    def _coll_source(self, value: ast.AST, colls: set) -> bool:
        """list(queue) / tuple(queue) -- a named protocol collection."""
        if isinstance(value, ast.Call) and isinstance(value.func, ast.Name) \
                and value.func.id in ("list", "tuple") and value.args:
            arg = value.args[0]
            return _queue_expr(arg) or (isinstance(arg, ast.Name)
                                        and arg.id in colls)
        return False

    def _iter_seeds(self, it: ast.AST, colls: set) -> bool:
        return _queue_expr(it) or (isinstance(it, ast.Name)
                                   and it.id in colls)

    def _stmt(self, stmt: ast.AST, env: dict, colls: set) -> None:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            tgt = stmt.targets[0]
            self._expr(stmt.value, env, colls)
            if isinstance(tgt, ast.Name):
                if self._seed_source(stmt.value, env, colls):
                    env[tgt.id] = self.all_types
                elif self._coll_source(stmt.value, colls):
                    colls.add(tgt.id)
                    env.pop(tgt.id, None)
                else:
                    env.pop(tgt.id, None)
                    colls.discard(tgt.id)
            elif isinstance(tgt, (ast.Tuple, ast.List)):
                # Tuple targets rebind their element names (the `for
                # item, offered in spans:` shape) -- unseed them.
                for sub in tgt.elts:
                    for name in ast.walk(sub):
                        if isinstance(name, ast.Name):
                            env.pop(name.id, None)
                            colls.discard(name.id)
            else:
                # Attribute/Subscript target: a STORE on a protocol value
                # (`item.counted = True`) must satisfy the same contract
                # as a read -- and does not rebind the base name.
                self._expr(tgt, env, colls)
            return
        if isinstance(stmt, ast.For):
            self._expr(stmt.iter, env, colls)
            if isinstance(stmt.target, ast.Name):
                if self._iter_seeds(stmt.iter, colls):
                    env[stmt.target.id] = self.all_types
                else:
                    env.pop(stmt.target.id, None)
            else:
                for sub in ast.walk(stmt.target):
                    if isinstance(sub, ast.Name):
                        env.pop(sub.id, None)
            self._body(stmt.body, env, colls)
            self._body(stmt.orelse, env, colls)
            return
        if isinstance(stmt, ast.If):
            narrowed = self._narrow(stmt.test, env, colls)
            self._body(stmt.body, narrowed, colls)
            self._body(stmt.orelse, dict(env), colls)
            return
        if isinstance(stmt, (ast.While,)):
            # A while test narrows its body exactly like an if test.
            narrowed = self._narrow(stmt.test, env, colls)
            self._body(stmt.body, narrowed, colls)
            self._body(stmt.orelse, dict(env), colls)
            return
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                self._expr(item.context_expr, env, colls)
            self._body(stmt.body, env, colls)
            return
        if isinstance(stmt, ast.Try):
            self._body(stmt.body, env, colls)
            for h in stmt.handlers:
                self._body(h.body, dict(env), colls)
            self._body(stmt.orelse, env, colls)
            self._body(stmt.finalbody, env, colls)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs: deferred, out of scope
        if isinstance(stmt, ast.Expr):
            self._expr(stmt.value, env, colls)
            return
        if isinstance(stmt, ast.Return) and stmt.value is not None:
            self._expr(stmt.value, env, colls)
            return
        if isinstance(stmt, ast.AugAssign):
            self._expr(stmt.target, env, colls)
            self._expr(stmt.value, env, colls)
            return
        for node in ast.iter_child_nodes(stmt):
            if isinstance(node, ast.expr):
                self._expr(node, env, colls)
            elif isinstance(node, ast.stmt):
                self._stmt(node, env, colls)

    def _narrow(self, test: ast.AST, env: dict, colls: set) -> dict:
        """Evaluate a test for its checks AND return the env the If body
        sees (isinstance narrowing, including across `and` conjuncts)."""
        narrowed = dict(env)
        if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
            for val in test.values:
                narrowed = self._narrow_one(val, narrowed, colls)
            return narrowed
        return self._narrow_one(test, narrowed, colls)

    def _narrow_one(self, test: ast.AST, env: dict, colls: set) -> dict:
        pos = test
        negate = False
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            pos = test.operand
            negate = True
        if isinstance(pos, ast.Call) and _terminal_name(pos.func) == "isinstance" \
                and len(pos.args) == 2 and isinstance(pos.args[0], ast.Name) \
                and pos.args[0].id in env:
            var = pos.args[0].id
            named = set()
            cls_arg = pos.args[1]
            elts = cls_arg.elts if isinstance(cls_arg, (ast.Tuple, ast.List)) \
                else [cls_arg]
            for e in elts:
                named.add(_terminal_name(e))
            hit = named & set(self.all_types)
            if hit:
                out = dict(env)
                out[var] = (env[var] - hit) if negate \
                    else (env[var] & frozenset(hit))
                return out
            return env
        self._expr(test, env, colls)
        return env

    def _expr(self, node: ast.AST, env: dict, colls: set) -> None:
        if isinstance(node, ast.BoolOp) and isinstance(node.op, ast.And):
            cur = dict(env)
            for val in node.values:
                cur = self._narrow_one(val, cur, colls)
            return
        if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
            var = node.value.id
            types = env.get(var)
            if types:
                missing = [c for c in sorted(types)
                           if node.attr not in self.classes[c]]
                if missing:
                    self.findings.append(Finding(
                        self.relpath, node.lineno, "duck-attr",
                        f"attribute `{node.attr}` read on a TX-item "
                        f"protocol value that may be {'/'.join(missing)} "
                        "-- which does not define it (narrow with "
                        "isinstance or use getattr; the PR-6 "
                        "TxCtl.counted crash class)"))
            self._expr(node.value, env, colls)
            return
        if isinstance(node, (ast.GeneratorExp, ast.ListComp, ast.SetComp)):
            sub_env = dict(env)
            for gen in node.generators:
                self._expr(gen.iter, sub_env, colls)
                if isinstance(gen.target, ast.Name):
                    if self._iter_seeds(gen.iter, colls):
                        sub_env[gen.target.id] = self.all_types
                    else:
                        sub_env.pop(gen.target.id, None)
                for cond in gen.ifs:
                    sub_env = self._narrow(cond, sub_env, colls)
            self._expr(node.elt, sub_env, colls)
            return
        if isinstance(node, ast.Lambda):
            return  # deferred
        if isinstance(node, ast.Call):
            # getattr(item, "x", default) is the sanctioned escape hatch.
            if _terminal_name(node.func) == "getattr":
                for arg in node.args[1:]:
                    self._expr(arg, env, colls)
                return
            self._expr(node.func, env, colls)
            for arg in node.args:
                self._expr(arg, env, colls)
            for kw in node.keywords:
                self._expr(kw.value, env, colls)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child, env, colls)


def _duck_findings(root: Path, files: list) -> list:
    classes = _protocol_classes(root)
    if not classes:
        return []  # conn.py reshaped: protomodel's vacuity guard owns it
    out: list = []
    for path in files:
        relpath = rel(root, path)
        tree, _err = parse_or_finding(path, relpath)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                lint = _DuckLint(relpath, classes)
                lint.check(node)
                out.extend(lint.findings)
    return out


# ------------------------------------------------------- coverage audit


def _coverage_findings(root: Path) -> list:
    """Top-level starway_tpu modules using policed primitives must be in
    the lint surface; declared surface extras must exist."""
    out: list = []
    surface = {str(root / rel_) for rel_ in LINT_EXTRA_FILES}
    for rel_ in LINT_EXTRA_FILES:
        if not (root / rel_).is_file():
            out.append(Finding(
                rel_, 1, "lint-coverage",
                f"{rel_} is declared in the lint surface "
                "(analysis/base.py LINT_EXTRA_FILES) but does not exist "
                "-- the pass file lists drifted from the tree"))
    pkg = root / "starway_tpu"
    if not pkg.is_dir():
        return out
    for path in sorted(pkg.glob("*.py")):
        if str(path) in surface:
            continue
        relpath = rel(root, path)
        tree, err = parse_or_finding(path, relpath)
        if tree is None:
            continue  # top-level modules outside the surface: no parse gate
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and _blocking_desc(node) == "time.sleep":
                out.append(Finding(
                    relpath, node.lineno, "lint-coverage",
                    f"{relpath} calls time.sleep but is outside the "
                    "swcheck lint surface -- add it to LINT_EXTRA_FILES "
                    "(analysis/base.py) so the concurrency/hotpath passes "
                    "police it, or waive here"))
                break
    return out


# ----------------------------------------------------------------- pass


def run(root: Path) -> list:
    out: list = []
    files = lint_py_files(root)
    for path in files:
        relpath = rel(root, path)
        tree, err = parse_or_finding(path, relpath)
        if tree is None:
            out.append(err)
            continue
        for lint_cls in (_LockLint, _BlockingLint):
            lint = lint_cls(relpath)
            lint.visit(tree)
            out.extend(lint.findings)
    index, idx_findings = _index_functions(root, files)
    out.extend(idx_findings)
    out.extend(_interproc_findings(index))
    out.extend(_lock_order(root, files, index))
    out.extend(_duck_findings(root, files))
    out.extend(_coverage_findings(root))
    return out
