"""Extract the C++ side of the two-engine contract from native sources.

Deliberately lightweight: the native tree is plain C++17 with C-style
declarations in the extern "C" header, so regexes over comment-stripped
text are enough -- no compiler needed (swcheck must run in a bare venv).
The extraction surface is part of the contract: constants must stay
``constexpr`` initialisations, reason strings ``const char* kName = "...";``,
and ABI declarations single-statement prototypes in sw_engine.h (see
DESIGN.md §11 for the add-a-constant recipe).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional

from .base import read_text


def _strip_comments(text: str) -> str:
    """Remove // and /* */ comments, preserving line numbers (block
    comments are replaced by their newlines)."""

    def _block(m: re.Match) -> str:
        return "\n" * m.group(0).count("\n")

    text = re.sub(r"/\*.*?\*/", _block, text, flags=re.S)
    text = re.sub(r"//[^\n]*", "", text)
    return text


def _line_of(text: str, pos: int) -> int:
    return text.count("\n", 0, pos) + 1


_INT_SUFFIX = re.compile(r"(?<=[0-9a-fA-FxX])(?:[uU][lL]{0,2}|[lL]{1,2}[uU]?)\b")


def _eval_cpp_int(expr: str, env: dict) -> Optional[int]:
    """Evaluate a constexpr initialiser: ints (with u/l suffixes), hex,
    shifts, + * parentheses, and previously-extracted constant names."""
    expr = _INT_SUFFIX.sub("", expr.strip())
    if not re.fullmatch(r"[\w\s+\-*()<>x]+", expr):
        return None
    try:
        return int(eval(expr, {"__builtins__": {}}, dict(env)))  # noqa: S307
    except Exception:
        return None


@dataclass
class CppFunc:
    name: str
    ret: str                 # normalised C type, e.g. "char*", "int", "void"
    args: list               # normalised C types; [] for (void)
    line: int


@dataclass
class CppModel:
    constants: dict = field(default_factory=dict)   # name -> (int, line)
    reasons: dict = field(default_factory=dict)     # kName -> (str, line)
    trace_events: dict = field(default_factory=dict)  # kEv* -> (str, line)
    counter_names: Optional[tuple] = None           # (list[str], line)
    gauge_names: Optional[tuple] = None             # (list[str], line)
    hist_names: Optional[tuple] = None              # (list[str], line)
    stall_reasons: Optional[tuple] = None           # (list[str], line)
    version: Optional[tuple] = None                 # (str, line) from .cpp
    header_version: Optional[tuple] = None          # (str, line) from .h
    functions: dict = field(default_factory=dict)   # name -> CppFunc (.h)
    callbacks: dict = field(default_factory=dict)   # typedef -> CppFunc (.h)
    cpp_text: str = ""
    cpp_code: str = ""   # comment-stripped: literals that survive are CODE
    cpp_file: str = "native/sw_engine.cpp"
    h_file: str = "native/sw_engine.h"


_CONSTEXPR_RE = re.compile(
    r"(?:static\s+)?constexpr\s+(?:uint8_t|uint16_t|uint32_t|uint64_t|int|size_t|unsigned)\s+"
    r"([^;=]+=[^;]+);"
)

_REASON_RE = re.compile(r'const\s+char\s*\*\s*(k\w+)\s*=\s*"([^"]*)"\s*;')

# const char* kCounterNames[] = {"a", "b", ...}; -- the swtrace counter
# vocabulary (contract-trace pairs it with core/swtrace.py COUNTER_NAMES).
_COUNTERS_RE = re.compile(
    r"const\s+char\s*\*\s*kCounterNames\s*\[\s*\]\s*=\s*\{([^}]*)\}", re.S
)

# const char* kGaugeNames[] = {"a", ...}; -- the swscope per-conn gauge
# vocabulary (contract-trace pairs it with core/telemetry.py GAUGE_NAMES).
_GAUGES_RE = re.compile(
    r"const\s+char\s*\*\s*kGaugeNames\s*\[\s*\]\s*=\s*\{([^}]*)\}", re.S
)

# const char* kHistNames[] = {"a", ...}; -- the swpulse histogram
# vocabulary (contract-pulse pairs it with core/swtrace.py HIST_NAMES).
_HISTS_RE = re.compile(
    r"const\s+char\s*\*\s*kHistNames\s*\[\s*\]\s*=\s*\{([^}]*)\}", re.S
)

# const char* kStallReasons[] = {"stall-flush", ...}; -- the swpulse
# sentinel vocabulary (contract-pulse pairs it with STALL_REASONS).
_STALLS_RE = re.compile(
    r"const\s+char\s*\*\s*kStallReasons\s*\[\s*\]\s*=\s*\{([^}]*)\}", re.S
)

_VERSION_RE = re.compile(
    r'const\s+char\s*\*\s*sw_version\s*\(\s*\)\s*\{\s*return\s*"([^"]+)"\s*;'
)

_HDR_VERSION_RE = re.compile(r'swcheck:\s*engine-version\s*"([^"]+)"')

_TYPEDEF_RE = re.compile(
    r"typedef\s+(\w[\w\s\*]*?)\(\s*\*\s*(sw_\w+)\s*\)\s*\(([^)]*)\)\s*;", re.S
)

# No leading anchor: an anchor character (`;` of the previous declaration)
# would be consumed by each match and make finditer skip every other
# prototype.  The `sw_\w+(` shape is specific enough on its own -- no
# parameter in this header is itself a call expression.
_FUNC_RE = re.compile(
    r"((?:const\s+)?\w+\s*\**)\s*\b(sw_\w+)\s*\(([^;{)]*)\)\s*;", re.S
)


def _norm_type(raw: str) -> str:
    toks = raw.replace("*", " * ").split()
    toks = [t for t in toks if t != "const"]
    return "".join(toks) if toks else ""


def _parse_args(raw: str) -> list:
    raw = raw.strip()
    if not raw or raw == "void":
        return []
    out = []
    for piece in raw.split(","):
        toks = piece.replace("*", " * ").split()
        toks = [t for t in toks if t != "const"]
        # Drop a trailing parameter name (everything here is "type name";
        # the name is the token after the last type word / '*').
        if len(toks) > 1 and toks[-1] != "*" and re.fullmatch(r"\w+", toks[-1]):
            toks = toks[:-1]
        out.append("".join(toks))
    return out


def extract_cpp(root: Path) -> CppModel:
    model = CppModel()
    cpp_path = root / "native" / "sw_engine.cpp"
    h_path = root / "native" / "sw_engine.h"

    if cpp_path.is_file():
        raw = read_text(cpp_path)
        model.cpp_text = raw
        text = _strip_comments(raw)
        model.cpp_code = text
        for m in _CONSTEXPR_RE.finditer(text):
            line = _line_of(text, m.start())
            env = {k: v for k, (v, _) in model.constants.items()}
            for decl in m.group(1).split(","):
                if "=" not in decl:
                    continue
                name, expr = decl.split("=", 1)
                name = name.strip()
                val = _eval_cpp_int(expr, env)
                if re.fullmatch(r"\w+", name) and val is not None:
                    model.constants[name] = (val, line)
                    env[name] = val
        for m in _REASON_RE.finditer(text):
            name = m.group(1)
            entry = (m.group(2), _line_of(text, m.start()))
            if name.startswith("kEv"):
                model.trace_events[name] = entry
            else:
                model.reasons[name] = entry
        m = _COUNTERS_RE.search(text)
        if m:
            names = re.findall(r'"([^"]*)"', m.group(1))
            model.counter_names = (names, _line_of(text, m.start()))
        m = _GAUGES_RE.search(text)
        if m:
            names = re.findall(r'"([^"]*)"', m.group(1))
            model.gauge_names = (names, _line_of(text, m.start()))
        m = _HISTS_RE.search(text)
        if m:
            names = re.findall(r'"([^"]*)"', m.group(1))
            model.hist_names = (names, _line_of(text, m.start()))
        m = _STALLS_RE.search(text)
        if m:
            names = re.findall(r'"([^"]*)"', m.group(1))
            model.stall_reasons = (names, _line_of(text, m.start()))
        m = _VERSION_RE.search(text)
        if m:
            model.version = (m.group(1), _line_of(text, m.start()))

    if h_path.is_file():
        raw = read_text(h_path)
        m = _HDR_VERSION_RE.search(raw)
        if m:
            model.header_version = (m.group(1), _line_of(raw, m.start()))
        text = _strip_comments(raw)
        for m in _TYPEDEF_RE.finditer(text):
            model.callbacks[m.group(2)] = CppFunc(
                name=m.group(2),
                ret=_norm_type(m.group(1)),
                args=_parse_args(m.group(3)),
                line=_line_of(text, m.start()),
            )
        for m in _FUNC_RE.finditer(text):
            name = m.group(2)
            if name in model.callbacks:
                continue
            model.functions[name] = CppFunc(
                name=name,
                ret=_norm_type(m.group(1)),
                args=_parse_args(m.group(3)),
                line=_line_of(text, m.start(2)),
            )

    return model
