"""Extract the Python side of the two-engine contract.

Sources are parsed with ``ast`` -- never imported -- so the checker can run
against mutated copies of the tree (tests/test_swcheck.py) and in a venv
with no third-party packages installed.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional

from .base import read_text


def _const_eval(node: ast.AST, env: dict) -> Optional[int]:
    """Fold a small integer expression: literals, names from ``env``, and
    + - * ** << >> arithmetic (the shapes layout constants are written in)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.Name):
        v = env.get(node.id)
        return v if isinstance(v, int) else None
    if isinstance(node, ast.BinOp):
        lo = _const_eval(node.left, env)
        hi = _const_eval(node.right, env)
        if lo is None or hi is None:
            return None
        try:
            if isinstance(node.op, ast.Add):
                return lo + hi
            if isinstance(node.op, ast.Sub):
                return lo - hi
            if isinstance(node.op, ast.Mult):
                return lo * hi
            if isinstance(node.op, ast.Pow):
                return lo ** hi if hi < 128 else None
            if isinstance(node.op, ast.LShift):
                return lo << hi if hi < 128 else None
            if isinstance(node.op, ast.RShift):
                return lo >> hi
            if isinstance(node.op, ast.FloorDiv):
                return lo // hi if hi else None
        except (OverflowError, ValueError):
            return None
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = _const_eval(node.operand, env)
        return -v if v is not None else None
    return None


def module_int_constants(tree: ast.Module) -> dict:
    """Top-level NAME = <int expr> assignments -> {name: (value, line)}."""
    out: dict = {}
    env: dict = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            val = _const_eval(node.value, env)
            if val is not None:
                name = node.targets[0].id
                out[name] = (val, node.lineno)
                env[name] = val
    return out


def module_str_constants(tree: ast.Module) -> dict:
    out: dict = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            out[node.targets[0].id] = (node.value.value, node.lineno)
    return out


def code_string_literals(tree: ast.Module) -> set:
    """Every string literal that is CODE, not documentation: all str
    constants except docstrings (first Expr of a module/class/function
    body).  Searching these instead of raw source keeps vacuity out of
    substring checks -- a key surviving only in a comment or docstring
    must not count as 'referenced'."""
    doc_ids = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = node.body
            if body and isinstance(body[0], ast.Expr) \
                    and isinstance(body[0].value, ast.Constant) \
                    and isinstance(body[0].value.value, str):
                doc_ids.add(id(body[0].value))
    return {
        node.value
        for node in ast.walk(tree)
        if isinstance(node, ast.Constant) and isinstance(node.value, str)
        and id(node) not in doc_ids
    }


def canon_ctypes(node: ast.AST) -> str:
    """Canonical spelling for a ctypes signature element:
    ``ctypes.c_void_p`` -> "c_void_p", ``_DONE_CB`` -> "_DONE_CB",
    ``ctypes.POINTER(ctypes.c_uint64)`` -> "POINTER(c_uint64)"."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Call):
        args = ", ".join(canon_ctypes(a) for a in node.args)
        return f"{canon_ctypes(node.func)}({args})"
    if isinstance(node, ast.Constant):
        return repr(node.value)
    return ast.dump(node)


@dataclass
class PyModel:
    frames: dict = field(default_factory=dict)       # T_* -> (int, line)
    header_fmt: Optional[tuple] = None               # (fmt str, line)
    sdata_sub_fmt: Optional[tuple] = None            # (fmt str, line)
    frames_doc: Optional[str] = None                 # module docstring
    shm: dict = field(default_factory=dict)          # layout name -> (int, line)
    doorbell: dict = field(default_factory=dict)     # DB_* -> (int, line)
    reasons: dict = field(default_factory=dict)      # REASON_* -> (str, line)
    argtypes: dict = field(default_factory=dict)     # fn -> (list[str], line)
    restype: dict = field(default_factory=dict)      # fn -> (str, line)
    cfunctypes: dict = field(default_factory=dict)   # _X_CB -> (list[str], line)
    engine_strings: set = field(default_factory=set)  # engine.py code literals
    trace_events: dict = field(default_factory=dict)  # EV_* -> (str, line)
    counter_names: Optional[tuple] = None            # (list[str], line)
    gauge_names: Optional[tuple] = None              # (list[str], line)
    hist_names: Optional[tuple] = None               # (list[str], line)
    hist_buckets: Optional[tuple] = None             # (int, line)
    stall_reasons: Optional[tuple] = None            # (list[str], line)
    native_text: str = ""                            # core/native.py source
    files: dict = field(default_factory=dict)        # logical -> repo-rel path


def _parse(path: Path) -> Optional[ast.Module]:
    try:
        return ast.parse(read_text(path))
    except (OSError, SyntaxError):
        return None


def extract_py(root: Path) -> PyModel:
    model = PyModel()
    core = root / "starway_tpu" / "core"
    model.files = {
        "frames": "starway_tpu/core/frames.py",
        "shmring": "starway_tpu/core/shmring.py",
        "conn": "starway_tpu/core/conn.py",
        "native": "starway_tpu/core/native.py",
        "engine": "starway_tpu/core/engine.py",
        "errors": "starway_tpu/errors.py",
        "swtrace": "starway_tpu/core/swtrace.py",
        "telemetry": "starway_tpu/core/telemetry.py",
    }

    tree = _parse(core / "frames.py")
    if tree is not None:
        model.frames = {
            k: v for k, v in module_int_constants(tree).items()
            if k.startswith("T_")
        }
        model.frames_doc = ast.get_docstring(tree)
        for node in tree.body:
            # HEADER = struct.Struct("<BQQ") / SDATA_SUB = struct.Struct("<QQQ")
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id in ("HEADER", "SDATA_SUB") \
                    and isinstance(node.value, ast.Call) \
                    and node.value.args \
                    and isinstance(node.value.args[0], ast.Constant) \
                    and isinstance(node.value.args[0].value, str):
                rec = (node.value.args[0].value, node.lineno)
                if node.targets[0].id == "HEADER":
                    model.header_fmt = rec
                else:
                    model.sdata_sub_fmt = rec

    tree = _parse(core / "shmring.py")
    if tree is not None:
        consts = module_int_constants(tree)
        for name in ("MAGIC", "GLOBAL_HDR", "RING_HDR", "DATA_OFF",
                     "OFF_TAIL", "OFF_HEAD", "REC_HDR"):
            if name in consts:
                model.shm[name] = consts[name]

    tree = _parse(core / "conn.py")
    if tree is not None:
        consts = module_int_constants(tree)
        for name in ("DB_DATA", "DB_STARVING"):
            if name in consts:
                model.doorbell[name] = consts[name]

    tree = _parse(root / "starway_tpu" / "errors.py")
    if tree is not None:
        model.reasons = {
            k: v for k, v in module_str_constants(tree).items()
            if k.startswith("REASON_")
        }

    native_path = core / "native.py"
    tree = _parse(native_path)
    if tree is not None:
        model.native_text = read_text(native_path)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            # lib.<fn>.argtypes / lib.<fn>.restype assignments (inside load())
            if isinstance(tgt, ast.Attribute) \
                    and isinstance(tgt.value, ast.Attribute) \
                    and isinstance(tgt.value.value, ast.Name) \
                    and tgt.value.value.id == "lib":
                fn = tgt.value.attr
                if tgt.attr == "argtypes" and isinstance(node.value, ast.List):
                    model.argtypes[fn] = (
                        [canon_ctypes(e) for e in node.value.elts], node.lineno)
                elif tgt.attr == "restype":
                    model.restype[fn] = (canon_ctypes(node.value), node.lineno)
            # _X_CB = ctypes.CFUNCTYPE(None, ...)
            elif isinstance(tgt, ast.Name) and tgt.id.endswith("_CB") \
                    and isinstance(node.value, ast.Call) \
                    and canon_ctypes(node.value.func) == "CFUNCTYPE":
                model.cfunctypes[tgt.id] = (
                    [canon_ctypes(e) for e in node.value.args], node.lineno)

    tree = _parse(core / "engine.py")
    if tree is not None:
        model.engine_strings = code_string_literals(tree)

    tree = _parse(core / "swtrace.py")
    if tree is not None:
        model.trace_events = {
            k: v for k, v in module_str_constants(tree).items()
            if k.startswith("EV_")
        }
        # HIST_BUCKETS = 64 -- the swpulse histogram resolution
        # (contract-pulse pairs it with the kHistBuckets constexpr).
        consts = module_int_constants(tree)
        if "HIST_BUCKETS" in consts:
            model.hist_buckets = consts["HIST_BUCKETS"]
        for node in tree.body:
            # COUNTER_NAMES = ("sends_posted", ...) -- the shared counter
            # vocabulary (contract-trace pairs it with kCounterNames[]);
            # HIST_NAMES / STALL_REASONS are the swpulse twins (DESIGN.md
            # §25; contract-pulse pairs them with kHistNames[] /
            # kStallReasons[]).
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id in ("COUNTER_NAMES", "HIST_NAMES",
                                               "STALL_REASONS") \
                    and isinstance(node.value, (ast.Tuple, ast.List)):
                names = [e.value for e in node.value.elts
                         if isinstance(e, ast.Constant)
                         and isinstance(e.value, str)]
                rec = (names, node.lineno)
                if node.targets[0].id == "COUNTER_NAMES":
                    model.counter_names = rec
                elif node.targets[0].id == "HIST_NAMES":
                    model.hist_names = rec
                else:
                    model.stall_reasons = rec

    tree = _parse(core / "telemetry.py")
    if tree is not None:
        for node in tree.body:
            # GAUGE_NAMES = ("tx_queue_depth", ...) -- the swscope per-conn
            # gauge vocabulary (contract-trace pairs it with kGaugeNames[]).
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == "GAUGE_NAMES" \
                    and isinstance(node.value, (ast.Tuple, ast.List)):
                names = [e.value for e in node.value.elts
                         if isinstance(e, ast.Constant)
                         and isinstance(e.value, str)]
                model.gauge_names = (names, node.lineno)

    return model
