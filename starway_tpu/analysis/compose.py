"""Pass: compose -- bounded model checking of the COMPOSED protocol planes.

analysis/explore.py exhausts the §14 session core in isolation; this
pass exhausts the *product* of the opt-in planes that have grown around
it -- §14 sessions x §17 striped chunks x §18 credit flow control x §19
integrity retransmit -- because every recent review-round bug (a CTS
consumed by a dead incarnation, per-conn unexpected-queue charging, the
resume re-debit of parked frames) lived in exactly the cross-plane seams
a single-plane model cannot see (DESIGN.md §21).

**The model.**  One sender, one receiver, one resilient session.  The
workload is one striped message of two chunks (offsets 0 and 1, unit
sized, SACKed at the last offset) plus one eager data frame governed by
a one-unit §18 window.  Channels are FIFO: a c2s control stream (the
eager frame), an r2s control stream (ACK / CREDIT / SACK / SNACK), and
two rails carrying chunks.  The fault vocabulary, enumerated
exhaustively at every interleaving: a connection kill (suspend + resume
with journal replay and the §18 fresh-window re-debit), a rail death
(in-flight chunks redistribute onto the survivor), one corrupt chunk
(the §19 verified-routing T_SNACK retransmit), and one wire-duplicated
chunk (offset-dedup idempotence).  Faithful rules, straight from
DESIGN.md §§14/17/18/19:

* chunks are idempotent self-describing frames; the receiver records
  each offset once and answers SACK when the last byte lands;
* the sender pins the striped payload until the SACK -- a SNACK
  retransmit, a rail-death redistribution, and a resume re-announce all
  re-read the pinned bytes;
* the eager frame debits the window at submit and the grant returns as
  the receiver matches/drains it; resume resets to the full window and
  re-debits journal-replayed frames;
* a corrupt chunk with verified routing NACKs and retransmits alone --
  its bytes are never recorded;
* session replay re-offers undelivered chunks and the journaled eager
  frame; the receiver's seq/offset dedup keeps delivery exactly-once.

**Invariants** (each backed by a seeded model mutation in
tests/test_swcheck.py that makes it fire):

===================  ==================================================
stripe-exactly-once  a striped message completes exactly once, from
                     exactly the full offset set, across dups, rail
                     deaths, and resume replay (``chunk-no-dedup``)
pin-release          the pinned payload is released only by the SACK;
                     no retransmit / redistribution / replay ever needs
                     bytes that are gone (``early-unpin``: release at
                     local handoff, the pre-§17 eager discipline)
credit-conservation  the §18 window is never overcommitted across
                     incarnations: outstanding debits + remaining
                     credits never exceed the advertised window, and at
                     clean quiescence the window is whole
                     (``resume-no-redebit``: a resume that resets the
                     window without re-debiting replayed frames)
no-wrong-answer      corrupt chunk bytes never complete a receive
                     (``accept-corrupt``: record the chunk anyway)
quiescence           every schedule ends with the ops complete or
                     stably failed -- no silent wedge
                     (``snack-drop``: the sender ignores SNACK and the
                     chunk is never re-queued)
===================  ==================================================

Like explore, the pass refuses to run vacuously: the Python engine's
extracted machine (analysis/protomodel.py) must still contain the
dispatch arms this model abstracts -- (estab, SDATA/SACK/SNACK/CREDIT)
and (suspended, resume); if extraction lost them the model no longer
describes the code and that is a finding, not a pass.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from pathlib import Path
from typing import Optional

from .base import Finding
from . import protomodel

#: §18 window in abstract units; the eager frame debits one.
FC_W = 1

#: Striped-message offsets (unit chunks; SACK at the full set).
OFFS = (0, 1)

#: Per-schedule fault budgets.  One of each is enough for every seam the
#: invariants guard (a replay overlapping a retransmit needs kill after
#: corrupt; redistribution-after-release needs rail death alone) while
#: keeping the product space exhaustible on the 1-core box.
BUDGET_KILLS = 1
BUDGET_RAIL_DEATHS = 1
BUDGET_CORRUPTS = 1
BUDGET_DUPS = 1

#: Seeded model mutations -> the invariant each must trip.
MUTATIONS = {
    "chunk-no-dedup": "stripe-exactly-once",
    "early-unpin": "pin-release",
    "resume-no-redebit": "credit-conservation",
    "accept-corrupt": "no-wrong-answer",
    "snack-drop": "quiescence",
}

INVARIANTS = ("stripe-exactly-once", "pin-release", "credit-conservation",
              "no-wrong-answer", "quiescence")

# chunk states: "todo" (needs a rail), "fly" (riding one), "landed"
# (offset recorded by the receiver), "lost" (corrupt-dropped, awaiting
# the SNACK round trip).


@dataclass(frozen=True)
class _State:
    chunks: tuple = ("todo", "todo")
    pinned: bool = True
    sacked: bool = False
    completed: bool = False
    completions: int = 0
    received: int = 0            # recorded chunk units (dups count under
    got_offs: frozenset = frozenset()  # the no-dedup mutation)
    wrong: bool = False          # a corrupt chunk's bytes were recorded
    e_submitted: bool = False
    journal_e: bool = False      # journaled & unacked
    e_deliv: int = 0
    rx_cum: int = 0              # seq dedup for the eager frame
    credits: int = FC_W
    debits: int = 0              # debited, grant not yet back
    c2s: tuple = ()              # ("e",)
    r2s: tuple = ()              # ("ack",)/("credit",)/("sack",)/("snack", off)
    rail0: tuple = ()            # (off, corrupt)
    rail1: tuple = ()
    rail1_alive: bool = True
    suspended: bool = False
    expired: bool = False
    kills: int = BUDGET_KILLS
    rail_deaths: int = BUDGET_RAIL_DEATHS
    corrupts: int = BUDGET_CORRUPTS
    dups: int = BUDGET_DUPS


def _is_terminal(s: _State) -> bool:
    if s.expired:
        return True
    if s.suspended:
        return False
    return (s.e_submitted and s.e_deliv >= 1 and not s.journal_e
            and s.sacked and all(c == "landed" for c in s.chunks)
            and not s.c2s and not s.r2s and not s.rail0 and not s.rail1)


@dataclass
class _Run:
    mutation: Optional[str] = None
    schedules: int = 0
    states: int = 0
    violations: list = field(default_factory=list)
    _seen_viol: set = field(default_factory=set)

    def violate(self, invariant: str, msg: str, trace: tuple) -> None:
        if invariant not in self._seen_viol:
            self._seen_viol.add(invariant)
            self.violations.append((invariant, msg, trace))


def _check_window(s: _State, run: _Run, trace: tuple) -> None:
    """§18 conservation, checked at every state: the receiver advertised
    FC_W -- outstanding debits plus the sender's remaining credits can
    never exceed it (overcommit = unbounded receiver memory), and no
    counter may go negative."""
    if s.credits + s.debits > FC_W or s.credits < 0 or s.debits < 0:
        run.violate(
            "credit-conservation",
            f"window overcommitted: credits={s.credits} debits={s.debits} "
            f"exceed the advertised window {FC_W} (the receiver's memory "
            "bound no longer holds)", trace)


def _set_chunk(chunks: tuple, off: int, state: str) -> tuple:
    out = list(chunks)
    out[off] = state
    return tuple(out)


def _record_chunk(s: _State, off: int, corrupt: bool, run: _Run,
                  trace: tuple) -> _State:
    """The receiver records one arriving chunk (dedup already decided by
    the caller under the faithful model)."""
    wrong = s.wrong or corrupt
    received = s.received + 1
    got = s.got_offs | {off}
    chunks = _set_chunk(s.chunks, off, "landed")
    completions = s.completions
    completed = s.completed
    r2s = s.r2s
    if run.mutation == "chunk-no-dedup":
        complete_now = received >= len(OFFS)
    else:
        complete_now = got == frozenset(OFFS) and not completed
    if complete_now:
        completions += 1
        if completions > 1:
            run.violate(
                "stripe-exactly-once",
                "striped message completed twice (duplicate offsets "
                "double-counted into the assembly)", trace)
        if len(got) < len(OFFS):
            run.violate(
                "stripe-exactly-once",
                f"striped message completed from offsets {sorted(got)} -- "
                f"not the full set {list(OFFS)} (duplicate counted for a "
                "missing chunk)", trace)
        if wrong:
            run.violate(
                "no-wrong-answer",
                "a corrupt chunk's bytes completed the striped receive "
                "(corruption must be a recoverable fault, never a wrong "
                "answer)", trace)
        completed = True
        r2s = r2s + (("sack",),)
    return replace(s, chunks=chunks, received=received, got_offs=got,
                   wrong=wrong, completions=completions,
                   completed=completed, r2s=r2s)


def _enabled(s: _State) -> list:
    if s.expired:
        return []
    if s.suspended:
        return ["resume", "expire"]
    acts = []
    if not s.e_submitted and s.credits > 0:
        acts.append("submit_e")
    if "todo" in s.chunks:
        acts.append("send0")
        if s.rail1_alive:
            acts.append("send1")
    if s.c2s:
        acts.append("dlv_m")
    if s.r2s:
        acts.append("dlv_r")
    if s.rail0:
        acts.append("dlv_c0")
    if s.rail1:
        acts.append("dlv_c1")
    if s.corrupts > 0:
        if s.rail0 and not s.rail0[0][1]:
            acts.append("corrupt0")
        if s.rail1 and not s.rail1[0][1]:
            acts.append("corrupt1")
    if s.dups > 0:
        if s.rail0:
            acts.append("dup0")
        if s.rail1:
            acts.append("dup1")
    if s.kills > 0:
        acts.append("kill")
    if s.rail_deaths > 0 and s.rail1_alive:
        acts.append("rail_death")
    return acts


def _apply(s: _State, act: str, run: _Run, trace: tuple) -> _State:
    mut = run.mutation
    if act == "submit_e":
        return replace(s, e_submitted=True, journal_e=True,
                       credits=s.credits - 1, debits=s.debits + 1,
                       c2s=s.c2s + (("e",),))
    if act in ("send0", "send1"):
        off = s.chunks.index("todo")
        if not s.pinned:
            run.violate(
                "pin-release",
                f"chunk (re)send at offset {off} after the pinned payload "
                "was released -- only the receiver's SACK may release it "
                "(retransmit/redistribution/replay all re-read the pin)",
                trace + (act,))
        chunks = _set_chunk(s.chunks, off, "fly")
        rail = "rail0" if act == "send0" else "rail1"
        ns = replace(s, chunks=chunks,
                     **{rail: getattr(s, rail) + ((off, False),)})
        if mut == "early-unpin" and "todo" not in ns.chunks:
            # The buggy shape: release at local handoff (every chunk on a
            # rail), not at end-to-end SACK.
            ns = replace(ns, pinned=False)
        return ns
    if act in ("dlv_c0", "dlv_c1"):
        rail = "rail0" if act == "dlv_c0" else "rail1"
        (off, corrupt), rest = getattr(s, rail)[0], getattr(s, rail)[1:]
        s = replace(s, **{rail: rest})
        if corrupt and mut != "accept-corrupt":
            # §19: payload CRC failed, routing verified -> SNACK, and the
            # chunk is NOT recorded.  The sender re-queues it from the
            # pinned payload when the SNACK lands.
            chunks = s.chunks
            if chunks[off] == "fly":
                chunks = _set_chunk(chunks, off, "lost")
            return replace(s, chunks=chunks,
                           r2s=s.r2s + (("snack", off),))
        if off in s.got_offs and mut != "chunk-no-dedup":
            # Duplicate offset (wire dup / replay overlap): idempotent
            # drop.  A completed message re-SACKs so the sender stops
            # (the done-ids path).
            r2s = s.r2s
            if s.completed and not s.sacked:
                r2s = r2s + (("sack",),)
            return replace(s, r2s=r2s)
        return _record_chunk(s, off, corrupt, run, trace + (act,))
    if act == "dlv_m":
        msg, rest = s.c2s[0], s.c2s[1:]
        assert msg[0] == "e"
        if s.rx_cum >= 1:
            # Seq dedup: drained, not delivered -- but the (re-)debited
            # window still returns (§18).
            return replace(s, c2s=rest, r2s=s.r2s + (("credit",),))
        return replace(s, c2s=rest, rx_cum=1, e_deliv=s.e_deliv + 1,
                       r2s=s.r2s + (("credit",), ("ack",)))
    if act == "dlv_r":
        msg, rest = s.r2s[0], s.r2s[1:]
        if msg[0] == "credit":
            ns = replace(s, r2s=rest, credits=s.credits + 1,
                         debits=s.debits - 1)
            _check_window(ns, run, trace + (act,))
            return ns
        if msg[0] == "ack":
            return replace(s, r2s=rest, journal_e=False)
        if msg[0] == "sack":
            return replace(s, r2s=rest, sacked=True, pinned=False)
        # snack: re-queue exactly that chunk from the pinned payload.
        off = msg[1]
        if mut == "snack-drop":
            return replace(s, r2s=rest)
        chunks = s.chunks
        if chunks[off] == "lost":
            chunks = _set_chunk(chunks, off, "todo")
        return replace(s, r2s=rest, chunks=chunks)
    if act in ("corrupt0", "corrupt1"):
        rail = "rail0" if act == "corrupt0" else "rail1"
        q = getattr(s, rail)
        return replace(s, corrupts=s.corrupts - 1,
                       **{rail: ((q[0][0], True),) + q[1:]})
    if act in ("dup0", "dup1"):
        rail = "rail0" if act == "dup0" else "rail1"
        q = getattr(s, rail)
        return replace(s, dups=s.dups - 1, **{rail: (q[0],) + q})
    if act == "rail_death":
        # The secondary transport died: its in-flight chunks are gone and
        # redistribute onto the survivor (which re-reads the pin).
        chunks = s.chunks
        for off, _corrupt in s.rail1:
            if chunks[off] == "fly":
                chunks = _set_chunk(chunks, off, "todo")
        return replace(s, rail_deaths=s.rail_deaths - 1, rail1_alive=False,
                       rail1=(), chunks=chunks)
    if act == "kill":
        # Conn death: every wire wiped, session suspended.  In-flight
        # chunks will be re-announced by the resume replay.
        chunks = tuple("todo" if c == "fly" else c for c in s.chunks)
        return replace(s, kills=s.kills - 1, suspended=True, chunks=chunks,
                       c2s=(), r2s=(), rail0=(), rail1=())
    if act == "resume":
        # §14 replay + §17 per-message re-announce + §18 fresh window.
        # The resume handshake carries the receiver's cumulative seq
        # (sess_ack): an eager frame the receiver already processed is
        # trimmed from the journal HERE, never replayed -- losing its
        # in-flight ACK with the conn costs nothing.
        journal_e = s.journal_e and s.rx_cum < 1
        chunks = tuple("todo" if c in ("fly", "lost") else c
                       for c in s.chunks) if not s.sacked else s.chunks
        c2s = (("e",),) if journal_e else ()
        replay_debit = 1 if journal_e else 0
        if mut == "resume-no-redebit":
            # The buggy shape: full window, replayed frames not debited.
            credits, debits = FC_W, s.debits
        else:
            credits, debits = FC_W - replay_debit, replay_debit
        r2s = ()
        if s.completed and not s.sacked:
            # The sender's re-announce meets the receiver's done-ids
            # dedup and draws a fresh SACK (modeled as the direct
            # re-offer).
            r2s = (("sack",),)
        ns = replace(s, suspended=False, journal_e=journal_e, chunks=chunks,
                     c2s=c2s, r2s=r2s, credits=credits, debits=debits)
        _check_window(ns, run, trace + (act,))
        return ns
    if act == "expire":
        # Grace expiry: terminal; every pending op fails with the stable
        # reason and the pin is released with the failed sends.
        return replace(s, expired=True, suspended=False, pinned=False,
                       c2s=(), r2s=(), rail0=(), rail1=())
    raise AssertionError(f"unknown action {act}")


def check(mutation: Optional[str] = None, max_states: int = 400_000) -> dict:
    """Exhaust the composed model under ``mutation`` (None = faithful).
    Returns ``{"schedules", "states", "violations"}`` -- schedules is the
    number of distinct complete root-to-terminal action sequences,
    counted by DP over the memoized state graph (explore.check's
    convention)."""
    if mutation is not None and mutation not in MUTATIONS:
        raise ValueError(f"unknown mutation {mutation!r} "
                         f"(choose from {sorted(MUTATIONS)})")
    run = _Run(mutation=mutation)
    paths: dict = {}

    def visit(s: _State, trace: tuple, depth: int) -> int:
        if s in paths:
            return paths[s]
        if depth > 400 or len(paths) > max_states:
            run.violate("quiescence",
                        "state space exploded past the model bound "
                        "(runaway retransmit/replay loop)", trace)
            paths[s] = 0
            return 0
        if _is_terminal(s):
            paths[s] = 1
            return 1
        acts = _enabled(s)
        if not acts:
            pending = [f"chunk{off}={st}" for off, st in enumerate(s.chunks)
                       if st != "landed"]
            run.violate(
                "quiescence",
                "deadlock: ops pending but no action enabled "
                f"({', '.join(pending) or 'control plane wedged'}, "
                f"sacked={s.sacked})", trace)
            paths[s] = 0
            return 0
        paths[s] = 0  # cycle guard
        total = 0
        for act in acts:
            total += visit(_apply(s, act, run, trace), trace + (act,),
                           depth + 1)
        paths[s] = total
        return total

    schedules = visit(_State(), (), 0)
    for s in list(paths):
        if _is_terminal(s) and not s.expired:
            if s.completions != 1:
                run.violate(
                    "stripe-exactly-once",
                    f"clean quiescence with completions={s.completions} "
                    "(want exactly 1)", ())
            if s.credits != FC_W or s.debits != 0:
                run.violate(
                    "credit-conservation",
                    f"clean quiescence with credits={s.credits} "
                    f"debits={s.debits} -- the §18 window ({FC_W}) was "
                    "permanently lost across the schedule", ())
            if s.pinned:
                run.violate(
                    "pin-release",
                    "clean quiescence with the payload still pinned after "
                    "its SACK -- the release leaked", ())
    return {"schedules": schedules, "states": len(paths),
            "violations": run.violations}


#: Dispatch arms the composed model abstracts; their disappearance from
#: the extracted machine means the model no longer describes the code.
_REQUIRED_TRANSITIONS = (
    ("estab", "SDATA"), ("estab", "SACK"), ("estab", "SNACK"),
    ("estab", "CREDIT"), ("suspended", "resume"),
)


#: The faithful model is pure (no tree input): memoized so the many
#: seeded-tree invocations in tests/test_swcheck.py pay the exploration
#: once, not per run_all call.  Mutated runs are never cached.
_FAITHFUL: Optional[dict] = None


def run(root: Path) -> list:
    global _FAITHFUL
    out: list = []
    machine, extract_findings = protomodel.extract_py_machine(root)
    missing = [key for key in _REQUIRED_TRANSITIONS
               if key not in machine.transitions]
    if missing and not extract_findings:
        out.append(Finding(
            "starway_tpu/core/lane.py", 1, "proto-compose",
            f"the composed model's transitions {missing} are no longer "
            "extracted from the engine -- the product model would verify "
            "planes the code does not implement (update the model or the "
            "extraction grammar, DESIGN.md §21)"))
        return out
    if _FAITHFUL is None:
        _FAITHFUL = check(None)
    result = _FAITHFUL
    for invariant, msg, trace in result["violations"]:
        out.append(Finding(
            "starway_tpu/core/lane.py", 1, "proto-compose",
            f"composed-plane invariant `{invariant}` violated: {msg} "
            f"[schedule: {' -> '.join(trace) or '<initial>'}]"))
    if result["schedules"] < 2000:
        out.append(Finding(
            "starway_tpu/core/lane.py", 1, "proto-compose",
            f"only {result['schedules']} composed fault schedules "
            "enumerated -- the bounded exploration lost coverage (model "
            "bounds shrunk?)"))
    return out
