"""Pass: wirefuzz -- a contract-derived differential fuzzer for the wire
decoders (DESIGN.md §21).

tests/test_fuzz_differential.py fuzzes the *matcher* with well-formed
traffic; nothing fuzzed the frame *decoders* with adversarial bytes --
exactly where the zero-length-ctl-body divergence lived (silent drop in
the C++ engine, conn-death-or-stall in the Python one).  This pass
closes that gap with three redundant implementations of the structural
decode contract, diffed byte-for-byte on identical inputs:

1. an **oracle** decoder implemented HERE, driven entirely by tables
   extracted (ast/regex, never imported) from the contract surface --
   frame-type constants, the 17-byte header layout, the stripe
   sub-header, the §19 checksum scope sets, the ctl-body bound, and the
   sm slot-record framing;
2. the Python engine's reference decoder, ``frames.decode_stream`` /
   ``shmring.decode_sm_records``, loaded FROM THE TREE UNDER CHECK (a
   throwaway package, so mutated copies are honoured);
3. the native engine's ``sw_wire_decode`` export, when the tree's built
   artifact is present (skipped quietly in a bare venv -- the repo's CI
   gate and test suite always have it).

All three render the same canonical outcome string (status, consumed
bytes, frame list); any disagreement is a ``wire-diff`` finding.  Inputs
come from two sources, both deterministic:

* the **regression corpus** (``wirefuzz_corpus.txt`` next to this file):
  every previously-divergent or edge-pinning case, replayed by every
  gate run -- the corpus going missing or shrinking below its floor is
  itself a finding, never a silent skip;
* a **seeded generator** that builds structurally valid frame scripts
  from the extracted grammar and then mutates fields, lengths, types,
  and truncation points.  The merge gate runs a bounded quick mode
  (``QUICK_SEEDS`` per mode, ~0.2 s); the nightly CI job sets
  ``SWCHECK_WIREFUZZ_SEEDS`` for the long run and appends any new
  divergent case to the corpus.

A **static leg** runs even without any dynamic target: the §19/§21
decode tables themselves are diffed between the engines
(``frames.CSUM_EXEMPT/CSUM_BODY/HEADER_ONLY/CTL_MAX`` vs the native
``kCsumExempt[]/kCsumBody[]/kHeaderOnly[]/CTL_MAX``), and conn.py must
still *alias* the shared tables (a live parser growing its own private
set is the drift this pass exists to prevent).
"""

from __future__ import annotations

import ast
import ctypes
import importlib.util
import os
import re
import struct
import sys
import types
import uuid
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional

from .base import Finding, parse_or_finding
from .cpp_model import extract_cpp

#: Seeds per mode in the merge-gate quick run (SWCHECK_WIREFUZZ_SEEDS
#: overrides for the nightly long run).
QUICK_SEEDS = 50

#: Regression-corpus floor: the gate replays >= this many checked-in
#: cases or the corpus itself became the regression.
CORPUS_FLOOR = 100

#: Findings cap per run: a systemic divergence (e.g. a reshaped decoder)
#: would otherwise bury the signal under thousands of identical diffs.
MAX_DIVERGENCES = 8

MODES = ("stream", "csum", "smrec")
_MODE_NUM = {"stream": 0, "csum": 1, "smrec": 2}

#: The decode-table names shared (by value) between the engines.
_TABLE_PAIRS = (("CSUM_EXEMPT", "kCsumExempt"), ("CSUM_BODY", "kCsumBody"),
                ("HEADER_ONLY", "kHeaderOnly"))

_CPP_ARRAY_RE = r"constexpr\s+uint8_t\s+{name}\s*\[\s*\]\s*=\s*\{{([^}}]*)\}}"


# ------------------------------------------------------------- tables


@dataclass
class Tables:
    """The decode grammar, as extracted from frames.py (the oracle's and
    the generator's single source of truth)."""
    t: dict = field(default_factory=dict)      # T_* name -> value
    exempt: set = field(default_factory=set)   # values
    body: set = field(default_factory=set)
    header_only: set = field(default_factory=set)
    ctl_max: int = 0
    header: struct.Struct = struct.Struct("<BQQ")
    sub: struct.Struct = struct.Struct("<QQQ")
    rec_ring: int = 1 << 20                    # shmring.DEFAULT_RING
    decode_line: int = 1                       # frames.decode_stream anchor
    rec_line: int = 1                          # shmring decoder anchor


def _py_set_members(tree: ast.Module, name: str) -> Optional[tuple]:
    """``NAME = frozenset((T_A, T_B, ...))`` -> (set of T_ names, line)."""
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == name \
                and isinstance(node.value, ast.Call) \
                and isinstance(node.value.func, ast.Name) \
                and node.value.func.id == "frozenset" \
                and node.value.args \
                and isinstance(node.value.args[0], (ast.Tuple, ast.List)):
            names = set()
            for elt in node.value.args[0].elts:
                if isinstance(elt, ast.Name):
                    names.add(elt.id)
                elif isinstance(elt, ast.Attribute):
                    names.add(elt.attr)
            return names, node.lineno
    return None


def _extract_tables(root: Path, out: list) -> Optional[tuple]:
    """Extract the shared decode tables from BOTH engines and diff them.
    Returns (Tables, py_sets) or None when extraction lost the surface
    (vacuity findings appended either way)."""
    f_frames = "starway_tpu/core/frames.py"
    tree, err = parse_or_finding(root / f_frames, f_frames)
    if tree is None:
        out.append(err)
        return None
    consts: dict = {}
    env: dict = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            try:
                val = ast.literal_eval(node.value)
            except (ValueError, TypeError, SyntaxError):
                # CTL_MAX-style shift expressions don't literal_eval.
                val = _fold_int(node.value, env)
            if isinstance(val, int) and not isinstance(val, bool):
                consts[name] = (val, node.lineno)
                env[name] = val
    tbl = Tables()
    tbl.t = {k: v[0] for k, v in consts.items() if k.startswith("T_")
             and k != "T_"}
    # The wire layouts come from the contract surface too (the contract
    # pass already pins them against HEADER_SIZE/SDATA_SUB_SIZE).
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id in ("HEADER", "SDATA_SUB") \
                and isinstance(node.value, ast.Call) and node.value.args \
                and isinstance(node.value.args[0], ast.Constant) \
                and isinstance(node.value.args[0].value, str):
            try:
                s = struct.Struct(node.value.args[0].value)
            except struct.error:
                continue
            if node.targets[0].id == "HEADER":
                tbl.header = s
            else:
                tbl.sub = s
    py_sets: dict = {}
    for name in ("CSUM_EXEMPT", "CSUM_BODY", "HEADER_ONLY"):
        got = _py_set_members(tree, name)
        if got is None:
            out.append(Finding(
                f_frames, 1, "wire-diff",
                f"decode table {name} not found in frames.py -- the shared "
                "decode contract lost its Python side (wirefuzz would be "
                "vacuous)"))
        else:
            py_sets[name] = got
    if "CTL_MAX" not in consts:
        out.append(Finding(f_frames, 1, "wire-diff",
                           "CTL_MAX bound not found in frames.py"))
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == "decode_stream":
            tbl.decode_line = node.lineno
            break
    else:
        out.append(Finding(
            f_frames, 1, "wire-diff",
            "frames.decode_stream (the Python engine's reference decoder) "
            "not found -- differential fuzzing would be vacuous"))
    f_shm = "starway_tpu/core/shmring.py"
    shm_tree, shm_err = parse_or_finding(root / f_shm, f_shm)
    if shm_tree is None:
        out.append(shm_err)
    else:
        for node in ast.walk(shm_tree):
            if isinstance(node, ast.FunctionDef) \
                    and node.name == "decode_sm_records":
                tbl.rec_line = node.lineno
                break
        else:
            out.append(Finding(
                f_shm, 1, "wire-diff",
                "shmring.decode_sm_records (the slot-record reference "
                "decoder) not found -- the smrec mode would be vacuous"))
        # The record-length bound the smrec decoders share: the oracle
        # follows the tree's DEFAULT_RING; the native harness hardcodes
        # its twin, so pin it statically (the CTL_MAX precedent) --
        # corpus boundary cases make a drift fire dynamically too.
        ring = None
        ring_line = 1
        for node in shm_tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == "DEFAULT_RING":
                ring = _fold_int(node.value, {})
                ring_line = node.lineno
                break
        if ring is None:
            out.append(Finding(
                f_shm, 1, "wire-diff",
                "shmring.DEFAULT_RING not found -- the smrec record "
                "bound lost its Python side (oracle would guess)"))
        else:
            tbl.rec_ring = ring
    if not tbl.t or len(py_sets) < 3 or "CTL_MAX" not in consts:
        return None
    tbl.exempt = {tbl.t[n] for n in py_sets["CSUM_EXEMPT"][0] if n in tbl.t}
    tbl.body = {tbl.t[n] for n in py_sets["CSUM_BODY"][0] if n in tbl.t}
    tbl.header_only = {tbl.t[n] for n in py_sets["HEADER_ONLY"][0]
                       if n in tbl.t}
    tbl.ctl_max = consts["CTL_MAX"][0]

    # --- cross-engine table diff (the static leg)
    cpp = extract_cpp(root)
    for py_name, cpp_name in _TABLE_PAIRS:
        if py_name not in py_sets:
            continue
        m = re.search(_CPP_ARRAY_RE.format(name=cpp_name), cpp.cpp_code)
        if m is None:
            out.append(Finding(
                cpp.cpp_file, 1, "wire-diff",
                f"{cpp_name}[] decode table not found in the native engine "
                f"(the frames.py {py_name} twin)"))
            continue
        cpp_names = set(re.findall(r"T_\w+", m.group(1)))
        names, line = py_sets[py_name]
        if cpp_names != names:
            only_py = sorted(names - cpp_names)
            only_cpp = sorted(cpp_names - names)
            out.append(Finding(
                f_frames, line, "wire-diff",
                f"decode table {py_name} disagrees with {cpp_name}[] "
                f"({cpp.cpp_file}): only-Python {only_py}, only-C++ "
                f"{only_cpp} (two engines, one decode contract)"))
    if "CTL_MAX" in cpp.constants:
        cval, cline = cpp.constants["CTL_MAX"]
        if cval != tbl.ctl_max:
            out.append(Finding(
                f_frames, consts["CTL_MAX"][1], "wire-diff",
                f"CTL_MAX = {tbl.ctl_max} but {cpp.cpp_file}:{cline} has "
                f"CTL_MAX = {cval} (the engines disagree on the ctl-body "
                "bound)"))
    elif cpp.constants:
        out.append(Finding(cpp.cpp_file, 1, "wire-diff",
                           "CTL_MAX constexpr not found in the native "
                           "engine (the frames.py CTL_MAX twin)"))
    m = re.search(r"ring_size\s*=\s*1ull\s*<<\s*(\d+)", cpp.cpp_code)
    if m is None:
        out.append(Finding(
            cpp.cpp_file, 1, "wire-diff",
            "wire_decode_recs ring_size bound not found in the native "
            "harness (the shmring.DEFAULT_RING twin)"))
    elif (1 << int(m.group(1))) != tbl.rec_ring:
        out.append(Finding(
            f_shm, ring_line, "wire-diff",
            f"shmring.DEFAULT_RING = {tbl.rec_ring} but the native "
            f"harness bounds sm records at 1<<{m.group(1)} "
            f"({cpp.cpp_file}) -- the smrec decoders disagree on the "
            "record-length bound"))

    # --- the live parser must still ALIAS the shared tables
    f_conn = "starway_tpu/core/conn.py"
    conn_tree, conn_err = parse_or_finding(root / f_conn, f_conn)
    if conn_tree is None:
        out.append(conn_err)
    else:
        for local, shared in (("_CSUM_EXEMPT", "CSUM_EXEMPT"),
                              ("_CSUM_BODY", "CSUM_BODY")):
            for node in conn_tree.body:
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and node.targets[0].id == local:
                    v = node.value
                    ok = (isinstance(v, ast.Attribute) and v.attr == shared
                          and isinstance(v.value, ast.Name)
                          and v.value.id == "frames")
                    if not ok:
                        out.append(Finding(
                            f_conn, node.lineno, "wire-diff",
                            f"{local} no longer aliases frames.{shared}: the "
                            "live parser grew a private decode table the "
                            "fuzzer (and the native twin) cannot see"))
                    break
            else:
                out.append(Finding(
                    f_conn, 1, "wire-diff",
                    f"{local} not found in conn.py -- cannot prove the live "
                    "parser shares the decode tables"))
    return tbl, py_sets


def _fold_int(node: ast.AST, env: dict) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.Name):
        v = env.get(node.id)
        return v if isinstance(v, int) else None
    if isinstance(node, ast.BinOp):
        lo, hi = _fold_int(node.left, env), _fold_int(node.right, env)
        if lo is None or hi is None:
            return None
        if isinstance(node.op, ast.LShift) and hi < 128:
            return lo << hi
        if isinstance(node.op, ast.Add):
            return lo + hi
        if isinstance(node.op, ast.Sub):
            return lo - hi
        if isinstance(node.op, ast.Mult):
            return lo * hi
    return None


# ------------------------------------------------------------- oracle
#
# An independent CRC32C and decoder: table-driven off the extracted
# grammar, sharing no code with core/frames.py.  Divergence between this
# and either engine decoder is the pass's whole point, so resist the
# urge to "reuse".

_CRC_TBL: Optional[list] = None


def _crc(data: bytes, crc: int = 0) -> int:
    global _CRC_TBL
    if _CRC_TBL is None:
        tbl = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
            tbl.append(c)
        _CRC_TBL = tbl
    c = (crc & 0xFFFFFFFF) ^ 0xFFFFFFFF
    for b in data:
        c = _CRC_TBL[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


def _fmt(status: str, consumed: int, entries: list) -> str:
    shown = entries[:64]
    extra = len(entries) - len(shown)
    if extra > 0:
        shown.append(f"+{extra}")
    return f"{status} n={consumed} [" + " ".join(shown) + "]"


def oracle_stream(tbl: Tables, data: bytes, csum: bool) -> str:
    t = tbl.t
    hsz, ssz = tbl.header.size, tbl.sub.size
    n = len(data)
    pos = consumed = 0
    entries: list = []
    pend: Optional[tuple] = None
    accum = 0
    ctl = {t["T_HELLO"], t["T_HELLO_ACK"], t["T_DEVPULL"], t["T_RTS"]}
    while True:
        if n - pos < hsz:
            return _fmt("ok" if pos == n else "short:header",
                        consumed, entries)
        ftype, a, b = tbl.header.unpack_from(data, pos)
        if pend is not None:
            accum = _crc(data[pos:pos + hsz], accum)
        pos += hsz
        if csum:
            if ftype == t["T_CSUM"]:
                if pend is not None:
                    return _fmt("reject(nested checksum prefix)",
                                consumed, entries)
                pend = (a & 0xFFFFFFFF, b & 0xFFFFFFFF)
                accum = 0
                entries.append(f"{ftype}:{a}:{b}")
                consumed = pos
                continue
            if ftype not in tbl.exempt:
                if pend is None:
                    return _fmt("reject(frame without checksum)",
                                consumed, entries)
                if ftype != t["T_SDATA"] and accum != pend[1]:
                    return _fmt("reject(frame header checksum)",
                                consumed, entries)
                if not (ftype == t["T_SDATA"]
                        or (ftype in tbl.body and b > 0)):
                    cf, pend = pend[0], None
                    if accum != cf:
                        return _fmt("reject(frame checksum)",
                                    consumed, entries)
        if ftype == t["T_SDATA"]:
            if b <= ssz:
                return _fmt("reject(sdata sub-header)", consumed, entries)
            if n - pos < ssz:
                return _fmt("short:sub", consumed, entries)
            if pend is not None:
                accum = _crc(data[pos:pos + ssz], accum)
                if accum != pend[1]:
                    return _fmt("reject(stripe sub-header checksum)",
                                consumed, entries)
            mid, off, tot = tbl.sub.unpack_from(data, pos)
            pos += ssz
            clen = b - ssz
            if clen > n - pos:
                return _fmt("short:body", consumed, entries)
            if pend is not None:
                accum = _crc(data[pos:pos + clen], accum)
                cf, pend = pend[0], None
                if accum != cf:
                    pos += clen
                    entries.append(f"snack:{mid}:{off}")
                    consumed = pos
                    continue
            pos += clen
            entries.append(f"{ftype}:{a}:{b}:{mid}:{off}:{tot}")
            consumed = pos
            continue
        if ftype == t["T_DATA"]:
            if b:
                if b > n - pos:
                    return _fmt("short:body", consumed, entries)
                if pend is not None:
                    accum = _crc(data[pos:pos + b], accum)
                    cf, pend = pend[0], None
                    if accum != cf:
                        return _fmt("reject(payload checksum (DATA))",
                                    consumed, entries)
                pos += b
            entries.append(f"{ftype}:{a}:{b}")
            consumed = pos
            continue
        if ftype in ctl:
            if b == 0:
                return _fmt("reject(zero control body)", consumed, entries)
            if b > tbl.ctl_max:
                return _fmt("reject(oversized control body)",
                            consumed, entries)
            if b > n - pos:
                return _fmt("short:body", consumed, entries)
            if pend is not None:
                accum = _crc(data[pos:pos + b], accum)
                cf, pend = pend[0], None
                if accum != cf:
                    return _fmt("reject(control body checksum)",
                                consumed, entries)
            pos += b
            entries.append(f"{ftype}:{a}:{b}")
            consumed = pos
            continue
        if ftype in tbl.header_only:
            entries.append(f"{ftype}:{a}:{b}")
            consumed = pos
            continue
        return _fmt("reject(unknown frame type)", consumed, entries)


_REC = struct.Struct("<II")  # shmring slot record: u32 len, u32 crc
_SEQ8 = struct.Struct("<Q")


def oracle_recs(tbl: Tables, data: bytes) -> str:
    n = len(data)
    pos = consumed = seq = 0
    entries: list = []
    while True:
        if n - pos == 0:
            return _fmt("ok", consumed, entries)
        if n - pos < _REC.size:
            return _fmt("short:rec-header", consumed, entries)
        ln, crc = _REC.unpack_from(data, pos)
        if ln == 0 or ln > tbl.rec_ring:
            return _fmt("reject(sm record header)", consumed, entries)
        if pos + _REC.size + ln > n:
            return _fmt("short:rec-body", consumed, entries)
        accum = _crc(data[pos + _REC.size:pos + _REC.size + ln],
                     _crc(_SEQ8.pack(seq)))
        if accum != crc:
            return _fmt("reject(sm record checksum)", consumed, entries)
        seq += 1
        pos += _REC.size + ln
        consumed = pos
        entries.append(f"r:{ln}")


# ----------------------------------------------------- dynamic targets


def _load_target_modules(root: Path):
    """Load the tree-under-check's frames.py + shmring.py as a throwaway
    package (mutated copies honoured; never the installed starway_tpu).
    Returns (frames_mod, shmring_mod, cleanup_names)."""
    pkgname = "_swfuzz_" + uuid.uuid4().hex
    core = root / "starway_tpu" / "core"
    pkg = types.ModuleType(pkgname)
    pkg.__path__ = [str(core)]
    sys.modules[pkgname] = pkg
    names = [pkgname]
    mods = []
    for sub in ("frames", "shmring"):
        full = f"{pkgname}.{sub}"
        spec = importlib.util.spec_from_file_location(full, core / f"{sub}.py")
        mod = importlib.util.module_from_spec(spec)
        sys.modules[full] = mod
        names.append(full)
        spec.loader.exec_module(mod)
        mods.append(mod)
    return mods[0], mods[1], names


_NATIVE_CACHE: dict = {}


def _load_native(root: Path):
    """The tree's built engine artifact with the sw_wire_decode export,
    or None (fresh checkout / bare venv / pre-§21 build)."""
    so = root / "starway_tpu" / "_sw_native.so"
    key = str(so)
    if key in _NATIVE_CACHE:
        return _NATIVE_CACHE[key]
    lib = None
    if so.is_file():
        try:
            cand = ctypes.CDLL(str(so))
            if hasattr(cand, "sw_wire_decode"):
                cand.sw_wire_decode.argtypes = [
                    ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int,
                    ctypes.c_char_p, ctypes.c_int,
                ]
                lib = cand
        except OSError:
            lib = None
    _NATIVE_CACHE[key] = lib
    return lib


def _native_decode(lib, data: bytes, mode: str) -> str:
    out = ctypes.create_string_buffer(1 << 16)
    lib.sw_wire_decode(data, len(data), _MODE_NUM[mode], out, len(out))
    return out.value.decode("utf-8", "replace")


# ---------------------------------------------------------- generator


def _gen_frame(rng, tbl: Tables, csum: bool) -> bytes:
    """One structurally valid frame (with its T_CSUM prefix when the
    mode demands one)."""
    t = tbl.t
    kind = rng.randrange(6)
    if kind == 0:  # DATA
        body = bytes(rng.randrange(256) for _ in range(rng.randrange(0, 24)))
        frame = tbl.header.pack(t["T_DATA"], rng.randrange(1 << 16),
                                len(body))
        payload = body
    elif kind == 1:  # striped chunk
        clen = rng.randrange(1, 24)
        mid, off, tot = rng.randrange(1, 8), rng.randrange(0, 64), 64
        frame = (tbl.header.pack(t["T_SDATA"], rng.randrange(1 << 16),
                                 tbl.sub.size + clen)
                 + tbl.sub.pack(mid, off, tot))
        payload = bytes(rng.randrange(256) for _ in range(clen))
    elif kind == 2:  # ctl (JSON-ish body)
        ftype = rng.choice((t["T_HELLO"], t["T_HELLO_ACK"], t["T_DEVPULL"],
                            t["T_RTS"]))
        body = b'{"k":"' + bytes(0x61 + rng.randrange(26)
                                 for _ in range(rng.randrange(1, 12))) + b'"}'
        frame = tbl.header.pack(ftype, rng.randrange(1 << 8), len(body))
        payload = body
    else:  # header-only ctl plane
        ftype = rng.choice(sorted(tbl.header_only))
        frame = tbl.header.pack(ftype, rng.randrange(1 << 8),
                                rng.randrange(1 << 4))
        payload = b""
    if csum and frame[0] not in tbl.exempt:
        head_len = tbl.header.size
        if frame[0] == tbl.t["T_SDATA"]:
            head_len += tbl.sub.size
        ch = _crc(frame[:head_len])
        cf = _crc(frame[head_len:] + payload, ch)
        return tbl.header.pack(t["T_CSUM"], cf, ch) + frame + payload
    return frame + payload


def _gen_record(rng, seq: int) -> bytes:
    body = bytes(rng.randrange(256) for _ in range(rng.randrange(1, 24)))
    crc = _crc(body, _crc(_SEQ8.pack(seq)))
    return _REC.pack(len(body), crc) + body


def gen_case(tbl: Tables, mode: str, seed: int) -> bytes:
    """Deterministic adversarial input for ``seed``: a valid script of
    frames/records, then zero or more structure-aware mutations."""
    import random

    rng = random.Random((seed << 2) | _MODE_NUM[mode])
    if mode == "smrec":
        buf = bytearray(b"".join(_gen_record(rng, i)
                                 for i in range(rng.randrange(1, 4))))
    else:
        buf = bytearray(b"".join(_gen_frame(rng, tbl, mode == "csum")
                                 for _ in range(rng.randrange(1, 4))))
    for _ in range(rng.randrange(0, 3)):
        op = rng.randrange(6)
        if not buf:
            break
        if op == 0:    # flip one byte
            buf[rng.randrange(len(buf))] ^= 1 << rng.randrange(8)
        elif op == 1:  # truncate
            del buf[rng.randrange(len(buf)):]
        elif op == 2:  # rewrite a length field (header offset 9..16)
            if len(buf) >= tbl.header.size:
                b = rng.choice((0, 1, tbl.sub.size, tbl.sub.size + 1,
                                tbl.ctl_max, tbl.ctl_max + 1,
                                (1 << 63) - 1, (1 << 64) - 1))
                struct.pack_into("<Q", buf, 9, b)
        elif op == 3:  # rewrite a type byte at a frame-ish offset
            buf[0] = rng.randrange(256)
        elif op == 4:  # duplicate a slice
            i = rng.randrange(len(buf))
            j = rng.randrange(i, min(len(buf), i + 40) + 1)
            buf[i:i] = buf[i:j]
        else:          # zero a span
            i = rng.randrange(len(buf))
            j = rng.randrange(i, min(len(buf), i + 16) + 1)
            buf[i:j] = bytes(j - i)
    return bytes(buf[:4096])


# ------------------------------------------------------------- corpus


def corpus_path(root: Optional[Path] = None) -> Path:
    """The tree-under-check's corpus when it carries one (so seeded
    mutations in tests/test_swcheck.py are honoured), else this
    package's checked-in copy."""
    if root is not None:
        cand = root / "starway_tpu" / "analysis" / "wirefuzz_corpus.txt"
        if cand.is_file():
            return cand
    return Path(__file__).resolve().parent / "wirefuzz_corpus.txt"


def load_corpus(out: list, root: Optional[Path] = None) -> list:
    """[(label, mode, seed_or_bytes)] from the checked-in corpus file
    (``hex`` pins exact bytes, ``-`` meaning zero of them; ``seed`` pins
    generator cases).  Format errors and a shrunken corpus are findings,
    not skips."""
    path = corpus_path(root)
    rel = "starway_tpu/analysis/wirefuzz_corpus.txt"
    cases: list = []
    if not path.is_file():
        out.append(Finding(rel, 1, "wire-diff",
                           "regression corpus missing -- the gate would "
                           "replay nothing"))
        return cases
    for i, line in enumerate(path.read_text().splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.split(None, 2)
        if len(parts) < 3 or parts[0] not in ("seed", "hex") \
                or parts[1] not in MODES:
            out.append(Finding(rel, i, "wire-diff",
                               f"malformed corpus line: {line[:60]!r}"))
            continue
        kind, mode, rest = parts
        rest = rest.split()[0]
        if kind == "seed":
            try:
                cases.append((f"corpus:{i}", mode, int(rest)))
            except ValueError:
                out.append(Finding(rel, i, "wire-diff",
                                   f"malformed corpus seed: {rest!r}"))
        else:
            try:
                cases.append((f"corpus:{i}", mode,
                              b"" if rest == "-" else bytes.fromhex(rest)))
            except ValueError:
                out.append(Finding(rel, i, "wire-diff",
                                   f"malformed corpus hex: {rest[:40]!r}"))
    if len(cases) < CORPUS_FLOOR:
        out.append(Finding(
            rel, 1, "wire-diff",
            f"regression corpus holds {len(cases)} cases -- below the "
            f"{CORPUS_FLOOR}-case floor (corpus truncated?)"))
    return cases


# ---------------------------------------------------------------- run


def _outcome(fn, *args) -> str:
    """A decoder RAISING on adversarial bytes is itself an outcome (and
    a divergence when the others reject cleanly) -- render it instead of
    letting the exception kill the whole pass."""
    try:
        return fn(*args)
    except Exception as e:
        return f"crash({type(e).__name__})"


def _diff_case(tbl: Tables, frames_mod, shm_mod, lib, label: str,
               mode: str, data: bytes, out: list, counts: dict) -> None:
    if mode == "smrec":
        want = _outcome(oracle_recs, tbl, data)
        got_py = _outcome(shm_mod.decode_sm_records, data)
        anchor = ("starway_tpu/core/shmring.py", tbl.rec_line)
    else:
        want = _outcome(oracle_stream, tbl, data, mode == "csum")
        got_py = _outcome(
            lambda: frames_mod.decode_stream(data, csum=(mode == "csum")))
        anchor = ("starway_tpu/core/frames.py", tbl.decode_line)
    hexs = data.hex()
    if len(hexs) > 96:
        hexs = hexs[:96] + f"..({len(data)}B)"
    if got_py != want:
        counts["divergences"] += 1
        out.append(Finding(
            anchor[0], anchor[1], "wire-diff",
            f"[{label} mode={mode}] Python decoder diverges from the "
            f"grammar oracle on {hexs}: oracle {want!r} != python "
            f"{got_py!r} (replay: analysis/wirefuzz.py)"))
        return  # don't double-report the same bytes against native
    if lib is not None:
        got_nat = _native_decode(lib, data, mode)
        if got_nat != want:
            counts["divergences"] += 1
            out.append(Finding(
                "native/sw_engine.cpp", 1, "wire-diff",
                f"[{label} mode={mode}] native sw_wire_decode diverges on "
                f"{hexs}: oracle {want!r} != native {got_nat!r} "
                "(replay: analysis/wirefuzz.py; rebuild the engine if the "
                "artifact is stale)"))


def fuzz(root: Path, tbl: Tables, out: list,
         seeds_per_mode: Optional[int] = None) -> dict:
    """Replay the corpus, then run ``seeds_per_mode`` fresh seeds per
    mode, diffing oracle vs Python vs native on every case.  Returns
    ``{"cases", "divergences", "native"}``."""
    if seeds_per_mode is None:
        try:
            seeds_per_mode = int(os.environ.get("SWCHECK_WIREFUZZ_SEEDS",
                                                QUICK_SEEDS))
        except ValueError:
            seeds_per_mode = QUICK_SEEDS
    counts = {"cases": 0, "divergences": 0, "native": False}
    try:
        frames_mod, shm_mod, names = _load_target_modules(root)
    except Exception as e:
        out.append(Finding(
            "starway_tpu/core/frames.py", 1, "wire-diff",
            f"cannot load the tree's reference decoders: {e} "
            "(differential fuzzing would be vacuous)"))
        return counts
    try:
        if not hasattr(frames_mod, "decode_stream") \
                or not hasattr(shm_mod, "decode_sm_records"):
            return counts  # vacuity findings already appended by tables
        lib = _load_native(root)
        counts["native"] = lib is not None
        cases = load_corpus(out, root)
        for seed in range(seeds_per_mode):
            for mode in MODES:
                cases.append((f"seed:{seed}", mode, seed))
        for label, mode, case in cases:
            if counts["divergences"] >= MAX_DIVERGENCES:
                out.append(Finding(
                    "starway_tpu/core/frames.py", tbl.decode_line,
                    "wire-diff",
                    f"stopped after {MAX_DIVERGENCES} decoder divergences "
                    "-- the decode contract is systemically split (fix the "
                    "first finding and re-run)"))
                break
            try:
                data = case if isinstance(case, bytes) \
                    else gen_case(tbl, mode, case)
            except Exception as e:
                # The generator packs with the extracted layouts; it can
                # only fail when the grammar itself drifted under a
                # seeded mutation -- report once, don't die.
                if not counts.get("gen_error"):
                    counts["gen_error"] = True
                    out.append(Finding(
                        "starway_tpu/core/frames.py", tbl.decode_line,
                        "wire-diff",
                        f"case generator failed on the extracted grammar "
                        f"({type(e).__name__}: {e}) -- the wire layout "
                        "drifted out from under the fuzzer"))
                continue
            counts["cases"] += 1
            _diff_case(tbl, frames_mod, shm_mod, lib, label, mode, data,
                       out, counts)
    finally:
        for name in names:
            sys.modules.pop(name, None)
    return counts


def minimize_corpus(root: Path) -> dict:
    """Dedup the regression corpus in place by canonical-outcome
    signature (``(mode, grammar-oracle outcome)``): the oracle IS the
    contract, so two seeds it maps to the same outcome exercise the same
    decode behaviour and one suffices.  Every ``hex`` case is a pinned
    divergence (each carries its ``# why`` note) and is always kept --
    their outcomes also seed the duplicate set, so a generator seed
    shadowing a pin drops.  Comment lines survive verbatim, and if
    dedup would shrink the corpus below the CORPUS_FLOOR replay floor,
    dropped seeds are padded back (first-dropped first) under a marker
    comment.  Returns a summary dict for the CLI."""
    out: list = []
    got = _extract_tables(root, out)
    if got is None or out:
        raise SystemExit(
            "wirefuzz: cannot minimize -- grammar extraction failed:\n"
            + "\n".join(f.render() for f in out))
    tbl, _sets = got
    path = corpus_path(root)
    lines = path.read_text().splitlines()

    def signature(mode: str, data: bytes) -> tuple:
        if mode == "smrec":
            return (mode, _outcome(oracle_recs, tbl, data))
        return (mode, _outcome(oracle_stream, tbl, data, mode == "csum"))

    parsed = []
    for line in lines:
        s = line.strip()
        kind = mode = tok = None
        if s and not s.startswith("#"):
            parts = s.split(None, 2)
            if len(parts) >= 3 and parts[0] in ("seed", "hex") \
                    and parts[1] in MODES:
                kind, mode, tok = parts[0], parts[1], parts[2].split()[0]
        parsed.append((line, kind, mode, tok))

    seen: set = set()
    for _, kind, mode, tok in parsed:
        if kind == "hex":
            try:
                seen.add(signature(
                    mode, b"" if tok == "-" else bytes.fromhex(tok)))
            except ValueError:
                pass  # load_corpus flags malformed pins; keep them as-is

    kept: list = []
    dropped: list = []
    before = after = hex_kept = 0
    for line, kind, mode, tok in parsed:
        if kind is None:
            kept.append(line)
            continue
        before += 1
        if kind == "hex":
            hex_kept += 1
            kept.append(line)
            after += 1
            continue
        try:
            key = signature(mode, gen_case(tbl, mode, int(tok)))
        except Exception:
            kept.append(line)  # unparseable seed: a finding, not a drop
            after += 1
            continue
        if key in seen:
            dropped.append(line)
        else:
            seen.add(key)
            kept.append(line)
            after += 1
    if after < CORPUS_FLOOR and dropped:
        refill = dropped[:CORPUS_FLOOR - after]
        kept.append("# floor padding: outcome-duplicate seeds retained to "
                    f"keep the corpus at the {CORPUS_FLOOR}-case replay "
                    "floor")
        kept.extend(refill)
        after += len(refill)
    path.write_text("\n".join(kept) + "\n")
    return {"path": str(path), "before": before, "after": after,
            "hex_kept": hex_kept, "floor": CORPUS_FLOOR}


def run(root: Path) -> list:
    out: list = []
    got = _extract_tables(root, out)
    if got is None:
        return out
    tbl, _sets = got
    fuzz(root, tbl, out)
    return out
