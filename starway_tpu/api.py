"""Public asyncio API: ``Server`` and ``Client``.

The exact contract of the reference's Python layer
(src/starway/__init__.py:71-348 and src/starway/_bindings.pyi): callback-style
``send``/``recv``/``flush`` plus future-style ``asend``/``arecv``/``aflush``
variants, dual bootstrap (socket listener / worker-address bytes), endpoint
introspection, and ``evaluate_perf``.  Completion callbacks run on the engine
thread and trampoline into asyncio with ``loop.call_soon_threadsafe``
(reference: src/starway/__init__.py:124-128).

Buffers: 1-D ``uint8`` NumPy arrays are the host path (zero-copy, the buffer
must outlive the operation -- reference: src/bindings/main.hpp:55-59).
Non-uint8 arrays are value-cast to uint8 via a copy, matching nanobind's
implicit ndarray conversion in the reference bindings.  ``jax.Array`` and
:class:`~starway_tpu.device.DeviceBuffer` payloads take the device plane (see
device.py).
"""

from __future__ import annotations

import asyncio
import logging
import random
import threading
import weakref
from collections import deque
from typing import Callable, Optional

import numpy as np

from . import config
from .core import swtrace
from .core.endpoint import ServerEndpoint
from .core.engine import ClientWorker, ServerWorker
from .errors import REASON_TIMEOUT

logger = logging.getLogger("starway_tpu")


def _use_native_engine() -> bool:
    """The C++ engine serves the pure-TCP mode (STARWAY_TLS=tcp); the
    in-process fast path and device handoff need the Python engine."""
    if not config.use_native() or config.inproc_enabled():
        return False
    from .core import native

    return native.available()


def _new_client_worker():
    if _use_native_engine():
        from .core.native import NativeClientWorker

        return NativeClientWorker()
    return ClientWorker()


def _new_server_worker():
    if _use_native_engine():
        from .core.native import NativeServerWorker

        return NativeServerWorker()
    return ServerWorker()

_U64_MASK = (1 << 64) - 1


_device_mod = None


def _is_device_payload(buffer) -> bool:
    global _device_mod
    if _device_mod is None:
        from . import device as _device_mod_local

        _device_mod = _device_mod_local
    return _device_mod.is_device_payload(buffer)


def _send_view(buffer):
    """Coerce a send payload to (keepalive, flat uint8 memoryview)."""
    if isinstance(buffer, np.ndarray):
        arr = buffer
        if arr.dtype != np.uint8:
            # nanobind-style implicit conversion: value-cast copy.
            arr = np.ascontiguousarray(arr).astype(np.uint8)
        elif not arr.flags["C_CONTIGUOUS"]:
            arr = np.ascontiguousarray(arr)
        return arr, memoryview(arr).cast("B")
    if isinstance(buffer, (bytes, bytearray, memoryview)):
        return buffer, memoryview(buffer).cast("B")
    raise TypeError(
        f"unsupported send buffer type {type(buffer)!r}; expected numpy uint8 "
        "array, bytes-like, jax.Array, or DeviceBuffer"
    )


def _recv_view(buffer):
    """Coerce a receive target to (keepalive, writable flat uint8 memoryview)."""
    if isinstance(buffer, np.ndarray):
        if buffer.dtype != np.uint8:
            raise TypeError("receive buffer must be a uint8 ndarray")
        if not buffer.flags["C_CONTIGUOUS"]:
            raise TypeError("receive buffer must be C-contiguous")
        if not buffer.flags["WRITEABLE"]:
            raise TypeError("receive buffer must be writable")
        return buffer, memoryview(buffer).cast("B")
    if isinstance(buffer, (bytearray, memoryview)):
        mv = memoryview(buffer).cast("B")
        if mv.readonly:
            raise TypeError("receive buffer must be writable")
        return buffer, mv
    raise TypeError(
        f"unsupported receive buffer type {type(buffer)!r}; expected numpy "
        "uint8 array, bytearray, or DeviceBuffer"
    )


def _tag(tag: int) -> int:
    return int(tag) & _U64_MASK


class _CompletionTrampoline:
    """Per-loop batcher for cross-thread completions.

    Engine threads deliver completions in bursts (one fires sweep per
    engine wakeup); paying one ``call_soon_threadsafe`` -- a self-pipe
    write plus a scheduler pass -- *per completion* made an N-op burst
    cost N wakeups.  This trampoline queues the completions and schedules
    exactly one drain per burst: the first submission after an empty
    queue pays the hop, the rest ride it.  FIFO order is preserved.
    """

    # The loop is held WEAKLY: this object is the value keyed by the loop
    # in a WeakKeyDictionary, and a strong value->key reference would keep
    # every event loop (and this trampoline) alive forever.
    __slots__ = ("_loop_ref", "_lock", "_pending", "_scheduled")

    def __init__(self, loop: asyncio.AbstractEventLoop):
        self._loop_ref = weakref.ref(loop)
        self._lock = threading.Lock()
        self._pending: deque = deque()
        self._scheduled = False

    def submit(self, apply) -> None:
        loop = self._loop_ref()
        if loop is None or loop.is_closed():
            # Closed/collected loop: drop, like the pre-batching
            # call_soon_threadsafe path did -- and clear any backlog a
            # drain scheduled-but-never-run left behind, so _scheduled
            # cannot stick True and pin the dead loop via _pending.
            with self._lock:
                self._scheduled = False
                self._pending.clear()
            return
        with self._lock:
            self._pending.append(apply)
            if self._scheduled:
                return
            self._scheduled = True
        try:
            loop.call_soon_threadsafe(self._drain)
        except RuntimeError:
            # Lost the race with loop close: same drop contract.
            with self._lock:
                self._scheduled = False
                self._pending.clear()

    def _drain(self) -> None:
        while True:
            with self._lock:
                if not self._pending:
                    self._scheduled = False
                    return
                batch = list(self._pending)
                self._pending.clear()
            for apply in batch:
                try:
                    apply()
                except Exception:
                    logger.exception("starway: completion callback raised")


_trampolines: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_trampolines_lock = threading.Lock()


def _loop_trampoline(loop: asyncio.AbstractEventLoop) -> _CompletionTrampoline:
    with _trampolines_lock:
        tramp = _trampolines.get(loop)
        if tramp is None:
            tramp = _trampolines[loop] = _CompletionTrampoline(loop)
        return tramp


def _future_pair(loop: Optional[asyncio.AbstractEventLoop], result_factory=None):
    """Build (future, done_cb, fail_cb) bridging completions to asyncio.

    Completions from engine threads hop via the per-loop trampoline --
    one ``call_soon_threadsafe`` per burst, not per op (reference hops per
    op: src/starway/__init__.py:124-128).  Completions fired on the loop
    thread itself (the in-process inline fast path) resolve directly --
    no self-pipe write, no extra scheduler pass.
    """
    if loop is None:
        loop = asyncio.get_running_loop()
    fut: asyncio.Future = asyncio.Future(loop=loop)

    def _safe(call, *args):
        def apply():
            if not fut.done():
                call(*args)

        # Same-loop detection via thread id: CPython's BaseEventLoop pins
        # `_thread_id` while running, and threading.get_ident() is ~100x
        # cheaper than asyncio.get_running_loop() on virtualised hosts
        # (measured 7 us/call on this box -- it was the single largest
        # non-copy cost of the in-process pingpong).  Loop implementations
        # without the attribute fall back to the get_running_loop probe.
        tid = getattr(loop, "_thread_id", False)
        if tid is False:
            try:
                same = asyncio.get_running_loop() is loop
            except RuntimeError:
                same = False
        else:
            same = tid is not None and tid == threading.get_ident()
        if same:
            apply()
            return
        _loop_trampoline(loop).submit(apply)

    def done(*args):
        _safe(fut.set_result, result_factory(*args) if result_factory else None)

    def fail(reason: str):
        _safe(fut.set_exception, Exception(reason))

    return fut, done, fail


class Server:
    """Accepting side.  Reference: class Server, src/starway/__init__.py:71-209."""

    def __init__(self):
        self._server = _new_server_worker()

    # --------------------------------------------------------------- listen
    def listen(self, addr: str, port: int) -> None:
        self._server.listen(addr, port)

    def listen_address(self) -> bytes:
        return self._server.listen_address()

    def set_accept_cb(self, on_accept: Callable[[ServerEndpoint], None]) -> None:
        self._server.set_accept_cb(on_accept)

    def get_worker_address(self) -> bytes:
        return self._server.get_worker_address()

    def list_clients(self) -> set[ServerEndpoint]:
        return self._server.list_clients()

    # ---------------------------------------------------------------- close
    def aclose(self, loop: Optional[asyncio.AbstractEventLoop] = None):
        fut, done, _ = _future_pair(loop)

        def close_cb():
            logger.debug("starway server closed")
            done()

        self._server.close(close_cb)
        return fut

    # ----------------------------------------------------------------- send
    def send(self, client_ep: ServerEndpoint, buffer, tag: int,
             done_callback: Callable[[], None], fail_callback: Callable[[str], None],
             timeout: Optional[float] = None) -> None:
        """``timeout`` (seconds) bounds local completion: an unsettled send
        fails with the stable ``"timed out"`` reason.  Host payloads only;
        device-plane (jax.Array) sends ride the PJRT pull path, which has
        its own transfer lifecycle (device.py)."""
        if _is_device_payload(buffer):
            from . import device

            device.send_device(self._server, client_ep._conn, buffer, _tag(tag),
                               done_callback, fail_callback)
            return
        owner, view = _send_view(buffer)
        self._server.submit_send(client_ep._conn, view, _tag(tag),
                                 done_callback, fail_callback, owner,
                                 timeout=timeout)

    def asend(self, client_ep: ServerEndpoint, buffer, tag: int,
              loop: Optional[asyncio.AbstractEventLoop] = None,
              timeout: Optional[float] = None):
        fut, done, fail = _future_pair(loop)
        self.send(client_ep, buffer, tag, done, fail, timeout=timeout)
        return fut

    # ----------------------------------------------------------------- recv
    def recv(self, buffer, tag: int, tag_mask: int,
             done_callback: Callable[[int, int], None],
             fail_callback: Callable[[str], None],
             timeout: Optional[float] = None) -> None:
        """``timeout`` (seconds) bounds completion: an unmatched (or
        mid-stream) receive fails with ``"timed out"`` and its buffer is
        immediately safe to repost.  Host buffers only (see send)."""
        if _is_device_payload(buffer):
            from . import device

            device.post_device_recv(self._server, buffer, _tag(tag), _tag(tag_mask),
                                    done_callback, fail_callback)
            return
        owner, view = _recv_view(buffer)
        self._server.post_recv(view, _tag(tag), _tag(tag_mask),
                               done_callback, fail_callback, owner,
                               timeout=timeout)

    def arecv(self, buffer, tag: int, tag_mask: int,
              loop: Optional[asyncio.AbstractEventLoop] = None,
              timeout: Optional[float] = None):
        fut, done, fail = _future_pair(loop, result_factory=lambda st, ln: (st, ln))
        self.recv(buffer, tag, tag_mask, done, fail, timeout=timeout)
        return fut

    # ---------------------------------------------------------------- flush
    def flush(self, done_callback: Callable[[], None],
              fail_callback: Callable[[str], None],
              timeout: Optional[float] = None) -> None:
        self._server.submit_flush(done_callback, fail_callback, timeout=timeout)

    def aflush(self, loop: Optional[asyncio.AbstractEventLoop] = None,
               timeout: Optional[float] = None):
        fut, done, fail = _future_pair(loop)
        self.flush(done, fail, timeout=timeout)
        return fut

    def flush_ep(self, client_ep: ServerEndpoint, done_callback: Callable[[], None],
                 fail_callback: Callable[[str], None],
                 timeout: Optional[float] = None) -> None:
        self._server.submit_flush(done_callback, fail_callback, [client_ep._conn],
                                  timeout=timeout)

    def aflush_ep(self, client_ep: ServerEndpoint,
                  loop: Optional[asyncio.AbstractEventLoop] = None,
                  timeout: Optional[float] = None):
        fut, done, fail = _future_pair(loop)
        self.flush_ep(client_ep, done, fail, timeout=timeout)
        return fut

    # ------------------------------------------------------------ telemetry
    def evaluate_perf(self, client_ep: ServerEndpoint, msg_size: int) -> float:
        return self._server.evaluate_perf(client_ep._conn, msg_size)

    def evaluate_perf_detail(self, client_ep: ServerEndpoint,
                             msg_size: int) -> dict:
        """:meth:`evaluate_perf` plus ``calibrated``/``source`` honesty
        fields — a live per-endpoint fit, a live class fit, and a
        spec-sheet prior all say which they are (perf.py)."""
        return self._server.evaluate_perf_detail(client_ep._conn, msg_size)

    def __del__(self):
        try:
            self._server.force_close()
        except Exception:
            pass


class Client:
    """Connecting side.  Reference: class Client, src/starway/__init__.py:212-348."""

    def __init__(self):
        self._client = _new_client_worker()

    # -------------------------------------------------------------- connect
    def _aconnect_once(self, target, loop, timeout):
        """One connect attempt on the current (fresh) worker; returns an
        awaitable resolving to None or raising Exception(reason)."""
        fut, done, fail = _future_pair(loop)

        def connection_cb(status: str):
            if status == "":
                logger.debug("starway client connected to %s", target)
                done()
            else:
                fail(status)

        if isinstance(target, bytes):
            self._client.connect_address(target, connection_cb, timeout=timeout)
        else:
            addr, port = target
            self._client.connect(addr, port, connection_cb, timeout=timeout)
        return fut

    def _aconnect(self, target, loop, timeout, retries, backoff):
        """Connect with optional per-attempt ``timeout`` and ``retries``
        failed attempts retried under exponential backoff + jitter.  Workers
        are connect-once (the reference contract), so every retry swaps in a
        fresh engine worker -- callers never observe the churn.
        """
        if retries == 0 and timeout is None:
            return self._aconnect_once(target, loop, None)

        async def attempt_loop():
            last: Exception = Exception("connect: no attempt made")
            for attempt in range(retries + 1):
                if attempt > 0:
                    # Reconnect-attempt accounting is process-global by
                    # nature: every retry burns the old worker, so no
                    # single worker's registry could carry it.
                    swtrace.GLOBAL.reconnects += 1
                    # Exponential backoff, full jitter in [delay/2, delay]:
                    # a fleet of clients chasing one restarted server must
                    # not reconnect in lockstep.
                    delay = backoff * (2 ** (attempt - 1))
                    await asyncio.sleep(delay * (0.5 + random.random() / 2))
                    # Connect-once: fresh engine per attempt.  The burnt
                    # worker is force-closed, not just dropped -- a
                    # wait_for-expired attempt may still complete its
                    # handshake in the background and would otherwise leak
                    # a live engine thread + a ghost conn on the server.
                    old, self._client = self._client, _new_client_worker()
                    try:
                        old.force_close()
                    except Exception:
                        pass
                fut = self._aconnect_once(target, loop, timeout)
                try:
                    if timeout is not None:
                        await asyncio.wait_for(fut, timeout)
                    else:
                        await fut
                    return
                except asyncio.TimeoutError:
                    last = Exception(f"{REASON_TIMEOUT} (connect attempt {attempt + 1})")
                except Exception as e:  # "not connected: ..." from the engine
                    last = e
            # Out of attempts: retire the final burnt worker too (its
            # engine may still finish the handshake in the background) and
            # leave a fresh VOID worker so the Client can aconnect again.
            burnt, self._client = self._client, _new_client_worker()
            try:
                burnt.force_close()
            except Exception:
                pass
            raise last

        coro = attempt_loop()
        try:
            # Schedule eagerly when a loop is running: the return value then
            # behaves like the no-retry path's Future (connect underway
            # without an await, add_done_callback available).
            return asyncio.ensure_future(coro)
        except RuntimeError:
            return coro  # no running loop: caller awaits to drive it

    def aconnect(self, addr: str, port: int,
                 loop: Optional[asyncio.AbstractEventLoop] = None,
                 timeout: Optional[float] = None,
                 retries: int = 0, backoff: float = 0.5):
        """Connect to ``addr:port``.

        ``timeout`` bounds each attempt (default: the
        ``STARWAY_CONNECT_TIMEOUT`` knob, see config.py); ``retries`` extra
        attempts run under exponential backoff (base ``backoff`` seconds)
        with jitter.  Failure raises with a stable reason keyword:
        ``"not connected"`` (refused / reset / handshake failure) or
        ``"timed out"`` (deadline elapsed).
        """
        return self._aconnect((addr, port), loop, timeout, retries, backoff)

    def aconnect_address(self, remote_address: bytes,
                         loop: Optional[asyncio.AbstractEventLoop] = None,
                         timeout: Optional[float] = None,
                         retries: int = 0, backoff: float = 0.5):
        return self._aconnect(bytes(remote_address), loop, timeout, retries, backoff)

    def get_worker_address(self) -> bytes:
        return self._client.get_worker_address()

    # ---------------------------------------------------------------- close
    def aclose(self, loop: Optional[asyncio.AbstractEventLoop] = None):
        fut, done, _ = _future_pair(loop)

        def close_cb():
            logger.debug("starway client closed")
            done()

        self._client.close(close_cb)
        return fut

    # ----------------------------------------------------------------- send
    def send(self, buffer, tag: int, done_callback: Callable[[], None],
             fail_callback: Callable[[str], None],
             timeout: Optional[float] = None) -> None:
        """``timeout`` (seconds) bounds local completion (host payloads;
        see Server.send)."""
        if _is_device_payload(buffer):
            from . import device

            device.send_device(self._client, self._client.primary_conn, buffer,
                               _tag(tag), done_callback, fail_callback)
            return
        owner, view = _send_view(buffer)
        self._client.submit_send(self._client.primary_conn, view, _tag(tag),
                                 done_callback, fail_callback, owner,
                                 timeout=timeout)

    def asend(self, buffer, tag: int,
              loop: Optional[asyncio.AbstractEventLoop] = None,
              timeout: Optional[float] = None):
        fut, done, fail = _future_pair(loop)
        self.send(buffer, tag, done, fail, timeout=timeout)
        return fut

    # ----------------------------------------------------------------- recv
    def recv(self, buffer, tag: int, tag_mask: int,
             done_callback: Callable[[int, int], None],
             fail_callback: Callable[[str], None],
             timeout: Optional[float] = None) -> None:
        """``timeout`` (seconds) fails an unmatched receive with
        ``"timed out"``; the buffer is immediately safe to repost."""
        if _is_device_payload(buffer):
            from . import device

            device.post_device_recv(self._client, buffer, _tag(tag), _tag(tag_mask),
                                    done_callback, fail_callback)
            return
        owner, view = _recv_view(buffer)
        self._client.post_recv(view, _tag(tag), _tag(tag_mask),
                               done_callback, fail_callback, owner,
                               timeout=timeout)

    def arecv(self, buffer, tag: int, tag_mask: int,
              loop: Optional[asyncio.AbstractEventLoop] = None,
              timeout: Optional[float] = None):
        fut, done, fail = _future_pair(loop, result_factory=lambda st, ln: (st, ln))
        self.recv(buffer, tag, tag_mask, done, fail, timeout=timeout)
        return fut

    # ---------------------------------------------------------------- flush
    def flush(self, done_callback: Callable[[], None],
              fail_callback: Callable[[str], None],
              timeout: Optional[float] = None) -> None:
        self._client.submit_flush(done_callback, fail_callback, timeout=timeout)

    def aflush(self, loop: Optional[asyncio.AbstractEventLoop] = None,
               timeout: Optional[float] = None):
        fut, done, fail = _future_pair(loop)
        self.flush(done, fail, timeout=timeout)
        return fut

    # ------------------------------------------------------------ telemetry
    def evaluate_perf(self, msg_size: int) -> float:
        return self._client.evaluate_perf(self._client.primary_conn, msg_size)

    def evaluate_perf_detail(self, msg_size: int) -> dict:
        """:meth:`evaluate_perf` plus ``calibrated``/``source`` honesty
        fields (perf.py)."""
        return self._client.evaluate_perf_detail(self._client.primary_conn,
                                                 msg_size)

    def __del__(self):
        try:
            self._client.force_close()
        except Exception:
            pass
