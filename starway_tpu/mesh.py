"""Mesh addressing and multi-host bootstrap.

The reference's endpoint identity is a UCX worker address (opaque bytes
moved out-of-band, reference: src/bindings/main.cpp:241-251,834-860).  The
TPU-native equivalent enriches the worker-address blob with *mesh
coordinates*: which process, which devices, where in the logical mesh --
"peers resolve to mesh coordinates rather than IB addresses"
(BASELINE.json north star).

Two layers:

* :class:`MeshAddress` -- the serialized identity: host contact info plus
  ``process_index``, device kind/count and optional logical coords.  This is
  what ``listen_address()`` blobs become when minted through
  :func:`export_mesh_address`; plain blobs still parse (fields default).
* :func:`bootstrap_distributed` -- thin gate over ``jax.distributed``: on a
  real multi-host pod this initialises the DCN-side runtime so cross-host
  jax.Arrays and collectives work; the P2P layer then uses host TCP for
  control and the device plane for data.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional, Sequence


@dataclasses.dataclass(frozen=True)
class MeshAddress:
    worker_id: str
    host: str
    port: int
    process_index: int = 0
    device_kind: str = ""
    device_count: int = 0
    coords: Optional[tuple] = None  # logical mesh coords of this worker
    mesh_shape: Optional[dict] = None  # {"dp": 2, "tp": 4}

    def to_bytes(self) -> bytes:
        d = dataclasses.asdict(self)
        d["fabric"] = "starway-tpu"
        if d["coords"] is not None:
            d["coords"] = list(d["coords"])
        return json.dumps(d).encode()

    @classmethod
    def from_bytes(cls, blob: bytes) -> "MeshAddress":
        info = json.loads(bytes(blob).decode())
        coords = info.get("coords")
        return cls(
            worker_id=info.get("worker_id", ""),
            host=info.get("host", "127.0.0.1"),
            port=int(info.get("port", 0)),
            process_index=int(info.get("process_index", 0)),
            device_kind=info.get("device_kind", ""),
            device_count=int(info.get("device_count", 0)),
            coords=tuple(coords) if coords is not None else None,
            mesh_shape=info.get("mesh_shape"),
        )


def export_mesh_address(server, *, coords: Optional[Sequence[int]] = None,
                        mesh_shape: Optional[dict] = None) -> bytes:
    """Augment a Server's worker-address blob with local device/mesh info.

    The result still works with ``Client.aconnect_address`` (the extra keys
    are ignored by the bootstrap path) while letting mesh-aware peers route
    by coordinates.
    """
    base = json.loads(server.get_worker_address().decode())
    info = dict(base)
    try:
        import jax

        devs = jax.devices()
        info["process_index"] = jax.process_index()
        info["device_kind"] = devs[0].device_kind if devs else ""
        info["device_count"] = len(devs)
    except Exception:
        info.setdefault("process_index", 0)
        info.setdefault("device_kind", "")
        info.setdefault("device_count", 0)
    if coords is not None:
        info["coords"] = list(coords)
    if mesh_shape is not None:
        info["mesh_shape"] = dict(mesh_shape)
    return json.dumps(info).encode()


def parse_mesh_address(blob: bytes) -> MeshAddress:
    return MeshAddress.from_bytes(blob)


def bootstrap_distributed(coordinator_address: str, num_processes: int,
                          process_id: int) -> None:
    """Initialise the cross-host (DCN) jax runtime.

    On a multi-host TPU pod this is the analogue of exchanging UCX worker
    addresses out-of-band: after it returns, ``jax.devices()`` spans all
    hosts and mesh collectives ride ICI within a slice / DCN across slices.
    Safe to call once per process; raises RuntimeError where unsupported.
    """
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
