"""Transfer-time estimation: the ``evaluate_perf`` analogue.

The reference exposes UCX's transport model estimate
(``ucp_ep_evaluate_perf``, reference: src/bindings/main.cpp:452-467,666-678)
as seconds-to-transfer-msg_size.  The TPU build replaces it with an explicit
alpha-beta link model per transport (SURVEY.md section 5 "Tracing /
profiling": "keep an evaluate_perf analogue backed by an ICI/DCN link
model")::

    t(bytes) = alpha + bytes / beta

Default betas reflect TPU v5e-class hardware (ICI ~45 GB/s per link
direction, DCN ~12.5 GB/s per host NIC) and measured host-loopback numbers;
calibrate with :func:`calibrate` from observed samples.

Estimates are PER-ENDPOINT when live calibration has run (the reference's
``ucp_ep_evaluate_perf`` queries the endpoint, not a transport class:
two peers with different link quality report differently):
:func:`autocalibrate` (client side) and :func:`autocalibrate_ep` (server
side, probing one accepted endpoint) attach the fitted (alpha, beta) to
the CONNECTION, and both engines' ``evaluate_perf`` prefer that over the
class table.  Probes ride the reserved PROBE_TAG both directions — the
peer's matcher consumes and drops them (core/matching.py, sw_engine.cpp).
"""

from __future__ import annotations

import threading

# ------------------------------------------------------ per-stage telemetry
#
# The data plane records wall time + bytes per pipeline stage so a bench
# regression is attributable to the stage that moved (DESIGN.md §12):
#
#   ``stage`` -- device-to-host staging (D2H) on the send side (device.py)
#   ``tx``    -- transport writes (socket sendmsg / sm ring) (core/conn.py)
#   ``rx``    -- transport reads (core/conn.py)
#   ``place`` -- host-to-device placement (H2D) on the receive side
#
# Recording is two perf_counter calls + one short lock per transport
# syscall -- noise next to the syscall itself.  Samples land twice: in the
# recorder's :class:`StageScope` (per worker, so two concurrent clients --
# or bench loopback's two roles -- never pollute each other's
# ``evaluate_perf_detail()["stages"]``) and in the module-level aggregate
# below (the whole-process view bench.py and the bench CLI report).

_stage_lock = threading.Lock()
_stages: dict[str, list] = {}  # name -> [count, seconds, bytes]


class StageScope:
    """Per-worker stage accumulator (same shape as the module aggregate).

    ``ring`` optionally carries a core/swtrace.py TraceRing: each recorded
    sample then also lands as an EV_STAGE span in the worker's trace, so
    a bench run's Chrome export shows the stage timeline per op stream.
    """

    __slots__ = ("_lock", "_stages", "ring")

    def __init__(self, ring=None):
        self._lock = threading.Lock()
        self._stages: dict[str, list] = {}
        self.ring = ring

    def record(self, name: str, seconds: float, nbytes: int = 0) -> None:
        with self._lock:
            acc = self._stages.get(name)
            if acc is None:
                self._stages[name] = [1, seconds, nbytes]
            else:
                acc[0] += 1
                acc[1] += seconds
                acc[2] += nbytes

    def snapshot(self) -> dict:
        with self._lock:
            return _render_stages(self._stages)

    def reset(self) -> None:
        with self._lock:
            self._stages.clear()


def percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile of an ascending sequence (stdlib-only) --
    the one implementation both the driver bench and the bench CLI's
    stage p-tiles report through."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, round(q / 100 * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def _render_stages(stages: dict) -> dict:
    out = {}
    for name, (count, seconds, nbytes) in stages.items():
        out[name] = {
            "count": count,
            "seconds": seconds,
            "bytes": nbytes,
            "gbps": (nbytes / seconds / 1e9) if seconds > 0 else 0.0,
        }
    return out


def record_stage(name: str, seconds: float, nbytes: int = 0,
                 scope: "StageScope | None" = None) -> None:
    """Accumulate one sample for pipeline stage ``name`` (thread-safe;
    called from engine threads and the app thread alike).  ``scope`` is
    the recording worker's :class:`StageScope`; the module aggregate is
    always updated too."""
    with _stage_lock:
        acc = _stages.get(name)
        if acc is None:
            _stages[name] = [1, seconds, nbytes]
        else:
            acc[0] += 1
            acc[1] += seconds
            acc[2] += nbytes
    if scope is not None:
        scope.record(name, seconds, nbytes)
        ring = scope.ring
        if ring is not None:
            from .core import swtrace

            ring.rec(swtrace.EV_STAGE, 0, 0, nbytes, name, seconds)


def stage_snapshot() -> dict:
    """``{stage: {"count", "seconds", "bytes", "gbps"}}`` accumulated since
    process start (or the last :func:`stage_reset`) -- the whole-process
    aggregate; per-worker views live on ``Worker.stage_scope``."""
    with _stage_lock:
        return _render_stages(_stages)


def stage_reset() -> None:
    """Drop accumulated stage samples (bench warmup boundary)."""
    with _stage_lock:
        _stages.clear()


# transport -> (alpha seconds, beta bytes/second)
LINK_MODELS: dict[str, tuple[float, float]] = {
    "inproc": (2.0e-6, 30.0e9),  # same-process memcpy / HBM-to-HBM handoff
    "sm": (25.0e-6, 5.0e9),  # same-host shared-memory rings (core/shmring.py)
    "tcp": (30.0e-6, 2.5e9),  # host loopback / DCN-adjacent bootstrap path
    "ici": (1.0e-6, 45.0e9),  # v5e ICI per-link, one direction
    "dcn": (50.0e-6, 12.5e9),  # cross-slice data-center network
}

# Where each PRIOR came from (VERDICT r4 #5: an estimate from an
# uncalibrated constant must say so).  calibrate() replaces these with a
# live-fit note; conn_estimate_detail reports per-endpoint fits.
PROVENANCE: dict[str, str] = {
    "inproc": "prior: same-process handoff, measured host-loopback class",
    "sm": "prior: shared-memory ring class, measured host-loopback",
    "tcp": "prior: loopback/DCN-adjacent TCP class estimate",
    "ici": "prior: TPU v5e ICI ~45 GB/s per link per direction (public "
           "v5e system specs; 4x ICI links/chip) — no live ICI probe has "
           "ever run in this process",
    "dcn": "prior: ~100 Gbps-class host NIC (12.5 GB/s) cross-slice "
           "estimate — no live DCN probe has ever run in this process",
}

# Transports whose class entry was replaced by a live calibrate() fit.
CALIBRATED: set[str] = set()


def _apply(model: tuple[float, float], msg_size: int) -> float:
    """t(bytes) = alpha + bytes / beta — the one place the model runs."""
    alpha, beta = model
    return alpha + max(0, int(msg_size)) / beta


def estimate(transport: str, msg_size: int) -> float:
    """Estimated seconds to transfer ``msg_size`` bytes over ``transport``.

    Always > 0, matching the reference contract (tests/test_basic.py:445-457).
    """
    return estimate_detail(transport, msg_size)["seconds"]


def conn_estimate(conn, transport: str, msg_size: int) -> float:
    """Per-endpoint estimate: a live-calibrated model attached to the
    connection (``conn.perf_model``, set by :func:`autocalibrate` /
    :func:`autocalibrate_ep`) wins over the transport-class table —
    both engines' ``evaluate_perf`` route through here.  Delegates to
    :func:`conn_estimate_detail` so the resolution policy lives once."""
    return conn_estimate_detail(conn, transport, msg_size)["seconds"]


def estimate_detail(transport: str, msg_size: int,
                    scope: "StageScope | None" = None) -> dict:
    """:func:`estimate` with honesty attached: the model, whether it came
    from a live fit, and its provenance."""
    key = transport if transport in LINK_MODELS else "tcp"
    alpha, beta = LINK_MODELS[key]
    return {
        "seconds": _apply((alpha, beta), msg_size),
        "alpha": alpha,
        "beta": beta,
        "transport": key,
        "calibrated": key in CALIBRATED,
        "source": PROVENANCE.get(key, "prior: unknown transport class"),
        # Live per-stage pipeline timings (stage/tx/rx/place -- see
        # record_stage), so a model estimate and the measured data plane
        # sit side by side.  Scoped to the querying worker when it passes
        # its StageScope; the whole-process aggregate otherwise.
        "stages": scope.snapshot() if scope is not None else stage_snapshot(),
    }


def conn_estimate_detail(conn, transport: str, msg_size: int,
                         scope: "StageScope | None" = None) -> dict:
    """:func:`conn_estimate` with honesty attached (VERDICT r4 #5): a
    caller can tell a live per-endpoint fit from a class fit from a
    spec-sheet prior — confident numbers from uncalibrated constants are
    worse than numbers that say "uncalibrated"."""
    model = getattr(conn, "perf_model", None)
    if model is not None:
        alpha, beta = model
        return {
            "seconds": _apply(model, msg_size),
            "alpha": alpha,
            "beta": beta,
            "transport": transport,
            "calibrated": True,
            "source": "live per-endpoint fit (autocalibrate/"
                      "autocalibrate_ep over PROBE_TAG)",
            "stages": (scope.snapshot() if scope is not None
                       else stage_snapshot()),
        }
    return estimate_detail(transport, msg_size, scope=scope)


async def _probe_samples(send, flush, sizes):
    """(bytes, seconds) enqueue-to-flush samples over PROBE_TAG probes."""
    import time

    import numpy as np

    from .core.matching import PROBE_TAG

    samples = []
    for size in sizes:
        buf = np.zeros(size, dtype=np.uint8)
        # warmup
        await send(buf, PROBE_TAG)
        await flush()
        t0 = time.perf_counter()
        await send(buf, PROBE_TAG)
        await flush()
        samples.append((size, time.perf_counter() - t0))
    return samples


async def autocalibrate(client, transport: str = "inproc",
                        sizes=(1 << 10, 1 << 16, 1 << 20, 1 << 24)) -> tuple[float, float]:
    """Fit the link model from live one-way probes on a connected Client.

    Measures enqueue-to-flush time per size, which tracks the transport's
    alpha/beta -- the role ucp_ep_evaluate_perf's model plays in the
    reference.  Probes ride the reserved PROBE_TAG, which both engines'
    matchers consume and drop on arrival (core/matching.py) -- probing a
    live connection cannot pollute the peer's matching state or be claimed
    by wildcard receives.

    The fit lands twice: on ``transport``'s class-table entry (the
    fallback every uncalibrated estimate uses) and on THIS client's
    connection, so ``client.evaluate_perf`` reports the endpoint's own
    measured link from then on.
    """
    samples = await _probe_samples(client.asend, client.aflush, sizes)
    model = calibrate(transport, samples)
    conn = getattr(client, "_client", client).primary_conn
    if conn is not None:
        conn.perf_model = model
    return model


async def autocalibrate_ep(server, client_ep,
                           sizes=(1 << 10, 1 << 16, 1 << 20, 1 << 24)) -> tuple[float, float]:
    """Server-side per-endpoint calibration: probe ONE accepted endpoint
    (``server.asend(ep, ...)`` + ``aflush_ep``) and attach the fitted
    (alpha, beta) to that endpoint's connection only — the class table is
    untouched, so two peers on different links report different estimates
    from their own live probes (``server.evaluate_perf(ep, n)``)."""
    samples = await _probe_samples(
        lambda buf, tag: server.asend(client_ep, buf, tag),
        lambda: server.aflush_ep(client_ep), sizes)
    model = fit_alpha_beta(samples)
    client_ep._conn.perf_model = model
    return model


def fit_alpha_beta(samples: list[tuple[float, float]]) -> tuple[float, float]:
    """Least-squares (alpha, beta) from (bytes, seconds) samples."""
    if len(samples) < 2:
        raise ValueError("need at least two (bytes, seconds) samples")
    n = len(samples)
    sx = sum(b for b, _ in samples)
    sy = sum(t for _, t in samples)
    sxx = sum(b * b for b, _ in samples)
    sxy = sum(b * t for b, t in samples)
    denom = n * sxx - sx * sx
    if denom == 0:
        raise ValueError("degenerate samples")
    inv_beta = (n * sxy - sx * sy) / denom
    alpha = (sy - inv_beta * sx) / n
    return max(alpha, 1e-9), 1.0 / max(inv_beta, 1e-15)


def calibrate(transport: str, samples: list[tuple[float, float]]) -> tuple[float, float]:
    """:func:`fit_alpha_beta`, committed to ``transport``'s class-table
    entry (the fallback for uncalibrated endpoints).  Returns the fit."""
    model = fit_alpha_beta(samples)
    LINK_MODELS[transport] = model
    CALIBRATED.add(transport)
    PROVENANCE[transport] = (
        f"live class fit from {len(samples)} probe samples")
    return model
