"""Transfer-time estimation: the ``evaluate_perf`` analogue.

The reference exposes UCX's transport model estimate
(``ucp_ep_evaluate_perf``, reference: src/bindings/main.cpp:452-467,666-678)
as seconds-to-transfer-msg_size.  The TPU build replaces it with an explicit
alpha-beta link model per transport (SURVEY.md section 5 "Tracing /
profiling": "keep an evaluate_perf analogue backed by an ICI/DCN link
model")::

    t(bytes) = alpha + bytes / beta

Default betas reflect TPU v5e-class hardware (ICI ~45 GB/s per link
direction, DCN ~12.5 GB/s per host NIC) and measured host-loopback numbers;
calibrate with :func:`calibrate` from observed samples.
"""

from __future__ import annotations

# transport -> (alpha seconds, beta bytes/second)
LINK_MODELS: dict[str, tuple[float, float]] = {
    "inproc": (2.0e-6, 30.0e9),  # same-process memcpy / HBM-to-HBM handoff
    "sm": (25.0e-6, 5.0e9),  # same-host shared-memory rings (core/shmring.py)
    "tcp": (30.0e-6, 2.5e9),  # host loopback / DCN-adjacent bootstrap path
    "ici": (1.0e-6, 45.0e9),  # v5e ICI per-link, one direction
    "dcn": (50.0e-6, 12.5e9),  # cross-slice data-center network
}


def estimate(transport: str, msg_size: int) -> float:
    """Estimated seconds to transfer ``msg_size`` bytes over ``transport``.

    Always > 0, matching the reference contract (tests/test_basic.py:445-457).
    """
    alpha, beta = LINK_MODELS.get(transport, LINK_MODELS["tcp"])
    return alpha + max(0, int(msg_size)) / beta


async def autocalibrate(client, transport: str = "inproc",
                        sizes=(1 << 10, 1 << 16, 1 << 20, 1 << 24)) -> tuple[float, float]:
    """Fit the link model from live one-way probes on a connected Client.

    Measures enqueue-to-flush time per size, which tracks the transport's
    alpha/beta -- the role ucp_ep_evaluate_perf's model plays in the
    reference.  Probes ride the reserved PROBE_TAG, which both engines'
    matchers consume and drop on arrival (core/matching.py) -- probing a
    live connection cannot pollute the peer's matching state or be claimed
    by wildcard receives.
    """
    import time

    import numpy as np

    from .core.matching import PROBE_TAG

    samples = []
    for size in sizes:
        buf = np.zeros(size, dtype=np.uint8)
        # warmup
        await client.asend(buf, PROBE_TAG)
        await client.aflush()
        t0 = time.perf_counter()
        await client.asend(buf, PROBE_TAG)
        await client.aflush()
        samples.append((size, time.perf_counter() - t0))
    return calibrate(transport, samples)


def calibrate(transport: str, samples: list[tuple[int, float]]) -> tuple[float, float]:
    """Least-squares fit of (alpha, beta) from (bytes, seconds) samples and
    update the model in place.  Returns the fitted (alpha, beta)."""
    if len(samples) < 2:
        raise ValueError("need at least two (bytes, seconds) samples")
    n = len(samples)
    sx = sum(b for b, _ in samples)
    sy = sum(t for _, t in samples)
    sxx = sum(b * b for b, _ in samples)
    sxy = sum(b * t for b, t in samples)
    denom = n * sxx - sx * sx
    if denom == 0:
        raise ValueError("degenerate samples")
    inv_beta = (n * sxy - sx * sy) / denom
    alpha = (sy - inv_beta * sx) / n
    alpha = max(alpha, 1e-9)
    beta = 1.0 / max(inv_beta, 1e-15)
    LINK_MODELS[transport] = (alpha, beta)
    return alpha, beta
