"""swtrace export: ring / flight-recorder dumps -> Chrome ``trace_event``.

``python -m starway_tpu.trace dump1.json [dump2.json ...] -o out.json``
converts flight-recorder dumps (core/swtrace.py flight_dump) into one
Chrome/Perfetto-loadable trace; ``python -m starway_tpu.bench --trace
PATH`` uses :func:`write_chrome` directly on the live ring registry.

Layout: one trace *process* per worker (pid = worker index, process_name
metadata carries the worker label), one *thread* per connection (tid =
conn id; tid 0 is the worker-wide track: posted receives are fan-in and
have no conn until matched).  Op lifecycles render as complete ("X")
spans -- ``send_post``..``send_done``, ``recv_post``..``recv_done``,
``flush_post``..``flush_done``, with ``op_fail`` closing whichever op it
matches -- stage spans (``stage_span`` events from perf.record_stage)
as "X" spans of their measured duration, and everything unpaired
(matches, connection churn) as instants.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import deque
from pathlib import Path
from typing import Iterable

from .core import swtrace

# POST event -> (span kind, terminal event)
_POSTS = {
    swtrace.EV_SEND_POST: "send",
    swtrace.EV_RECV_POST: "recv",
    swtrace.EV_FLUSH_POST: "flush",
}
_DONES = {
    swtrace.EV_SEND_DONE: "send",
    swtrace.EV_RECV_DONE: "recv",
    swtrace.EV_FLUSH_DONE: "flush",
}


def _pop_start(open_spans: dict, kind: str, tag: int, fifo_fallback: bool):
    """The matching open span for a terminal event: exact (kind, tag)
    first; with ``fifo_fallback``, the oldest open span of that kind (a
    wildcard receive completes with the SENDER's tag, which may differ
    from the posted one).  Failure events carry the op's own posted tag,
    so they match exactly or not at all -- a fallback there would close
    an unrelated pending op's span."""
    q = open_spans.get((kind, tag))
    if q:
        return q.popleft()
    if not fifo_fallback:
        return None
    oldest_key, oldest = None, None
    for (k, t), dq in open_spans.items():
        if k != kind or not dq:
            continue
        if oldest is None or dq[0][0] < oldest[0]:
            oldest_key, oldest = (k, t), dq[0]
    if oldest_key is not None:
        return open_spans[oldest_key].popleft()
    return None


def chrome_events(label: str, events: Iterable, pid: int) -> list:
    """Chrome trace events for one worker's swtrace ring."""
    out = [{"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": label}}]
    tids = set()
    open_spans: dict = {}  # (kind, tag) -> deque[(ts_us, conn, nbytes)]
    for t, ev, tag, conn, nbytes, reason, dur in events:
        ts = t * 1e6
        tids.add(conn)
        if ev in _POSTS:
            open_spans.setdefault((_POSTS[ev], tag), deque()).append(
                (ts, conn, nbytes))
        elif ev in _DONES or ev == swtrace.EV_OP_FAIL:
            if ev == swtrace.EV_OP_FAIL:
                # A failure terminates the op whose posted tag it carries
                # (exact match only -- see _pop_start).
                start = None
                for kind in ("recv", "send", "flush"):
                    start = _pop_start(open_spans, kind, tag,
                                       fifo_fallback=False)
                    if start is not None:
                        break
                name = f"FAIL tag={tag:#x}"
            else:
                kind = _DONES[ev]
                start = _pop_start(open_spans, kind, tag,
                                   fifo_fallback=(kind == "recv"))
                name = f"{kind} tag={tag:#x}" if kind != "flush" else "flush"
            if start is None:
                out.append({"ph": "i", "name": name, "ts": ts, "pid": pid,
                            "tid": conn, "s": "t",
                            "args": {"nbytes": nbytes, "reason": reason}})
                continue
            ts0, conn0, nb0 = start
            tid = conn or conn0
            tids.add(tid)
            out.append({"ph": "X", "name": name, "ts": ts0,
                        "dur": max(0.0, ts - ts0), "pid": pid, "tid": tid,
                        "args": {"nbytes": nbytes or nb0, "reason": reason}})
        elif ev == swtrace.EV_STAGE:
            out.append({"ph": "X", "name": reason or "stage",
                        "ts": (t - dur) * 1e6, "dur": max(0.0, dur * 1e6),
                        "pid": pid, "tid": conn, "cat": "stage",
                        "args": {"nbytes": nbytes}})
        else:  # recv_match, conn_up, conn_down, anything future
            out.append({"ph": "i", "name": ev, "ts": ts, "pid": pid,
                        "tid": conn, "s": "t",
                        "args": {"tag": tag, "nbytes": nbytes}})
    # Spans still open at dump time (ops pending when the ring was read).
    for (kind, tag), dq in open_spans.items():
        for ts0, conn0, nb0 in dq:
            out.append({"ph": "i", "name": f"pending {kind} tag={tag:#x}",
                        "ts": ts0, "pid": pid, "tid": conn0, "s": "t",
                        "args": {"nbytes": nb0}})
    for tid in sorted(tids):
        out.append({"ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                    "args": {"name": "worker" if tid == 0 else f"conn {tid}"}})
    return out


def to_chrome(dumps: Iterable[dict]) -> dict:
    """``{"traceEvents": [...]}`` from ``[{"worker", "events"}, ...]``
    dumps (the shape of swtrace.dump_all() and of flight-recorder files).
    """
    trace_events: list = []
    for pid, dump in enumerate(dumps, start=1):
        trace_events.extend(
            chrome_events(dump.get("worker", f"worker-{pid}"),
                          dump.get("events", []), pid))
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}


def write_chrome(dumps: Iterable[dict], path) -> Path:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(to_chrome(dumps), indent=1))
    return path


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m starway_tpu.trace",
        description="Convert swtrace flight-recorder dumps to Chrome "
                    "trace_event JSON (open in Perfetto / chrome://tracing).")
    p.add_argument("inputs", nargs="+", type=Path,
                   help="flight-recorder JSON dumps (STARWAY_FLIGHT_DIR)")
    p.add_argument("-o", "--output", type=Path, default=Path("swtrace.json"))
    args = p.parse_args(argv)
    dumps = []
    for path in args.inputs:
        raw = json.loads(path.read_text())
        if "events" not in raw:
            print(f"{path}: not a swtrace dump (no 'events' key)",
                  file=sys.stderr)
            return 1
        dumps.append(raw)
    out = write_chrome(dumps, args.output)
    n = sum(len(d.get("events", [])) for d in dumps)
    print(f"wrote {out} ({n} events from {len(dumps)} dump(s))")
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
