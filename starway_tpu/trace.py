"""swtrace/swscope export: ring and flight dumps -> Chrome ``trace_event``.

Two modes (DESIGN.md §13 and §15):

* ``python -m starway_tpu.trace dump1.json [...] -o out.json`` converts
  flight-recorder dumps (core/swtrace.py flight_dump) or per-process ring
  dumps (swtrace.write_ring_dump) into one Chrome/Perfetto-loadable
  trace; ``python -m starway_tpu.bench --trace PATH`` uses
  :func:`write_chrome` directly on the live ring registry.

* ``python -m starway_tpu.trace --merge procA.json procB.json -o out``
  stitches dumps from DIFFERENT processes into ONE clock-aligned trace:
  EV_CLOCK samples (timestamped PING/PONG round trips) build a
  per-process offset graph, every process's timestamps are shifted onto
  the first process's timeline, and paired EV_E2E ordinals become Chrome
  flow events connecting each message's send span to its recv span
  across processes.  A wire-vs-stage latency breakdown (message wall
  time between the two rings vs. the recorded EV_STAGE spans) prints
  alongside and lands in the output under ``"swscope"``.

Layout: one trace *process* per worker (pid = worker index, process_name
metadata carries the worker label), one *thread* per connection
INCARNATION -- tracks are keyed by (conn, epoch), where a session resume
(EV_SESS_RESUME) bumps the conn's epoch, so pre- and post-resume events
never interleave on one track (tid = conn id for epoch 0; resumed
incarnations get fresh synthetic tids, named "conn N epoch E").  tid 0
is the worker-wide track: posted receives are fan-in and have no conn
until matched.  Op lifecycles render as complete ("X") spans --
``send_post``..``send_done``, ``recv_post``..``recv_done``,
``flush_post``..``flush_done``, with ``op_fail`` closing whichever op it
matches -- stage spans (``stage_span`` events from perf.record_stage) as
"X" spans of their measured duration, and everything unpaired (matches,
E2E ordinals, connection churn) as instants.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import deque
from pathlib import Path
from typing import Iterable, Optional

from .core import swtrace
from .perf import percentile

# POST event -> (span kind, terminal event)
_POSTS = {
    swtrace.EV_SEND_POST: "send",
    swtrace.EV_RECV_POST: "recv",
    swtrace.EV_FLUSH_POST: "flush",
}
_DONES = {
    swtrace.EV_SEND_DONE: "send",
    swtrace.EV_RECV_DONE: "recv",
    swtrace.EV_FLUSH_DONE: "flush",
}

#: First synthetic tid handed to a resumed conn incarnation -- far above
#: any realistic per-process conn id, so epoch tracks never collide with
#: epoch-0 tracks (which keep tid = conn id).
_EPOCH_TID_BASE = 1_000_000


def _pop_start(open_spans: dict, kind: str, tag: int, fifo_fallback: bool):
    """The matching open span for a terminal event: exact (kind, tag)
    first; with ``fifo_fallback``, the oldest open span of that kind (a
    wildcard receive completes with the SENDER's tag, which may differ
    from the posted one).  Failure events carry the op's own posted tag,
    so they match exactly or not at all -- a fallback there would close
    an unrelated pending op's span."""
    q = open_spans.get((kind, tag))
    if q:
        return q.popleft()
    if not fifo_fallback:
        return None
    oldest_key, oldest = None, None
    for (k, t), dq in open_spans.items():
        if k != kind or not dq:
            continue
        if oldest is None or dq[0][0] < oldest[0]:
            oldest_key, oldest = (k, t), dq[0]
    if oldest_key is not None:
        return open_spans[oldest_key].popleft()
    return None


def chrome_events(label: str, events: Iterable, pid: int,
                  ts_shift: float = 0.0,
                  e2e_out: Optional[list] = None) -> list:
    """Chrome trace events for one worker's swtrace ring.  ``ts_shift``
    (seconds, from the --merge clock alignment) is added to every
    timestamp.  ``e2e_out``, when given, collects one
    ``(tcid, direction, ordinal, ts_us, tid, nbytes)`` entry per EV_E2E
    tx/rx event -- carrying the SAME (conn, epoch)-keyed tid the event
    renders on, so --merge flow arrows anchor to the track that actually
    holds the post-resume spans."""
    out = [{"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": label}}]
    # (conn, epoch) -> tid: a session resume starts a NEW track so the
    # two incarnations' events never interleave on one line.
    epochs: dict = {}
    tid_map: dict = {}
    tid_label: dict = {0: "worker"}
    next_epoch_tid = [_EPOCH_TID_BASE + pid * 10_000]

    def tid_of(conn: int) -> int:
        if conn == 0:
            return 0
        e = epochs.get(conn, 0)
        t = tid_map.get((conn, e))
        if t is None:
            if e == 0:
                t = conn
                tid_label[t] = f"conn {conn}"
            else:
                t = next_epoch_tid[0]
                next_epoch_tid[0] += 1
                tid_label[t] = f"conn {conn} epoch {e}"
            tid_map[(conn, e)] = t
        return t

    open_spans: dict = {}  # (kind, tag) -> deque[(ts_us, conn, nbytes)]
    for t, ev, tag, conn, nbytes, reason, dur in events:
        ts = (t + ts_shift) * 1e6
        if ev == swtrace.EV_SESS_RESUME:
            epochs[conn] = epochs.get(conn, 0) + 1
        if ev in _POSTS:
            tid_of(conn)
            open_spans.setdefault((_POSTS[ev], tag), deque()).append(
                (ts, conn, nbytes))
        elif ev in _DONES or ev == swtrace.EV_OP_FAIL:
            if ev == swtrace.EV_OP_FAIL:
                # A failure terminates the op whose posted tag it carries
                # (exact match only -- see _pop_start).
                start = None
                for kind in ("recv", "send", "flush"):
                    start = _pop_start(open_spans, kind, tag,
                                       fifo_fallback=False)
                    if start is not None:
                        break
                name = f"FAIL tag={tag:#x}"
            else:
                kind = _DONES[ev]
                start = _pop_start(open_spans, kind, tag,
                                   fifo_fallback=(kind == "recv"))
                name = f"{kind} tag={tag:#x}" if kind != "flush" else "flush"
            if start is None:
                out.append({"ph": "i", "name": name, "ts": ts, "pid": pid,
                            "tid": tid_of(conn), "s": "t",
                            "args": {"nbytes": nbytes, "reason": reason}})
                continue
            ts0, conn0, nb0 = start
            out.append({"ph": "X", "name": name, "ts": ts0,
                        "dur": max(0.0, ts - ts0), "pid": pid,
                        "tid": tid_of(conn or conn0),
                        "args": {"nbytes": nbytes or nb0, "reason": reason}})
        elif ev == swtrace.EV_STAGE:
            out.append({"ph": "X", "name": reason or "stage",
                        "ts": ts - dur * 1e6, "dur": max(0.0, dur * 1e6),
                        "pid": pid, "tid": tid_of(conn), "cat": "stage",
                        "args": {"nbytes": nbytes}})
        else:  # recv_match, conn churn, e2e, clock, anything future
            if e2e_out is not None and ev == swtrace.EV_E2E:
                tcid, _, direction = reason.rpartition(":")
                # "sx"/"sr" are the striped-message markers (DESIGN.md
                # §17): one per message on the primary, ordinal = msg id,
                # so the pair survives chunks landing on many rails.
                if tcid and direction in ("tx", "rx", "sx", "sr"):
                    e2e_out.append((tcid, direction, int(tag), ts,
                                    tid_of(conn), nbytes))
            out.append({"ph": "i", "name": ev, "ts": ts, "pid": pid,
                        "tid": tid_of(conn), "s": "t",
                        "args": {"tag": tag, "nbytes": nbytes,
                                 "reason": reason}})
    # Spans still open at dump time (ops pending when the ring was read).
    for (kind, tag), dq in open_spans.items():
        for ts0, conn0, nb0 in dq:
            out.append({"ph": "i", "name": f"pending {kind} tag={tag:#x}",
                        "ts": ts0, "pid": pid, "tid": tid_of(conn0), "s": "t",
                        "args": {"nbytes": nb0}})
    for tid, name in sorted(tid_label.items()):
        out.append({"ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                    "args": {"name": name}})
    return out


def to_chrome(dumps: Iterable[dict]) -> dict:
    """``{"traceEvents": [...]}`` from ``[{"worker", "events"}, ...]``
    dumps (the shape of swtrace.dump_all() and of flight-recorder files).
    """
    trace_events: list = []
    for pid, dump in enumerate(dumps, start=1):
        trace_events.extend(
            chrome_events(dump.get("worker", f"worker-{pid}"),
                          dump.get("events", []), pid))
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}


def write_chrome(dumps: Iterable[dict], path) -> Path:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(to_chrome(dumps), indent=1))
    return path


# --------------------------------------------------------------- --merge
#
# Cross-process stitching (DESIGN.md §15).  Inputs are per-process dumps;
# each worker's EV_CLOCK samples carry "tcid:offset_us:err_us" (peer ~=
# local + offset) and each data frame left one EV_E2E per end with
# "tcid:tx|rx" and a per-conn wire ordinal, so (tcid, ordinal) pairs the
# two halves of every message with no per-frame wire bytes.


def _normalize_dump(raw: dict, fallback_name: str) -> list:
    """One loaded JSON file -> [{"pid", "worker", "events", "hists"}, ...]
    (``hists``: the §25 swpulse buckets a ring dump / flight dump carries
    next to its events; {} on older dumps)."""
    if "workers" in raw:  # swtrace.write_ring_dump shape
        return [{"pid": raw.get("pid"), "worker": w.get("worker", "worker"),
                 "events": w.get("events", []),
                 "hists": w.get("hists", {})} for w in raw["workers"]]
    if "events" in raw:   # flight-recorder / single-ring shape
        return [{"pid": raw.get("pid"), "worker": raw.get("worker",
                                                          fallback_name),
                 "events": raw["events"], "hists": raw.get("hists", {})}]
    raise ValueError("not a swtrace dump (no 'events' or 'workers' key)")


def _tcid_of(reason: str) -> str:
    return reason.split(":", 1)[0] if ":" in reason else ""


def _clock_deltas(procs: dict) -> tuple[dict, list]:
    """Per-process timeline shift (seconds, onto the first process's
    clock) from the EV_CLOCK sample graph.  Returns (deltas, edges) --
    edges for the summary; processes unreachable through any clock edge
    keep delta 0 (unaligned, better than dropped)."""
    # Best sample per (proc, tcid): smallest error wins.
    samples: dict = {}   # (proc, tcid) -> (off_us, err_us)
    members: dict = {}   # tcid -> set of procs that saw it
    for pkey, workers in procs.items():
        for w in workers:
            for t, ev, tag, conn, nbytes, reason, dur in w["events"]:
                if ev not in (swtrace.EV_CLOCK, swtrace.EV_E2E):
                    continue
                tcid = _tcid_of(reason)
                if not tcid:
                    continue
                members.setdefault(tcid, set()).add(pkey)
                if ev == swtrace.EV_CLOCK:
                    parts = reason.split(":")
                    if len(parts) != 3:
                        continue
                    try:
                        off, err = int(parts[1]), int(parts[2])
                    except ValueError:
                        continue
                    cur = samples.get((pkey, tcid))
                    if cur is None or err < cur[1]:
                        samples[(pkey, tcid)] = (off, err)
    # proc graph: an edge per (sampling proc, peer proc) pair.
    adj: dict = {p: [] for p in procs}
    edges = []
    for (pkey, tcid), (off, err) in samples.items():
        for peer in members.get(tcid, ()):  # the conn's other end
            if peer == pkey:
                continue
            # t_peer ~= t_local + off
            adj[pkey].append((peer, off * 1e-6))
            adj[peer].append((pkey, -off * 1e-6))
            edges.append({"tcid": tcid, "from": str(pkey), "to": str(peer),
                          "offset_us": off, "err_us": err})
    deltas = {p: 0.0 for p in procs}
    seen: set = set()
    for root in procs:  # first process anchors its component
        if root in seen:
            continue
        seen.add(root)
        queue = [root]
        while queue:
            p = queue.pop()
            for q, off in adj.get(p, ()):
                if q in seen:
                    continue
                seen.add(q)
                # An event stamped t on q's clock happened at t - off on
                # p's clock (off = t_q - t_p for one instant).
                deltas[q] = deltas[p] - off
                queue.append(q)
    return deltas, edges


def merge_chrome(named_dumps: list) -> dict:
    """``[(name, raw_dict), ...]`` (one per input file) -> one
    clock-aligned Chrome doc with flow-connected send->recv spans and a
    ``"swscope"`` summary block."""
    procs: dict = {}  # proc key -> [{"pid","worker","events"}, ...]
    for i, (name, raw) in enumerate(named_dumps):
        for w in _normalize_dump(raw, name):
            pkey = w["pid"] if w["pid"] is not None else f"file-{i}"
            procs.setdefault(pkey, []).append(w)
    deltas, edges = _clock_deltas(procs)

    trace_events: list = []
    # tcid -> dir -> worker pid -> {ordinal: (ts_us, tid, nbytes)}.
    # Keyed per END (worker pid) because a bidirectional conn carries an
    # independent ordinal sequence per direction per end: tx ordinal n
    # from end A pairs with rx ordinal n at the OTHER end only.
    e2e: dict = {}
    stage_durs: dict = {}
    pulse: dict = {}  # per-worker §25 percentile view carried through
    pid = 0
    for pkey, workers in procs.items():
        shift = deltas[pkey]
        for w in workers:
            pid += 1
            label = f"{pkey}/{w['worker']}"
            if w.get("hists"):
                pulse[label] = swtrace.hist_summary(w["hists"])
            sink: list = []
            trace_events.extend(
                chrome_events(label, w["events"], pid, ts_shift=shift,
                              e2e_out=sink))
            for tcid, direction, ordinal, ts_us, tid, nbytes in sink:
                e2e.setdefault(tcid, {}).setdefault(direction, {}) \
                   .setdefault(pid, {})[ordinal] = (ts_us, tid, nbytes)
            for t, ev, tag, conn, nbytes, reason, dur in w["events"]:
                if ev == swtrace.EV_STAGE and dur > 0:
                    stage_durs.setdefault(reason, []).append(dur)

    # Flow events: one arrow per (tcid, ordinal) recorded as tx at one
    # end and rx at a different end.
    flow_id = 0
    wire_lat: list = []
    wire_bytes = 0
    for tcid, dirs in sorted(e2e.items()):
      # Stream ordinals pair tx<->rx; striped msg-id ordinals pair the
      # sx<->sr markers -- independent namespaces on the same trace conn.
      for tx_dir, rx_dir in (("tx", "rx"), ("sx", "sr")):
        for tx_pid, txs in sorted(dirs.get(tx_dir, {}).items()):
            rxs: dict = {}  # ordinal -> (ts_us, rx_pid, tid)
            for rx_pid, m in dirs.get(rx_dir, {}).items():
                if rx_pid != tx_pid:  # never pair an end with itself
                    for ordinal, (ts_us, tid, _nb) in m.items():
                        rxs[ordinal] = (ts_us, rx_pid, tid)
            for ordinal, (tx_ts, tx_tid, nbytes) in sorted(txs.items()):
                rx = rxs.get(ordinal)
                if rx is None:
                    continue  # still in flight (or the rx ring wrapped)
                rx_ts, rx_pid, rx_tid = rx
                flow_id += 1
                trace_events.append({"ph": "s", "cat": "swscope",
                                     "name": "e2e", "id": flow_id,
                                     "ts": tx_ts, "pid": tx_pid,
                                     "tid": tx_tid})
                trace_events.append({"ph": "f", "bp": "e", "cat": "swscope",
                                     "name": "e2e", "id": flow_id,
                                     "ts": rx_ts, "pid": rx_pid,
                                     "tid": rx_tid})
                wire_lat.append((rx_ts - tx_ts) * 1e-6)
                wire_bytes += nbytes

    wire_lat.sort()
    summary = {
        "processes": len(procs),
        "clock_edges": edges,
        "pairs": len(wire_lat),
        "bytes_paired": wire_bytes,
        "wire_us": {
            "p50": percentile(wire_lat, 50) * 1e6 if wire_lat else 0.0,
            "p90": percentile(wire_lat, 90) * 1e6 if wire_lat else 0.0,
            "p99": percentile(wire_lat, 99) * 1e6 if wire_lat else 0.0,
        },
        "stage_us": {
            name: {"count": len(xs),
                   "p50": percentile(sorted(xs), 50) * 1e6,
                   "p90": percentile(sorted(xs), 90) * 1e6}
            for name, xs in sorted(stage_durs.items())
        },
        # §25 swpulse: each dump's distributions survive the merge as
        # their per-worker percentile view (hists ride write_ring_dump).
        "pulse": pulse,
    }
    return {"traceEvents": trace_events, "displayTimeUnit": "ms",
            "swscope": summary}


def _print_merge_summary(summary: dict) -> None:
    print(f"[swscope] {summary['processes']} process(es), "
          f"{summary['pairs']} send->recv pair(s), "
          f"{summary['bytes_paired']} payload bytes paired")
    for e in summary["clock_edges"]:
        print(f"  clock {e['from']} -> {e['to']}: offset "
              f"{e['offset_us']}us (+/-{e['err_us']}us) via {e['tcid']}")
    w = summary["wire_us"]
    if summary["pairs"]:
        print(f"  wire (send-done -> recv-done): p50={w['p50']:.1f}us "
              f"p90={w['p90']:.1f}us p99={w['p99']:.1f}us")
    for name, s in summary["stage_us"].items():
        print(f"  stage {name}: n={s['count']} p50={s['p50']:.1f}us "
              f"p90={s['p90']:.1f}us")
    if summary["pairs"] and summary["stage_us"]:
        # The gap between wire time and summed stage medians is the
        # serialization/scheduling slack the §12 pipeline can still hide.
        staged = sum(s["p50"] for s in summary["stage_us"].values())
        print(f"  wire-vs-stage: p50 wire {w['p50']:.1f}us vs "
              f"{staged:.1f}us summed stage p50s")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m starway_tpu.trace",
        description="Convert swtrace dumps to Chrome trace_event JSON "
                    "(open in Perfetto / chrome://tracing).  With --merge, "
                    "stitch per-process ring dumps into ONE clock-aligned "
                    "trace with send->recv flow arrows (swscope).")
    p.add_argument("inputs", nargs="+", type=Path,
                   help="flight-recorder dumps (STARWAY_FLIGHT_DIR) or "
                        "ring dumps (swtrace.write_ring_dump)")
    p.add_argument("-o", "--output", type=Path, default=Path("swtrace.json"))
    p.add_argument("--merge", action="store_true",
                   help="treat inputs as dumps from different processes: "
                        "align clocks via EV_CLOCK samples and connect "
                        "EV_E2E ordinal pairs with Chrome flow events")
    args = p.parse_args(argv)
    named = []
    for path in args.inputs:
        raw = json.loads(path.read_text())
        if "events" not in raw and "workers" not in raw:
            print(f"{path}: not a swtrace dump (no 'events'/'workers' key)",
                  file=sys.stderr)
            return 1
        named.append((path.stem, raw))
    args.output.parent.mkdir(parents=True, exist_ok=True)
    if args.merge:
        doc = merge_chrome(named)
        args.output.write_text(json.dumps(doc, indent=1))
        _print_merge_summary(doc["swscope"])
        n = len(doc["traceEvents"])
        print(f"wrote {args.output} ({n} events from {len(named)} dump(s))")
        return 0
    dumps = []
    for name, raw in named:
        dumps.extend(_normalize_dump(raw, name))
    out = write_chrome(dumps, args.output)
    n = sum(len(d.get("events", [])) for d in dumps)
    print(f"wrote {out} ({n} events from {len(dumps)} dump(s))")
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
