"""swscope telemetry viewer: ``python -m starway_tpu.metrics <path|addr>``.

Renders the sampler's JSONL stream (core/telemetry.py; armed via
``STARWAY_METRICS_INTERVAL`` / ``STARWAY_METRICS_PATH`` /
``STARWAY_METRICS_ADDR``) as a top-like live table: one row per
(worker, conn) with the per-conn gauges, plus per-worker counter rates
computed between consecutive samples.

Sources:

* a **path** -- the ``STARWAY_METRICS_PATH`` JSONL file; followed
  tail -f style (default) or summarized once (``--once``, also the mode
  tests drive).
* an **addr** -- ``host:port`` of a live sampler feed
  (``STARWAY_METRICS_ADDR``); samples render as they arrive.
"""

from __future__ import annotations

import argparse
import json
import re
import socket
import sys
import time
from pathlib import Path
from typing import Iterator, Optional

_ADDR_RE = re.compile(r"^[\w.\-]*:\d+$")

# Counters whose per-second rate is worth a column (the rest are visible
# in evaluate_perf_detail / flight dumps).
_RATE_COUNTERS = ("sends_completed", "recvs_completed", "bytes_tx",
                  "bytes_rx", "sessions_resumed")


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024
    return f"{n:.1f}GiB"


def _fmt_us(n: float) -> str:
    if n < 1000:
        return f"{n:.0f}us"
    if n < 1e6:
        return f"{n / 1e3:.1f}ms"
    return f"{n / 1e6:.1f}s"


def _hist_lines(wk: dict) -> list:
    """swpulse percentile rows (DESIGN.md §25): one line per histogram
    that has samples.  ``hists`` carries the telemetry-sample percentile
    shape (hist_summary); `_us` names render as durations, the rest as
    sizes."""
    lines = []
    for name, h in sorted(wk.get("hists", {}).items()):
        count = int(h.get("count", 0))
        if not count:
            continue
        fmt = _fmt_us if name.endswith("_us") else _fmt_bytes
        lines.append(
            f"    {name}: n={count} " + " ".join(
                f"{p}={fmt(h.get(p, 0))}"
                for p in ("p50", "p90", "p99", "p999")))
    return lines


def render(sample: dict, prev: Optional[dict] = None) -> str:
    """One sample -> a text block (rates need the previous sample)."""
    lines = [time.strftime("%H:%M:%S", time.localtime(sample.get("t", 0)))
             + f"  ({len(sample.get('workers', {}))} worker(s))"]
    dt = 0.0
    if prev is not None:
        dt = float(sample.get("mono", 0)) - float(prev.get("mono", 0))
    for label, wk in sorted(sample.get("workers", {}).items()):
        ctr = wk.get("counters", {})
        parts = [f"  {label}:"]
        if prev is not None and dt > 0:
            pctr = prev.get("workers", {}).get(label, {}).get("counters", {})
            for name in _RATE_COUNTERS:
                rate = (ctr.get(name, 0) - pctr.get(name, 0)) / dt
                if rate:
                    val = (_fmt_bytes(rate) + "/s" if name.startswith("bytes")
                           else f"{rate:.0f}/s")
                    parts.append(f"{name}={val}")
        else:
            for name in _RATE_COUNTERS:
                if ctr.get(name):
                    parts.append(f"{name}={ctr[name]}")
        stalls = ctr.get("stall_alerts", 0)
        if stalls:
            parts.append(f"STALL_ALERTS={stalls}")
        gauges = wk.get("gauges", {})
        posted = gauges.get("posted_recvs", 0)
        if posted:
            parts.append(f"posted_recvs={posted}")
        pool = gauges.get("staging_pool_bytes", 0)
        if pool:
            parts.append(f"staging_pool={_fmt_bytes(pool)}")
        lines.append(" ".join(parts))
        lines.extend(_hist_lines(wk))
        for cid, g in sorted(gauges.get("conns", {}).items(),
                             key=lambda kv: str(kv[0])):
            busy = {k: v for k, v in g.items() if v}
            cols = " ".join(
                f"{k}={_fmt_bytes(v) if 'bytes' in k else v}"
                for k, v in busy.items()) or "idle"
            lines.append(f"    conn {cid}: {cols}")
    return "\n".join(lines)


def _iter_path(path: Path, follow: bool) -> Iterator[dict]:
    with open(path) as f:
        while True:
            line = f.readline()
            if line:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except ValueError:
                    continue
            elif follow:
                # swcheck: allow(blocking-call): viewer CLI tails on its own app thread, no engine in-process
                time.sleep(0.2)
            else:
                return


def _iter_addr(addr: str) -> Iterator[dict]:
    host, _, port = addr.rpartition(":")
    # swcheck: allow(blocking-call): viewer CLI dials the feed on its own app thread and may wait for it
    with socket.create_connection((host or "127.0.0.1", int(port))) as s:
        buf = b""
        while True:
            chunk = s.recv(65536)
            if not chunk:
                return
            buf += chunk
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                if line.strip():
                    try:
                        yield json.loads(line)
                    except ValueError:
                        continue


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m starway_tpu.metrics",
        description="Top-like viewer for swscope telemetry samples "
                    "(STARWAY_METRICS_PATH JSONL, or the live "
                    "STARWAY_METRICS_ADDR feed).")
    p.add_argument("source",
                   help="JSONL sample file, or host:port of a live feed")
    p.add_argument("--once", action="store_true",
                   help="read everything available, print the latest "
                        "sample + run summary, and exit (no follow)")
    args = p.parse_args(argv)

    is_addr = bool(_ADDR_RE.match(args.source))
    if is_addr:
        samples: Iterator[dict] = _iter_addr(args.source)
        follow = not args.once
    else:
        path = Path(args.source)
        if not path.exists():
            print(f"{path}: no such file", file=sys.stderr)
            return 1
        follow = not args.once
        samples = _iter_path(path, follow)

    prev = None
    history: list = []
    try:
        for sample in samples:
            if follow:
                sys.stdout.write("\x1b[2J\x1b[H" + render(sample, prev) + "\n")
                sys.stdout.flush()
            else:
                history.append(sample)
                if is_addr:
                    # A live feed never EOFs: --once means one snapshot.
                    break
            prev = sample
    except KeyboardInterrupt:
        pass
    if args.once:
        if not history:
            print("no samples", file=sys.stderr)
            return 1
        before = history[-2] if len(history) > 1 else None
        print(render(history[-1], before))
        from .core.telemetry import summarize

        summary = summarize(history)
        print(f"-- {len(history)} sample(s); peak tx depth "
              f"{summary['peak_tx_queue_depth']}, peak journal "
              f"{_fmt_bytes(summary['peak_journal_bytes'])}")
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
