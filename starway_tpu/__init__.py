"""starway-tpu: TPU-native asynchronous point-to-point communication.

A brand-new implementation of the capabilities of the reference library
``Clouder0/starway`` (an asyncio tag-matched P2P layer over OpenUCX), built
for the TPU stack instead: host tag matching + event-driven engines replace
UCX workers, ``jax.Array`` HBM buffers ride an in-process/ICI device plane,
and TCP carries the cross-process (DCN-adjacent) bootstrap path.

Public surface mirrors the reference (src/starway/__init__.py:351-358):

>>> import starway_tpu as sw
>>> server = sw.Server(); server.listen("127.0.0.1", 13337)
>>> client = sw.Client(); await client.aconnect("127.0.0.1", 13337)
>>> await client.asend(np.arange(16, dtype=np.uint8), tag=7)
"""

from __future__ import annotations

from .api import Client, Server
from .core.endpoint import ServerEndpoint
from .device import DeviceBuffer

__version__ = "0.1.0"


def check_sys_libs() -> str:
    """Report which engine implementation is active.

    The reference's analogue reports system-vs-wheel libucx
    (src/starway/__init__.py:63-65).  There is no UCX here; instead this
    returns ``"native"`` when the C++ engine extension is loaded and
    ``"python"`` for the pure-Python engine.
    """
    from .api import _use_native_engine

    return "native" if _use_native_engine() else "python"


def list_benchmark_scenarios() -> list[str]:
    from .benchmarks import list_scenarios

    return list_scenarios()


__all__ = [
    "Server",
    "Client",
    "ServerEndpoint",
    "DeviceBuffer",
    "check_sys_libs",
    "list_benchmark_scenarios",
]
