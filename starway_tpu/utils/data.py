"""Token-stream batching for the training loop.

The reference moves opaque buffers and has no data story; this build ships
trainers, so it ships the minimal input pipeline they need: a deterministic,
epoch-shuffled sampler of next-token windows over one flat token array.
Memmap-friendly — pass ``np.memmap`` (or use :func:`load_tokens`) and only
the touched windows are read from disk; batches come out as host
``np.ndarray`` so the caller controls device placement/sharding
(``jax.device_put`` with a dp/fsdp NamedSharding).

>>> tokens = load_tokens("corpus.bin", dtype=np.uint16)
>>> for batch in TokenBatcher(tokens, batch_size=8, seq_len=1024, seed=0):
...     loss = trainer.step_sync(jax.device_put(batch, sharding))
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterator, Optional

import numpy as np


def load_tokens(path: str, dtype=None) -> np.ndarray:
    """Memmap a flat token file: ``.npy`` (dtype from the header) or raw
    binary (``dtype`` required, e.g. ``np.uint16`` for GPT-2 BPE ids)."""
    p = Path(path)
    if p.suffix == ".npy":
        arr = np.load(p, mmap_mode="r")
        if dtype is not None and np.dtype(dtype) != arr.dtype:
            raise ValueError(
                f"{p} holds {arr.dtype} tokens, caller asked for "
                f"{np.dtype(dtype)}")
        return arr
    if dtype is None:
        raise ValueError(f"raw token file {p} needs an explicit dtype")
    return np.memmap(p, dtype=dtype, mode="r")


class TokenBatcher:
    """Deterministic epoch-shuffled ``[batch_size, seq_len + 1]`` windows.

    The stream is cut into non-overlapping windows of ``seq_len + 1``
    tokens (input + shifted target share the window, the convention
    ``loss_fn`` expects); each epoch visits every window exactly once in a
    seed-derived order (epoch folded into the seed, so order differs per
    epoch but is reproducible).  A trailing partial window is dropped, and
    the final partial batch of an epoch is dropped too — static shapes, no
    recompiles.

    ``epochs=None`` iterates forever; ``state``/``restore`` round-trip the
    cursor for checkpoint/resume alignment.
    """

    def __init__(self, tokens, batch_size: int, seq_len: int, *,
                 seed: int = 0, epochs: Optional[int] = None):
        if len(tokens) < seq_len + 1:
            raise ValueError(
                f"stream of {len(tokens)} tokens is shorter than one "
                f"window ({seq_len + 1})")
        self.tokens = tokens
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.seed = seed
        self.epochs = epochs
        self.n_windows = len(tokens) // (seq_len + 1)
        self.batches_per_epoch = self.n_windows // batch_size
        if self.batches_per_epoch == 0:
            raise ValueError(
                f"{self.n_windows} windows cannot fill one batch of "
                f"{batch_size}")
        self._epoch = 0
        self._batch = 0
        self._active = False

    # ------------------------------------------------------------ resume
    def state(self) -> dict:
        """Cursor + the geometry it is only valid against."""
        return {"epoch": self._epoch, "batch": self._batch,
                "seed": self.seed, "batch_size": self.batch_size,
                "seq_len": self.seq_len, "n_windows": self.n_windows}

    def restore(self, state: dict) -> None:
        """Resume from :meth:`state`; refuses a cursor whose geometry does
        not match this batcher (a changed batch size / sequence length /
        corpus would silently misalign which windows get visited)."""
        for key in ("seed", "batch_size", "seq_len", "n_windows"):
            if key in state and state[key] != getattr(self, key):
                raise ValueError(
                    f"batcher state mismatch: saved {key}={state[key]}, "
                    f"this batcher has {getattr(self, key)}")
        self._epoch = int(state["epoch"])
        self._batch = int(state["batch"])

    # ---------------------------------------------------------- iterate
    def _order(self, epoch: int) -> np.ndarray:
        rng = np.random.default_rng((self.seed, epoch))
        return rng.permutation(self.n_windows)

    def reset(self) -> None:
        """Rewind to epoch 0 (re-iterating an epochs-bounded batcher).
        Refuses while an iterator is live — resetting the shared cursor
        under a running loop would silently rewind it."""
        if self._active:
            raise RuntimeError(
                "TokenBatcher.reset() with a live iterator; close it first")
        self._epoch = 0
        self._batch = 0

    def __iter__(self) -> "_BatcherIter":
        # The cursor is instance state (that is what makes state()/restore()
        # resume work), so iteration is single-consumer: a second live
        # iterator would silently interleave, and an exhausted bounded
        # batcher would silently yield nothing — both fail loudly instead.
        # The active mark is taken HERE, not at first next(), so two
        # iterators created back-to-back cannot both slip past the check;
        # the wrapper releases it on close/GC even if never advanced (a
        # bare generator's finally would not run in that case).
        if self.epochs is not None and self._epoch >= self.epochs:
            raise RuntimeError(
                "TokenBatcher exhausted; call reset() to re-iterate")
        if self._active:
            raise RuntimeError(
                "TokenBatcher supports one active iterator (the resume "
                "cursor is shared instance state)")
        self._active = True
        return _BatcherIter(self)

    def _gen(self) -> Iterator[np.ndarray]:
        w = self.seq_len + 1
        while self.epochs is None or self._epoch < self.epochs:
            order = self._order(self._epoch)
            while self._batch < self.batches_per_epoch:
                idx = order[self._batch * self.batch_size:
                            (self._batch + 1) * self.batch_size]
                batch = np.stack(
                    [np.asarray(self.tokens[i * w:(i + 1) * w]) for i in idx])
                self._batch += 1
                yield batch.astype(np.int32)
            self._batch = 0
            self._epoch += 1


class _BatcherIter:
    """Iterator handle owning the batcher's active mark: released on
    exhaustion, close(), or garbage collection — including before the
    first ``next()``."""

    __slots__ = ("_owner", "_gen")

    def __init__(self, owner: TokenBatcher):
        self._owner = owner
        self._gen = owner._gen()

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self._gen)
        except BaseException:
            self._release()
            raise

    def close(self) -> None:
        self._gen.close()
        self._release()

    __del__ = close

    def _release(self) -> None:
        if self._owner is not None:
            self._owner._active = False
            self._owner = None
