"""Checkpoint / resume for model + optimizer pytrees.

The reference has no checkpoint subsystem (SURVEY.md section 5: "Absent
entirely" -- its large-array scenario only *simulates* checkpoint traffic).
This build ships models, so it ships checkpointing: orbax-backed when
available (sharding-aware, async-capable), with a plain ``.npz`` fallback
that round-trips any pytree of arrays on hosts without orbax.

Every checkpoint carries a ``manifest.json`` recording the backend that
wrote it plus the leaf structure (count, shapes, dtypes).  Restore
dispatches on the recorded backend -- never on file-existence guessing --
and validates the caller's ``like`` tree against the manifest, so a shape
or structure mismatch fails loudly instead of silently casting garbage.

>>> save_pytree("/ckpt/step1000", {"params": params, "opt": opt_state})
>>> restored = restore_pytree("/ckpt/step1000", like={"params": params, "opt": opt_state})
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any

_MANIFEST = "manifest.json"


def _have_orbax() -> bool:
    try:
        import orbax.checkpoint  # noqa: F401

        return True
    except Exception:
        return False


def _leaf_specs(leaves) -> list[dict]:
    import numpy as np

    def dtype_of(x):
        # No np.asarray fallback unless needed: materialising every leaf on
        # the host would double save cost and break on multi-host shardings.
        return str(x.dtype) if hasattr(x, "dtype") else str(np.asarray(x).dtype)

    return [{"shape": list(np.shape(x)), "dtype": dtype_of(x)} for x in leaves]


def save_pytree(path: str, tree: Any) -> str:
    """Persist a pytree of arrays; returns the backend used."""
    import jax

    p = Path(path)
    p.mkdir(parents=True, exist_ok=True)
    leaves, _ = jax.tree_util.tree_flatten(tree)
    backend = "orbax" if _have_orbax() else "npz"
    if backend == "orbax":
        import orbax.checkpoint as ocp

        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save((p / "tree").absolute(), tree, force=True)
    else:
        import numpy as np

        np.savez(p / "leaves.npz", **{str(i): np.asarray(x) for i, x in enumerate(leaves)})
    # Manifest last and atomically: its presence marks a complete checkpoint.
    import os

    tmp = p / (_MANIFEST + ".tmp")
    tmp.write_text(json.dumps(
        {"backend": backend, "n": len(leaves), "leaves": _leaf_specs(leaves)}
    ))
    os.replace(tmp, p / _MANIFEST)
    return backend


def _validate(manifest: dict, leaves, path: Path) -> None:
    import numpy as np

    specs = manifest.get("leaves")
    if manifest.get("n") != len(leaves):
        raise ValueError(
            f"checkpoint {path}: structure mismatch -- holds "
            f"{manifest.get('n')} leaves, 'like' tree has {len(leaves)}"
        )
    if not specs:
        return  # older manifest without per-leaf specs
    for i, (spec, leaf) in enumerate(zip(specs, leaves)):
        want = tuple(spec["shape"])
        got = tuple(np.shape(leaf))
        if want != got:
            raise ValueError(
                f"checkpoint {path}: leaf {i} shape mismatch -- "
                f"checkpoint has {want}, 'like' tree has {got}"
            )


def restore_pytree(path: str, like: Any) -> Any:
    """Restore a pytree saved by :func:`save_pytree`, shaped like ``like``.

    Validates leaf count and shapes against the manifest; dtypes are cast
    to the ``like`` tree's dtypes (the documented way to restore e.g. a
    bf16 training checkpoint into f32 eval params).  Leaves whose ``like``
    counterpart is a sharded ``jax.Array`` are placed onto that sharding —
    a ZeRO/GSPMD training state resumes 1/N per device, not replicated on
    the default device.
    """
    import jax

    p = Path(path)
    leaves, treedef = jax.tree_util.tree_flatten(like)
    mf_path = p / _MANIFEST
    if mf_path.exists():
        manifest = json.loads(mf_path.read_text())
        _validate(manifest, leaves, p)
        backend = manifest["backend"]
    else:
        # Pre-manifest layout (round-1 checkpoints): npz marker file or a
        # bare orbax directory.
        backend = "npz" if (p / "leaves.npz").exists() else "orbax"
    if backend == "orbax":
        if not _have_orbax():
            raise RuntimeError(
                f"checkpoint {p} was written by orbax, which is not importable here"
            )
        import orbax.checkpoint as ocp

        ckptr = ocp.PyTreeCheckpointer()
        target = p / "tree" if (p / "tree").exists() else p
        out = ckptr.restore(target.absolute(), item=like)
        return jax.tree_util.tree_map(_placed_like, out, like)
    import numpy as np

    data = np.load(p / "leaves.npz")
    if len(data.files) != len(leaves):
        raise ValueError(
            f"checkpoint {p}: holds {len(data.files)} leaves, "
            f"'like' tree has {len(leaves)}"
        )
    restored = [_placed_like(data[str(i)], leaf) for i, leaf in enumerate(leaves)]
    return jax.tree_util.tree_unflatten(treedef, restored)


def _placed_like(x, like_leaf):
    """Cast to ``like_leaf``'s dtype and, when it is a sharded jax.Array,
    place the restored value onto the same sharding (both backends honour
    the documented dtype contract; orbax returns saved dtypes, npz returns
    host arrays).  The cast happens on the HOST so a sharded leaf never
    transits the default device whole — restoring a ZeRO state whose full
    size exceeds one device's HBM must not allocate full-size scratch."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    if not hasattr(like_leaf, "dtype"):
        return x
    sharding = getattr(like_leaf, "sharding", None)
    if sharding is not None:
        host = np.asarray(x).astype(like_leaf.dtype)
        return jax.device_put(host, sharding)
    return jnp.asarray(x).astype(like_leaf.dtype)
