"""Checkpoint / resume for model + optimizer pytrees.

The reference has no checkpoint subsystem (SURVEY.md section 5: "Absent
entirely" -- its large-array scenario only *simulates* checkpoint traffic).
This build ships models, so it ships checkpointing: orbax-backed when
available (sharding-aware, async-capable), with a plain ``.npz`` fallback
that round-trips any pytree of arrays on hosts without orbax.

>>> save_pytree("/ckpt/step1000", {"params": params, "opt": opt_state})
>>> restored = restore_pytree("/ckpt/step1000", like={"params": params, "opt": opt_state})
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any


def _have_orbax() -> bool:
    try:
        import orbax.checkpoint  # noqa: F401

        return True
    except Exception:
        return False


def save_pytree(path: str, tree: Any) -> str:
    """Persist a pytree of arrays; returns the backend used."""
    p = Path(path)
    if _have_orbax():
        import orbax.checkpoint as ocp

        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(p.absolute(), tree, force=True)
        return "orbax"
    import numpy as np
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    p.mkdir(parents=True, exist_ok=True)
    np.savez(p / "leaves.npz", **{str(i): np.asarray(x) for i, x in enumerate(leaves)})
    (p / "treedef.json").write_text(json.dumps({"n": len(leaves)}))
    return "npz"


def restore_pytree(path: str, like: Any) -> Any:
    """Restore a pytree saved by :func:`save_pytree`, shaped like ``like``."""
    p = Path(path)
    if _have_orbax() and not (p / "leaves.npz").exists():
        import orbax.checkpoint as ocp

        ckptr = ocp.PyTreeCheckpointer()
        return ckptr.restore(p.absolute(), item=like)
    import numpy as np
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree_util.tree_flatten(like)
    data = np.load(p / "leaves.npz")
    restored = [
        jnp.asarray(data[str(i)]).astype(leaf.dtype)
        for i, leaf in enumerate(leaves)
    ]
    return jax.tree_util.tree_unflatten(treedef, restored)
