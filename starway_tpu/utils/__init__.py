"""Utilities: tracing/telemetry helpers."""

from .trace import OpTimer, trace_span, profile_to

__all__ = ["OpTimer", "trace_span", "profile_to"]
