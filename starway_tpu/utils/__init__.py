"""Utilities: tracing/telemetry helpers, checkpointing, data batching."""

from .data import TokenBatcher, load_tokens
from .trace import OpTimer, trace_span, profile_to

__all__ = ["OpTimer", "trace_span", "profile_to", "TokenBatcher", "load_tokens"]
