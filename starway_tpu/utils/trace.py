"""Tracing / telemetry.

The reference's observability is thin by design (SURVEY.md section 5):
``debug_print`` compiled out in release, ``evaluate_perf`` transport
estimates, and per-iteration benchmark samples.  The TPU build keeps the
same shape and adds the two tools that matter on this stack:

* :func:`trace_span` / :func:`profile_to` -- ``jax.profiler`` integration:
  annotate host-side phases so they show up alongside device traces in
  Perfetto/TensorBoard.
* :class:`OpTimer` -- a tiny host-side span recorder for the comm runtime
  (p50/p95/mean summaries, the same metric vocabulary as the bench suite).
"""

from __future__ import annotations

import contextlib
import statistics
import time
from collections import defaultdict
from typing import Iterator


@contextlib.contextmanager
def trace_span(name: str) -> Iterator[None]:
    """Wall-clock span that also annotates the jax profiler timeline when a
    trace is active (no-op overhead otherwise)."""
    try:
        import jax.profiler as _prof

        ctx = _prof.TraceAnnotation(name)
    except Exception:
        ctx = contextlib.nullcontext()
    with ctx:
        yield


@contextlib.contextmanager
def profile_to(log_dir: str) -> Iterator[None]:
    """Capture a jax profiler trace (device + annotated host spans) into
    ``log_dir`` for TensorBoard / Perfetto."""
    import jax.profiler as _prof

    _prof.start_trace(log_dir)
    try:
        yield
    finally:
        _prof.stop_trace()


class OpTimer:
    """Accumulates named durations; summarises like the bench metrics."""

    def __init__(self) -> None:
        self._samples: dict[str, list[float]] = defaultdict(list)

    @contextlib.contextmanager
    def span(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._samples[name].append(time.perf_counter() - t0)

    def record(self, name: str, seconds: float) -> None:
        self._samples[name].append(seconds)

    def summary(self) -> dict[str, dict[str, float]]:
        out: dict[str, dict[str, float]] = {}
        for name, xs in self._samples.items():
            if not xs:
                continue
            s = sorted(xs)
            out[name] = {
                "count": float(len(s)),
                "mean_us": statistics.fmean(s) * 1e6,
                "p50_us": s[len(s) // 2] * 1e6,
                "p95_us": s[min(len(s) - 1, int(len(s) * 0.95))] * 1e6,
                "total_s": sum(s),
            }
        return out
