"""Device data plane: jax.Array payloads over the fabric.

This is the TPU-native replacement for the reference's zero-copy RDMA into
preallocated NumPy buffers (reference: src/bindings/main.hpp:155-161 captures
raw host pointers; BASELINE.json north star: "asend/arecv/aflush async
primitives operate on jax.Array device buffers in HBM").

Three transfer paths, chosen per connection:

* **in-process, device payload -> device sink**: the sender hands the
  ``jax.Array`` itself to the receiver's matcher; the receiver materialises
  it on its target device with ``jax.device_put`` -- on TPU hardware with
  both devices in the same process this is an HBM-to-HBM copy over ICI with
  zero host staging.  (Same-device delivery is a reference handoff.)
* **in-process, mixed host/device**: one host copy at the boundary
  (``np.asarray`` of the payload, or ``device_put`` of the staged bytes).
* **cross-process (TCP / DCN bootstrap path)**: payload bytes are staged to
  host, streamed, and re-materialised on the receiver's device.  Real
  cross-host device DMA (jax.transfer-style) can slot in behind the same
  sink protocol when available.

The tag matcher stays byte-oriented; device awareness enters through two
small duck-typed protocols (no jax import in the core):

* :class:`DevicePayload` -- wraps an array for sending (``nbytes``,
  ``as_host_view()``, ``.array``).
* :class:`DeviceRecvSink` -- wraps a :class:`DeviceBuffer` for receiving
  (``nbytes``, ``host_staging()``, ``finalize_from_host()``,
  ``accept_device()``).
"""

from __future__ import annotations

import logging
import sys
import threading
import time
from typing import Optional

logger = logging.getLogger("starway_tpu")


def _record_stage(name: str, seconds: float, nbytes: int, scope=None) -> None:
    from . import perf

    perf.record_stage(name, seconds, nbytes, scope)


def _np_dtype(dtype):
    """Normalise numpy / jax.numpy scalar types / strings to np.dtype
    (ml_dtypes like bfloat16 included -- by NAME too, which np.dtype
    alone rejects; reshard/api.py round-trips dtypes as strings)."""
    import numpy as np

    d = getattr(dtype, "dtype", None)
    if isinstance(d, np.dtype):
        return d
    try:
        return np.dtype(dtype)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, str(dtype)))


# --------------------------------------------------------------- fast copy
#
# Device-to-device transfer via the PJRT copy entry point directly
# (xla_client.batched_copy_array_to_devices_with_sharding), skipping
# jax.device_put's per-call Python dispatch (~100 us on this host).  This is
# the framework's data-plane edge over a hand-written device_put loop: the
# per-target plumbing (sharding, device list) is resolved once per sink and
# cached.  Private API -> probed once, with jax.device_put as the fallback.

_fast_copy_state = None  # None = unprobed, False = unavailable, else (xc, sem)


def _fast_copy_setup():
    global _fast_copy_state
    if _fast_copy_state is None:
        try:
            from jax._src.lib import xla_client as xc

            sem = xc.ArrayCopySemantics.ALWAYS_COPY
            _fast_copy_state = (xc.batched_copy_array_to_devices_with_sharding, sem)
        except Exception:
            _fast_copy_state = False
    return _fast_copy_state


def _copy_to_device(array, device, plan_cache):
    """Copy ``array`` onto ``device``; ``plan_cache`` is a one-slot list the
    caller owns (per-sink), holding the resolved (copy_fn, device_list,
    sharding, semantics) plan."""
    import jax

    plan = plan_cache[0]
    if plan is None:
        fast = _fast_copy_setup()
        if fast:
            try:
                from jax.sharding import SingleDeviceSharding

                copy_fn, sem = fast
                sharding = SingleDeviceSharding(device)
                plan = (copy_fn, sharding._internal_device_list, sharding, sem)
            except Exception:
                plan = False
        else:
            plan = False
        plan_cache[0] = plan
    if plan:
        copy_fn, dev_list, sharding, sem = plan
        try:
            return copy_fn([array], [dev_list], [sharding], [sem])[0]
        except (TypeError, AttributeError):
            # Drift-shaped error (signature/symbol changed): this plan will
            # never work, stop retrying for this sink.
            plan_cache[0] = False
            logger.warning(
                "PJRT fast-copy entry point unusable; falling back to "
                "jax.device_put for this sink", exc_info=True,
            )
        # Anything else (e.g. transient allocator pressure) falls through to
        # device_put for THIS transfer only; the plan stays cached.
    return jax.device_put(array, device)


# ------------------------------------------------------------- fast H2D
#
# Receive-side twin of the fast copy above: host placement goes through the
# PJRT client's buffer_from_pyval entry point, which performs exactly ONE
# host-to-device copy (force_copy=True: the result never aliases the source,
# so staging buffers are immediately reusable) and skips jax.device_put's
# per-call Python dispatch.  Private API -> probed once, device_put fallback.

_fast_h2d_state = None  # None = unprobed, False = unavailable, else semantics


def _fast_h2d(np_arr, device):
    """One-copy H2D of ``np_arr`` onto ``device`` via PJRT, or None when the
    entry point is unavailable (caller falls back to jax.device_put).
    ``device`` must be concrete: with no target device the caller's
    device_put fallback is what honours jax's default-device context.

    IMMUTABLE_ONLY_DURING_CALL is load-bearing: the runtime must finish
    reading the source buffer *during* the call (a synchronous staging
    copy), so the caller may recycle a pooled staging buffer the moment
    this returns.  The laxer default semantics allow the DMA to keep
    reading the host buffer asynchronously after return, which would
    corrupt a recycled buffer's previous delivery on real accelerators."""
    global _fast_h2d_state
    if _fast_h2d_state is False or device is None:
        return None
    if _fast_h2d_state is None:
        try:
            from jax._src.lib import xla_client as xc

            _fast_h2d_state = xc.HostBufferSemantics.IMMUTABLE_ONLY_DURING_CALL
        except Exception:
            _fast_h2d_state = False
            return None
    try:
        return device.client.buffer_from_pyval(
            np_arr, device, force_copy=True,
            host_buffer_semantics=_fast_h2d_state)
    except (TypeError, AttributeError):
        # Drift-shaped failure (signature/symbol changed): this entry
        # point will never work here -- stop retrying for the process.
        _fast_h2d_state = False
        logger.warning(
            "PJRT buffer_from_pyval unusable; falling back to "
            "jax.device_put for host placement", exc_info=True)
        return None
    except Exception:
        # Anything else (transient allocator pressure, one exotic payload
        # PJRT rejects): fall back for THIS transfer only; the fast path
        # stays available.
        return None


# ------------------------------------------------------- staging buffer pool
#
# Host staging buffers for streamed (TCP/sm) device payloads are reused
# across transfers instead of np.empty'd per transfer: first-touch page
# faults on a fresh multi-MiB buffer cost more than the memcpy it serves.
# Exact-size buckets (transfer sizes repeat in steady-state workloads),
# bounded total bytes.  A buffer is recycled ONLY when placement provably
# copied out of it (_fast_h2d force_copy); the jax.device_put fallback may
# zero-copy-alias host memory on CPU targets, and an aliased buffer must
# never be handed to the next transfer.


class _StagingPool:
    def __init__(self, cap_bytes: int = 64 << 20):
        self._lock = threading.Lock()
        self._buckets: dict[int, list] = {}
        self._held = 0
        self._cap = cap_bytes
        self.hits = 0
        self.misses = 0

    def get(self, nbytes: int):
        import numpy as np

        from .core import swtrace

        with self._lock:
            bucket = self._buckets.get(nbytes)
            if bucket:
                self._held -= nbytes
                self.hits += 1
                swtrace.GLOBAL.staging_hits += 1
                return bucket.pop()
            self.misses += 1
            swtrace.GLOBAL.staging_misses += 1
        return np.empty(nbytes, dtype=np.uint8)

    def put(self, arr) -> None:
        n = int(arr.nbytes)
        with self._lock:
            if self._held + n > self._cap:
                return  # dropped: the pool stays bounded
            self._buckets.setdefault(n, []).append(arr)
            self._held += n


_staging_pool = _StagingPool()


def _rx_overlap_ok(device) -> bool:
    """Chunked receive placement (async H2D per completed chunk + one
    device-side concatenate) only pays on accelerator targets where the
    DMA genuinely overlaps the remaining stream reads; on CPU the
    concatenate costs more than it hides.  Module-level so tests can
    force the path on the virtual CPU mesh."""
    return device is not None and getattr(device, "platform", "cpu") != "cpu"


_jax_array_type = None


def is_device_payload(buffer) -> bool:
    global _jax_array_type
    if isinstance(buffer, DeviceBuffer):
        return True
    if _jax_array_type is None:
        jax = sys.modules.get("jax")
        if jax is None:
            return False
        try:
            _jax_array_type = jax.Array
        except Exception:
            return False
    return isinstance(buffer, _jax_array_type)


class DeviceBuffer:
    """Mutable holder for a receive target living in device memory.

    jax.Arrays are immutable, so "receive into a preallocated device buffer"
    means: the framework materialises the received payload as a jax.Array on
    ``device`` and swaps it into ``.array``.  The previous array (if any) is
    dropped, letting XLA reuse its HBM.

    >>> sink = DeviceBuffer((1024,), jnp.bfloat16, device=jax.devices()[1])
    >>> tag, length = await server.arecv(sink, tag=7, tag_mask=MASK)
    >>> sink.array  # received payload, resident on devices()[1]
    """

    def __init__(self, shape, dtype, device=None, array=None):
        self.shape = tuple(int(s) for s in shape)
        self.dtype = _np_dtype(dtype)
        self.device = device
        self.array = array
        self._plan = [None]  # resolved copy plan, see _copy_to_device
        # How the last receive landed: "device" (array handoff -- inproc or
        # PJRT pull) or "staged" (bytes streamed through host staging).
        self.last_transport = None

    @classmethod
    def like(cls, array, device=None) -> "DeviceBuffer":
        """A sink shaped like ``array``, targeting ``device`` (default: the
        device ``array`` lives on)."""
        dev = device
        if dev is None:
            devs = getattr(array, "devices", None)
            if callable(devs):
                ds = devs()
                dev = next(iter(ds)) if ds else None
        return cls(array.shape, array.dtype, device=dev)

    @property
    def nbytes(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n * self.dtype.itemsize


class DevicePayload:
    """Send-side wrapper: a jax.Array plus a lazily-created host view.

    Two staging modes feed the framed stream:

    * ``as_host_view()`` -- one full-payload D2H (the in-process delivery
      path, and the fallback for engines without chunked TX support).
    * ``chunked(chunk_bytes)`` + ``host_chunk(pos)`` -- incremental D2H:
      the TX pump asks for the chunk containing byte ``pos`` and the
      payload kicks off the async device-to-host copy of the NEXT chunk
      before returning, so staging chunk k+1 overlaps the transport write
      of chunk k (DESIGN.md §12).  The duck protocol core/conn.py sees is
      just ``nbytes`` + ``host_chunk``.
    """

    __slots__ = ("array", "nbytes", "scope", "_host_view", "_flat",
                 "_chunk_elems", "_chunk_b", "_dev_chunks", "_host_chunks")

    def __init__(self, array):
        self.array = array
        self.nbytes = int(array.nbytes)
        self.scope = None  # owning worker's perf.StageScope (send_device)
        self._host_view: Optional[memoryview] = None
        self._flat = None  # chunked mode state (see chunked())
        self._chunk_elems = 0
        self._chunk_b = 0
        self._dev_chunks: Optional[dict] = None
        self._host_chunks: Optional[dict] = None

    def as_host_view(self) -> memoryview:
        if self._host_view is None:
            import numpy as np

            t0 = time.perf_counter()
            host = np.ascontiguousarray(np.asarray(self.array))
            # view(uint8) first: extension dtypes (ml_dtypes bfloat16 et
            # al) have no buffer-protocol format char, so memoryview()
            # on the raw array raises for exactly the payloads TPU work
            # ships most.
            self._host_view = memoryview(host.view(np.uint8)).cast("B")
            _record_stage("stage", time.perf_counter() - t0, self.nbytes,
                          self.scope)
        return self._host_view

    # ------------------------------------------------------- chunked D2H
    def chunked(self, chunk_bytes: int) -> Optional["DevicePayload"]:
        """Arm incremental staging, or None when it cannot help (payload
        smaller than two chunks, pipelining disabled, or the array refuses
        the flat view).  Arming prefetches chunk 0 so its D2H runs while
        the message header is still being written."""
        if chunk_bytes <= 0 or self.nbytes < 2 * chunk_bytes:
            return None
        try:
            flat = self.array.reshape(-1)
            itemsize = _np_dtype(flat.dtype).itemsize
            elems = chunk_bytes // itemsize
            if elems <= 0 or self.nbytes < 2 * elems * itemsize:
                return None
            self._flat = flat
            self._chunk_elems = elems
            self._chunk_b = elems * itemsize
            self._dev_chunks = {}
            self._host_chunks = {}
            self._prefetch(0)
        except Exception:
            logger.debug("chunked staging unavailable for this payload",
                         exc_info=True)
            return None
        return self

    def _prefetch(self, k: int) -> None:
        """Start the async D2H of chunk ``k`` (device-side slice +
        copy_to_host_async); no-op past the end or when already started."""
        if k * self._chunk_b >= self.nbytes or k in self._dev_chunks:
            return
        if self._host_chunks is not None and k in self._host_chunks:
            return
        sl = self._flat[k * self._chunk_elems:(k + 1) * self._chunk_elems]
        try:
            sl.copy_to_host_async()
        except Exception:
            pass  # best-effort: np.asarray below still blocks correctly
        self._dev_chunks[k] = sl

    def host_chunk(self, pos: int) -> tuple[int, memoryview]:
        """(chunk_start, host_view) for the chunk containing byte ``pos``,
        prefetching the following chunk before materialising this one."""
        import numpy as np

        k = pos // self._chunk_b
        self._prefetch(k)
        self._prefetch(k + 1)
        view = self._host_chunks.get(k)
        if view is None:
            t0 = time.perf_counter()
            host = np.ascontiguousarray(np.asarray(self._dev_chunks.pop(k)))
            view = memoryview(host).cast("B")
            _record_stage("stage", time.perf_counter() - t0, len(view),
                          self.scope)
            self._host_chunks[k] = view
            # The pump only moves forward: chunk k-1 is fully on the wire.
            self._host_chunks.pop(k - 1, None)
        return k * self._chunk_b, view


class DeviceRecvSink:
    """Receive-side adapter bridging the byte matcher to a DeviceBuffer.

    Streamed (TCP/sm) payloads land in a pooled host staging buffer; on
    accelerator targets the conn's RX pump reports progress via
    :meth:`staged` and every completed chunk starts its async H2D while
    later chunks are still on the wire, with one device-side concatenate
    at :meth:`finalize_from_host` (DESIGN.md §12)."""

    __slots__ = ("devbuf", "scope", "_staging", "_staging_view",
                 "_chunk_elems", "_chunk_b", "_placed", "_recyclable")

    def __init__(self, devbuf: DeviceBuffer):
        self.devbuf = devbuf
        self.scope = None  # owning worker's perf.StageScope (post_device_recv)
        self._staging = None
        self._staging_view: Optional[memoryview] = None
        self._chunk_elems = 0  # >0 = chunked placement armed
        self._chunk_b = 0
        self._placed: Optional[list] = None
        self._recyclable = True

    @property
    def nbytes(self) -> int:
        return self.devbuf.nbytes

    def host_staging(self) -> memoryview:
        """Host bounce buffer for streamed (TCP) payloads (pooled)."""
        if self._staging_view is None:
            from . import config

            self._staging = _staging_pool.get(self.nbytes)
            self._staging_view = memoryview(self._staging).cast("B")
            chunk = config.chunk_bytes()
            itemsize = self.devbuf.dtype.itemsize
            elems = chunk // itemsize if chunk > 0 else 0
            if (elems > 0 and self.nbytes >= 2 * elems * itemsize
                    and _rx_overlap_ok(self.devbuf.device)):
                self._chunk_elems = elems
                self._chunk_b = elems * itemsize
                self._placed = []
        return self._staging_view

    def staged(self, received: int) -> None:
        """RX progress hook (engine thread): start the async H2D of every
        fully-arrived chunk.  No-op unless chunked placement is armed.

        Chunked placement is purely an overlap optimisation -- the staging
        buffer receives every byte regardless -- so any failure here (or in
        the finalize assemble) disarms it and the transfer falls back to
        one full-buffer placement instead of killing the engine thread."""
        if not self._chunk_b:
            return
        try:
            while (len(self._placed) + 1) * self._chunk_b <= received:
                off = len(self._placed) * self._chunk_b
                self._place_chunk(off, self._chunk_b)
        except Exception:
            logger.warning("chunked H2D placement failed; falling back to "
                           "full-buffer placement", exc_info=True)
            self._disarm_chunks()

    def _disarm_chunks(self) -> None:
        self._chunk_elems = self._chunk_b = 0
        self._placed = None

    def _place_chunk(self, off: int, nbytes: int) -> None:
        import jax

        t0 = time.perf_counter()
        arr = self._staging[off:off + nbytes].view(self.devbuf.dtype)
        placed = _fast_h2d(arr, self.devbuf.device)
        if placed is None:
            # Fallback may zero-copy-alias the staging buffer (CPU): the
            # buffer then belongs to the delivered array, not the pool.
            self._recyclable = False
            placed = (jax.device_put(arr, self.devbuf.device)
                      if self.devbuf.device is not None else jax.device_put(arr))
        self._placed.append(placed)
        _record_stage("place", time.perf_counter() - t0, nbytes, self.scope)

    def finalize_from_host(self, length: int) -> None:
        """Staged bytes fully arrived: view as dtype/shape, place on device."""
        import numpy as np

        assembled = False
        if self._placed:
            try:
                self._finalize_chunked(length)
                assembled = True
            except Exception:
                logger.warning("chunked H2D assemble failed; falling back "
                               "to full-buffer placement", exc_info=True)
                self._disarm_chunks()
        if not assembled:
            self._place(np.asarray(self._staging[:length]), length)
        if self._recyclable and self._staging is not None:
            _staging_pool.put(self._staging)
        self._staging = None
        self._staging_view = None
        self._disarm_chunks()
        self._recyclable = True

    def _finalize_chunked(self, length: int) -> None:
        """Assemble the chunk arrays placed mid-stream into the delivered
        array (one device-side concatenate, pinned to the target device)."""
        import contextlib

        import jax
        import jax.numpy as jnp

        done_b = len(self._placed) * self._chunk_b
        if done_b < length:
            self._place_chunk(done_b, length - done_b)
        t0 = time.perf_counter()
        dev = self.devbuf.device
        # buffer_from_pyval chunks are uncommitted: pin the assemble to
        # the target device or jax's default device would claim it.
        ctx = jax.default_device(dev) if dev is not None else contextlib.nullcontext()
        with ctx:
            arr = (jnp.concatenate(self._placed) if len(self._placed) > 1
                   else self._placed[0])
            if length == self.nbytes:
                arr = arr.reshape(self.devbuf.shape)
        if dev is not None and arr.devices() != {dev}:
            arr = _copy_to_device(arr, dev, self.devbuf._plan)
        self.devbuf.array = arr
        self.devbuf.last_transport = "staged"
        _record_stage("place", time.perf_counter() - t0, 0, self.scope)

    def accept_host(self, view, length: int) -> None:
        """Complete host bytes already in hand (in-process delivery, or an
        owned unexpected-queue spill): place straight from the source view,
        eliding the staging memcpy, where that is safe.

        The fast path (_fast_h2d, PJRT buffer_from_pyval with
        force_copy=True) performs exactly one copy and never aliases the
        source, so it is safe on every target.  The jax.device_put
        fallback is NOT safe on CPU targets: jax zero-copies aligned host
        numpy buffers onto the CPU device, which would alias the SENDER's
        buffer — and send completion explicitly licenses the sender to
        reuse it (pinned by tests/test_device.py::test_host_to_device_
        inline_snapshots, which fails loudly if a jax release changes
        either behavior).  Accelerator targets always copy host->HBM, so
        the elision stands there."""
        import numpy as np
        import jax

        raw = np.frombuffer(view, dtype=np.uint8, count=length)
        t0 = time.perf_counter()
        placed = _fast_h2d(self._as_target(raw, length), self.devbuf.device)
        if placed is not None:
            placed.block_until_ready()  # recv-complete = data resident
            self.devbuf.array = placed
            self.devbuf.last_transport = "staged"
            _record_stage("place", time.perf_counter() - t0, length, self.scope)
            return
        dev = self.devbuf.device
        platform = dev.platform if dev is not None else jax.local_devices()[0].platform
        if platform == "cpu":
            raw = raw.copy()  # private snapshot; aliasing it is then fine
            self._place(raw, length)
        else:
            # H2D device_put is async: the DMA reads the source view after
            # the call returns, and completion licenses the sender to reuse
            # that buffer.  Block until the data is resident (the same
            # recv-complete semantics accept_device enforces).
            self._place(raw, length)
            self.devbuf.array.block_until_ready()

    def _as_target(self, raw, length: int):
        """View staged uint8 bytes as the sink's dtype (and shape, when the
        payload fills the buffer exactly)."""
        arr = raw.view(self.devbuf.dtype)
        if length == self.nbytes:
            arr = arr.reshape(self.devbuf.shape)
        return arr

    def _place(self, raw, length: int) -> None:
        import jax

        arr = self._as_target(raw, length)
        t0 = time.perf_counter()
        placed = _fast_h2d(arr, self.devbuf.device)
        if placed is None:
            self._recyclable = False  # fallback may alias `raw` (CPU)
            placed = (jax.device_put(arr, self.devbuf.device)
                      if self.devbuf.device is not None
                      else jax.device_put(arr))
        self.devbuf.array = placed
        self.devbuf.last_transport = "staged"
        _record_stage("place", time.perf_counter() - t0, length, self.scope)

    def accept_device(self, array) -> None:
        """Direct device handoff (in-process path): HBM -> HBM over ICI when
        source and target devices differ, reference handoff when they match."""
        import jax

        self.devbuf.last_transport = "device"
        target = self.devbuf.device
        if target is not None:
            src_devs = array.devices() if hasattr(array, "devices") else set()
            if src_devs == {target}:
                self.devbuf.array = array
                return
            self.devbuf.array = _copy_to_device(array, target, self.devbuf._plan)
            # Make completion mean "data resident on target", matching the
            # reference's recv-complete semantics.
            self.devbuf.array.block_until_ready()
        else:
            self.devbuf.array = array


# ------------------------------------------------------ cross-process pull
#
# The reference's whole value is zero-copy RDMA directly into the receiver's
# buffer (reference: src/bindings/main.cpp:370,1172).  The TPU equivalent
# for device payloads crossing processes is the PJRT transfer server
# (jax.experimental.transfer, the DCN cross-slice transfer machinery):
# the sender registers the array for pull, a tiny descriptor rides the
# framed stream for tag matching, and the receiver pulls the buffer
# device-to-device over the PJRT data socket -- pinned staging and
# streaming overlap live inside PJRT, not in Python, and the framework
# never materialises the payload on the host.  Negotiated per connection
# ("devpull" in HELLO/HELLO_ACK); peers without it (the C++ engine, or no
# jax) fall back to staged DATA frames.


def devpull_supported() -> bool:
    """Capability probe (no server started): jax live + API available + a
    backend the transfer server is known-good on.

    MUST NOT initialise a backend: this runs during the TCP handshake, and
    backend bring-up can block for seconds (or forever, behind a dead
    accelerator tunnel).  A process whose jax backend is not up yet simply
    negotiates no devpull -- device payloads fall back to staging for that
    connection, which is always correct."""
    import sys

    from . import config

    if not config.devpull_enabled():
        return False
    jax = sys.modules.get("jax")
    if jax is None:
        return False
    try:
        from jax._src import xla_bridge
        from jax._src.lib import xla_client as xc

        if not hasattr(xc._xla, "start_transfer_server"):
            return False
        # _default_backend is assigned only when backend bring-up has fully
        # completed (checking the _backends dict instead would race: it is
        # populated entry-by-entry while another thread still holds the
        # init lock, and the default_backend() call below would then block
        # on that lock -- the handshake hang this guard exists to prevent).
        if getattr(xla_bridge, "_default_backend", None) is None:
            return False
        if jax.default_backend() not in ("cpu", "tpu", "gpu", "cuda", "rocm"):
            return False
        # Tunneled/proxied backends present as "tpu" but run the transfer
        # server against a remote PJRT endpoint where it wedges; the plugin
        # name only shows in platform_version.
        version = getattr(jax.local_devices()[0].client, "platform_version", "")
        return "axon" not in version
    except Exception:
        return False


class TransferManager:
    """Per-worker PJRT transfer server wrapper.

    Owned by a Worker; dropped at worker close so unpulled sends die with
    the worker (the close-cancels-in-flight contract).  Server creation and
    peer connections are lazy; completion waits run on one daemon thread so
    the engine loop never blocks on a transfer.
    """

    def __init__(self, host: str):
        import itertools
        import queue
        import threading

        self._host = host
        self._server = None
        self._failed = False
        self._conns: dict = {}  # address -> TransferConnection
        self._uuid = itertools.count(1)
        self._lock = threading.Lock()
        self._q: "queue.Queue" = queue.Queue()
        self._thread = None
        self._closed = False

    # ------------------------------------------------------------- server
    def _ensure_server(self):
        with self._lock:
            if self._server is None and not self._failed and not self._closed:
                try:
                    import jax
                    from jax.experimental import transfer

                    # local_devices, not devices: under jax.distributed the
                    # global list leads with process 0's devices, which are
                    # non-addressable from other members.
                    client = jax.local_devices()[0].client
                    # Explicit transport addresses: without them the
                    # same-host "local bulk transport" path aborts (probed
                    # on this jax version).
                    self._server = transfer.start_transfer_server(
                        client, f"{self._host}:0", [f"{self._host}:0"])
                except Exception:
                    logger.warning("PJRT transfer server unavailable; "
                                   "device payloads fall back to host "
                                   "staging", exc_info=True)
                    self._failed = True
            return self._server

    # -------------------------------------------------------------- sender
    def offer(self, array):
        """Register ``array`` for remote pull; returns the descriptor dict
        (or None when the server cannot start -- caller falls back)."""
        srv = self._ensure_server()
        if srv is None:
            return None
        uid = next(self._uuid)
        srv.await_pull(uid, [array])
        return {
            "u": uid,
            "a": srv.address(),
            "n": int(array.nbytes),
            "s": list(array.shape),
            "d": str(array.dtype),
        }

    # ------------------------------------------------------------ receiver
    def pull(self, desc: dict, device, on_done, on_fail) -> None:
        """Pull ``desc`` onto ``device`` (None = default), asynchronously.

        Everything that can block (server start, peer connect, the transfer
        itself) runs on the manager's completion thread -- the caller is
        typically the engine thread and must never stall.  Exactly one of
        the callbacks fires, on that thread.
        """
        self._submit(lambda: self._do_pull(desc, device, on_done, on_fail))

    def _do_pull(self, desc: dict, device, on_done, on_fail):
        try:
            srv = self._ensure_server()
            if srv is None:
                on_fail("transfer server unavailable")
                return
            import jax
            import numpy as np
            from jax.sharding import SingleDeviceSharding

            with self._lock:
                conn = self._conns.get(desc["a"])
            if conn is None:
                conn = srv.connect(desc["a"])
                with self._lock:
                    conn = self._conns.setdefault(desc["a"], conn)
            # Default to a LOCAL device: under jax.distributed, devices()[0]
            # is global device 0 -- non-addressable on every other member,
            # and a pull spec'd onto it yields an array whose value this
            # process cannot even read.
            dev = device if device is not None else jax.local_devices()[0]
            try:
                dt = np.dtype(desc["d"])
            except TypeError:
                import ml_dtypes  # bfloat16 etc. are extension dtypes

                dt = np.dtype(getattr(ml_dtypes, desc["d"]))
            spec = jax.ShapeDtypeStruct(
                tuple(desc["s"]), dt, sharding=SingleDeviceSharding(dev))
            (arr,) = conn.pull(int(desc["u"]), [spec])
            arr.block_until_ready()
        except Exception as exc:
            on_fail(str(exc))
            return
        on_done(arr)

    def _submit(self, thunk) -> None:
        import threading

        with self._lock:
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="starway-devpull", daemon=True)
                self._thread.start()
        self._q.put(thunk)

    def _run(self):
        while True:
            thunk = self._q.get()
            if thunk is None:
                return
            try:
                thunk()
            except Exception:
                logger.exception("devpull completion callback failed")

    def close(self) -> None:
        """Drop the server: unpulled offers die (close-cancel contract)."""
        with self._lock:
            self._closed = True
            self._server = None
            self._conns.clear()
        self._q.put(None)


class PulledPayload:
    """Duck-typed payload for a pulled array (matcher contract)."""

    __slots__ = ("array", "nbytes", "_host_view")

    def __init__(self, array):
        self.array = array
        self.nbytes = int(array.nbytes)
        self._host_view = None

    def as_host_view(self) -> memoryview:
        if self._host_view is None:
            import numpy as np

            host = np.ascontiguousarray(np.asarray(self.array))
            self._host_view = memoryview(host.view(np.uint8)).cast("B")
        return self._host_view


class RemoteMsg:
    """Receiver-side handle for one DEVPULL descriptor.

    Owned by the conn that received it (flush accounting) and referenced by
    the matcher's InboundMsg (``msg.remote``).  ``start(msg)`` is invoked by
    matcher fire thunks -- after the worker lock is released -- once the
    message is claimed by a receive (or force-started by a FLUSH barrier).
    """

    __slots__ = ("desc", "conn", "manager", "started")

    def __init__(self, desc: dict, conn, manager: TransferManager):
        self.desc = desc
        self.conn = conn
        self.manager = manager
        self.started = False

    @property
    def nbytes(self) -> int:
        return int(self.desc["n"])

    def start(self, msg) -> None:
        worker = self.conn.worker
        # Start thunks can be queued from two paths concurrently (a
        # post_recv claim and a FLUSH force-start): the check-and-set must
        # be atomic or the uuid gets pulled twice.
        with worker.lock:
            if self.started:
                return
            self.started = True
            pr = msg.posted
        device = None
        if pr is not None and not isinstance(pr.buf, memoryview):
            device = pr.buf.devbuf.device if isinstance(pr.buf, DeviceRecvSink) else None
        self.manager.pull(
            self.desc, device,
            lambda arr, m=msg: worker._on_pull_done(m, PulledPayload(arr), None),
            lambda err, m=msg: worker._on_pull_done(m, None, err),
        )


def send_device(worker, conn, buffer, tag, done, fail):
    """Route a device payload: direct array handoff in-process, PJRT pull
    when the peer negotiated it, host staging otherwise."""
    from . import config

    if isinstance(buffer, DeviceBuffer):
        if buffer.array is None:
            raise ValueError("DeviceBuffer has no array to send")
        payload = DevicePayload(buffer.array)
    else:
        payload = DevicePayload(buffer)
    payload.scope = getattr(worker, "stage_scope", None)
    if conn is not None and conn.kind == "inproc":
        worker.submit_send(conn, payload, tag, done, fail, payload)
        return
    if (conn is not None and getattr(conn, "devpull_ok", False)
            and payload.nbytes >= config.devpull_threshold()):
        mgr = worker.transfer_manager()
        desc = mgr.offer(payload.array) if mgr is not None else None
        if desc is not None:
            worker.submit_devpull(conn, desc, tag, done, fail, payload)
            return
    # A session conn's replay journal must OWN every eager frame's bytes
    # past local completion (core/conn.py sess_wrap snapshots flat host
    # views), but a chunked payload is re-staged lazily from the device
    # buffer -- which the eager contract lets the caller delete or donate
    # once ``done`` fires.  Journaled eager sends therefore take the full
    # host snapshot below instead of the chunked pipeline.
    journaled = (config.session_enabled() if conn is None
                 else getattr(conn, "sess", None) is not None)
    # §19 integrity conns checksum at framing time, which needs the whole
    # payload resident: device sends on them take the flat host snapshot
    # too (the CRC folds once over the full view; DESIGN.md §19).
    journaled = journaled or (
        config.integrity_enabled() if conn is None
        else bool(getattr(conn, "csum_ok", False)))
    # Multi-rail striping (DESIGN.md §17) needs a flat host view -- chunks
    # are random-offset slices, and the §12 lazy-chunked pipeline stages
    # strictly in order.  A stripe-eligible device send therefore takes
    # the full host snapshot; the stripe scheduler's chunk-level dispatch
    # then supplies the transport overlap the pipeline would have.
    stripe_thr = config.stripe_threshold()
    striped = (stripe_thr > 0 and payload.nbytes >= stripe_thr
               and bool(getattr(conn, "rails", None)))
    if (getattr(worker, "supports_chunked_tx", False)
            and not journaled and not striped
            and payload.nbytes <= config.rndv_threshold()):
        # Framed-stream staging pipelines: the TX pump pulls host chunks
        # incrementally so the D2H of chunk k+1 overlaps the write of
        # chunk k (core/conn.py TxData; DESIGN.md §12).  Eager payloads
        # only: an eager send completes when the LAST chunk is staged and
        # written, so completion still licenses the caller to delete or
        # donate the array.  A rendezvous send completes at header-on-wire
        # with lazy staging still reading the array afterwards, which
        # would silently revoke that license -- rndv payloads keep the
        # full up-front host snapshot instead.
        chunked = payload.chunked(config.chunk_bytes())
        if chunked is not None:
            worker.submit_send(conn, chunked, tag, done, fail, payload)
            return
    view = payload.as_host_view()
    worker.submit_send(conn, view, tag, done, fail, payload)


def post_device_recv(worker, buffer, tag, mask, done, fail):
    if not isinstance(buffer, DeviceBuffer):
        raise TypeError("device receives require a DeviceBuffer sink")
    sink = DeviceRecvSink(buffer)
    sink.scope = getattr(worker, "stage_scope", None)
    worker.post_recv(sink, tag, mask, done, fail, owner=sink)
