"""Device data plane: jax.Array payloads over the fabric.

This is the TPU-native replacement for the reference's zero-copy RDMA into
preallocated NumPy buffers (reference: src/bindings/main.hpp:155-161 captures
raw host pointers; BASELINE.json north star: "asend/arecv/aflush async
primitives operate on jax.Array device buffers in HBM").

Three transfer paths, chosen per connection:

* **in-process, device payload -> device sink**: the sender hands the
  ``jax.Array`` itself to the receiver's matcher; the receiver materialises
  it on its target device with ``jax.device_put`` -- on TPU hardware with
  both devices in the same process this is an HBM-to-HBM copy over ICI with
  zero host staging.  (Same-device delivery is a reference handoff.)
* **in-process, mixed host/device**: one host copy at the boundary
  (``np.asarray`` of the payload, or ``device_put`` of the staged bytes).
* **cross-process (TCP / DCN bootstrap path)**: payload bytes are staged to
  host, streamed, and re-materialised on the receiver's device.  Real
  cross-host device DMA (jax.transfer-style) can slot in behind the same
  sink protocol when available.

The tag matcher stays byte-oriented; device awareness enters through two
small duck-typed protocols (no jax import in the core):

* :class:`DevicePayload` -- wraps an array for sending (``nbytes``,
  ``as_host_view()``, ``.array``).
* :class:`DeviceRecvSink` -- wraps a :class:`DeviceBuffer` for receiving
  (``nbytes``, ``host_staging()``, ``finalize_from_host()``,
  ``accept_device()``).
"""

from __future__ import annotations

import logging
import sys
from typing import Optional

logger = logging.getLogger("starway_tpu")


def _np_dtype(dtype):
    """Normalise numpy / jax.numpy scalar types / strings to np.dtype
    (ml_dtypes like bfloat16 included)."""
    import numpy as np

    d = getattr(dtype, "dtype", None)
    if isinstance(d, np.dtype):
        return d
    return np.dtype(dtype)


# --------------------------------------------------------------- fast copy
#
# Device-to-device transfer via the PJRT copy entry point directly
# (xla_client.batched_copy_array_to_devices_with_sharding), skipping
# jax.device_put's per-call Python dispatch (~100 us on this host).  This is
# the framework's data-plane edge over a hand-written device_put loop: the
# per-target plumbing (sharding, device list) is resolved once per sink and
# cached.  Private API -> probed once, with jax.device_put as the fallback.

_fast_copy_state = None  # None = unprobed, False = unavailable, else (xc, sem)


def _fast_copy_setup():
    global _fast_copy_state
    if _fast_copy_state is None:
        try:
            from jax._src.lib import xla_client as xc

            sem = xc.ArrayCopySemantics.ALWAYS_COPY
            _fast_copy_state = (xc.batched_copy_array_to_devices_with_sharding, sem)
        except Exception:
            _fast_copy_state = False
    return _fast_copy_state


def _copy_to_device(array, device, plan_cache):
    """Copy ``array`` onto ``device``; ``plan_cache`` is a one-slot list the
    caller owns (per-sink), holding the resolved (copy_fn, device_list,
    sharding, semantics) plan."""
    import jax

    plan = plan_cache[0]
    if plan is None:
        fast = _fast_copy_setup()
        if fast:
            try:
                from jax.sharding import SingleDeviceSharding

                copy_fn, sem = fast
                sharding = SingleDeviceSharding(device)
                plan = (copy_fn, sharding._internal_device_list, sharding, sem)
            except Exception:
                plan = False
        else:
            plan = False
        plan_cache[0] = plan
    if plan:
        copy_fn, dev_list, sharding, sem = plan
        try:
            return copy_fn([array], [dev_list], [sharding], [sem])[0]
        except (TypeError, AttributeError):
            # Drift-shaped error (signature/symbol changed): this plan will
            # never work, stop retrying for this sink.
            plan_cache[0] = False
            logger.warning(
                "PJRT fast-copy entry point unusable; falling back to "
                "jax.device_put for this sink", exc_info=True,
            )
        # Anything else (e.g. transient allocator pressure) falls through to
        # device_put for THIS transfer only; the plan stays cached.
    return jax.device_put(array, device)


_jax_array_type = None


def is_device_payload(buffer) -> bool:
    global _jax_array_type
    if isinstance(buffer, DeviceBuffer):
        return True
    if _jax_array_type is None:
        jax = sys.modules.get("jax")
        if jax is None:
            return False
        try:
            _jax_array_type = jax.Array
        except Exception:
            return False
    return isinstance(buffer, _jax_array_type)


class DeviceBuffer:
    """Mutable holder for a receive target living in device memory.

    jax.Arrays are immutable, so "receive into a preallocated device buffer"
    means: the framework materialises the received payload as a jax.Array on
    ``device`` and swaps it into ``.array``.  The previous array (if any) is
    dropped, letting XLA reuse its HBM.

    >>> sink = DeviceBuffer((1024,), jnp.bfloat16, device=jax.devices()[1])
    >>> tag, length = await server.arecv(sink, tag=7, tag_mask=MASK)
    >>> sink.array  # received payload, resident on devices()[1]
    """

    def __init__(self, shape, dtype, device=None, array=None):
        self.shape = tuple(int(s) for s in shape)
        self.dtype = _np_dtype(dtype)
        self.device = device
        self.array = array
        self._plan = [None]  # resolved copy plan, see _copy_to_device

    @classmethod
    def like(cls, array, device=None) -> "DeviceBuffer":
        """A sink shaped like ``array``, targeting ``device`` (default: the
        device ``array`` lives on)."""
        dev = device
        if dev is None:
            devs = getattr(array, "devices", None)
            if callable(devs):
                ds = devs()
                dev = next(iter(ds)) if ds else None
        return cls(array.shape, array.dtype, device=dev)

    @property
    def nbytes(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n * self.dtype.itemsize


class DevicePayload:
    """Send-side wrapper: a jax.Array plus a lazily-created host view."""

    __slots__ = ("array", "nbytes", "_host_view")

    def __init__(self, array):
        self.array = array
        self.nbytes = int(array.nbytes)
        self._host_view: Optional[memoryview] = None

    def as_host_view(self) -> memoryview:
        if self._host_view is None:
            import numpy as np

            host = np.ascontiguousarray(np.asarray(self.array))
            self._host_view = memoryview(host).cast("B")
        return self._host_view


class DeviceRecvSink:
    """Receive-side adapter bridging the byte matcher to a DeviceBuffer."""

    __slots__ = ("devbuf", "_staging", "_staging_view")

    def __init__(self, devbuf: DeviceBuffer):
        self.devbuf = devbuf
        self._staging = None
        self._staging_view: Optional[memoryview] = None

    @property
    def nbytes(self) -> int:
        return self.devbuf.nbytes

    def host_staging(self) -> memoryview:
        """Host bounce buffer for streamed (TCP) payloads."""
        if self._staging_view is None:
            import numpy as np

            self._staging = np.empty(self.nbytes, dtype=np.uint8)
            self._staging_view = memoryview(self._staging).cast("B")
        return self._staging_view

    def finalize_from_host(self, length: int) -> None:
        """Staged bytes fully arrived: view as dtype/shape, place on device."""
        import jax

        raw = self._staging[:length]
        arr = raw.view(self.devbuf.dtype)
        if length == self.nbytes:
            arr = arr.reshape(self.devbuf.shape)
        self.devbuf.array = (
            jax.device_put(arr, self.devbuf.device)
            if self.devbuf.device is not None
            else jax.device_put(arr)
        )
        self._staging = None
        self._staging_view = None

    def accept_device(self, array) -> None:
        """Direct device handoff (in-process path): HBM -> HBM over ICI when
        source and target devices differ, reference handoff when they match."""
        import jax

        target = self.devbuf.device
        if target is not None:
            src_devs = array.devices() if hasattr(array, "devices") else set()
            if src_devs == {target}:
                self.devbuf.array = array
                return
            self.devbuf.array = _copy_to_device(array, target, self.devbuf._plan)
            # Make completion mean "data resident on target", matching the
            # reference's recv-complete semantics.
            self.devbuf.array.block_until_ready()
        else:
            self.devbuf.array = array


def send_device(worker, conn, buffer, tag, done, fail):
    """Route a device payload: direct array handoff in-process, host staging
    over TCP."""
    if isinstance(buffer, DeviceBuffer):
        if buffer.array is None:
            raise ValueError("DeviceBuffer has no array to send")
        payload = DevicePayload(buffer.array)
    else:
        payload = DevicePayload(buffer)
    if conn is not None and conn.kind == "inproc":
        worker.submit_send(conn, payload, tag, done, fail, payload)
    else:
        view = payload.as_host_view()
        worker.submit_send(conn, view, tag, done, fail, payload)


def post_device_recv(worker, buffer, tag, mask, done, fail):
    if not isinstance(buffer, DeviceBuffer):
        raise TypeError("device receives require a DeviceBuffer sink")
    sink = DeviceRecvSink(buffer)
    worker.post_recv(sink, tag, mask, done, fail, owner=sink)
