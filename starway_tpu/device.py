"""Device data plane: jax.Array payloads over the fabric.

This is the TPU-native replacement for the reference's zero-copy RDMA into
preallocated NumPy buffers (reference: src/bindings/main.hpp:155-161 captures
raw host pointers; BASELINE.json north star: "asend/arecv/aflush async
primitives operate on jax.Array device buffers in HBM").

Three transfer paths, chosen per connection:

* **in-process, device payload -> device sink**: the sender hands the
  ``jax.Array`` itself to the receiver's matcher; the receiver materialises
  it on its target device with ``jax.device_put`` -- on TPU hardware with
  both devices in the same process this is an HBM-to-HBM copy over ICI with
  zero host staging.  (Same-device delivery is a reference handoff.)
* **in-process, mixed host/device**: one host copy at the boundary
  (``np.asarray`` of the payload, or ``device_put`` of the staged bytes).
* **cross-process (TCP / DCN bootstrap path)**: payload bytes are staged to
  host, streamed, and re-materialised on the receiver's device.  Real
  cross-host device DMA (jax.transfer-style) can slot in behind the same
  sink protocol when available.

The tag matcher stays byte-oriented; device awareness enters through two
small duck-typed protocols (no jax import in the core):

* :class:`DevicePayload` -- wraps an array for sending (``nbytes``,
  ``as_host_view()``, ``.array``).
* :class:`DeviceRecvSink` -- wraps a :class:`DeviceBuffer` for receiving
  (``nbytes``, ``host_staging()``, ``finalize_from_host()``,
  ``accept_device()``).
"""

from __future__ import annotations

import logging
import sys
from typing import Optional

logger = logging.getLogger("starway_tpu")


def _np_dtype(dtype):
    """Normalise numpy / jax.numpy scalar types / strings to np.dtype
    (ml_dtypes like bfloat16 included)."""
    import numpy as np

    d = getattr(dtype, "dtype", None)
    if isinstance(d, np.dtype):
        return d
    return np.dtype(dtype)


# --------------------------------------------------------------- fast copy
#
# Device-to-device transfer via the PJRT copy entry point directly
# (xla_client.batched_copy_array_to_devices_with_sharding), skipping
# jax.device_put's per-call Python dispatch (~100 us on this host).  This is
# the framework's data-plane edge over a hand-written device_put loop: the
# per-target plumbing (sharding, device list) is resolved once per sink and
# cached.  Private API -> probed once, with jax.device_put as the fallback.

_fast_copy_state = None  # None = unprobed, False = unavailable, else (xc, sem)


def _fast_copy_setup():
    global _fast_copy_state
    if _fast_copy_state is None:
        try:
            from jax._src.lib import xla_client as xc

            sem = xc.ArrayCopySemantics.ALWAYS_COPY
            _fast_copy_state = (xc.batched_copy_array_to_devices_with_sharding, sem)
        except Exception:
            _fast_copy_state = False
    return _fast_copy_state


def _copy_to_device(array, device, plan_cache):
    """Copy ``array`` onto ``device``; ``plan_cache`` is a one-slot list the
    caller owns (per-sink), holding the resolved (copy_fn, device_list,
    sharding, semantics) plan."""
    import jax

    plan = plan_cache[0]
    if plan is None:
        fast = _fast_copy_setup()
        if fast:
            try:
                from jax.sharding import SingleDeviceSharding

                copy_fn, sem = fast
                sharding = SingleDeviceSharding(device)
                plan = (copy_fn, sharding._internal_device_list, sharding, sem)
            except Exception:
                plan = False
        else:
            plan = False
        plan_cache[0] = plan
    if plan:
        copy_fn, dev_list, sharding, sem = plan
        try:
            return copy_fn([array], [dev_list], [sharding], [sem])[0]
        except (TypeError, AttributeError):
            # Drift-shaped error (signature/symbol changed): this plan will
            # never work, stop retrying for this sink.
            plan_cache[0] = False
            logger.warning(
                "PJRT fast-copy entry point unusable; falling back to "
                "jax.device_put for this sink", exc_info=True,
            )
        # Anything else (e.g. transient allocator pressure) falls through to
        # device_put for THIS transfer only; the plan stays cached.
    return jax.device_put(array, device)


_jax_array_type = None


def is_device_payload(buffer) -> bool:
    global _jax_array_type
    if isinstance(buffer, DeviceBuffer):
        return True
    if _jax_array_type is None:
        jax = sys.modules.get("jax")
        if jax is None:
            return False
        try:
            _jax_array_type = jax.Array
        except Exception:
            return False
    return isinstance(buffer, _jax_array_type)


class DeviceBuffer:
    """Mutable holder for a receive target living in device memory.

    jax.Arrays are immutable, so "receive into a preallocated device buffer"
    means: the framework materialises the received payload as a jax.Array on
    ``device`` and swaps it into ``.array``.  The previous array (if any) is
    dropped, letting XLA reuse its HBM.

    >>> sink = DeviceBuffer((1024,), jnp.bfloat16, device=jax.devices()[1])
    >>> tag, length = await server.arecv(sink, tag=7, tag_mask=MASK)
    >>> sink.array  # received payload, resident on devices()[1]
    """

    def __init__(self, shape, dtype, device=None, array=None):
        self.shape = tuple(int(s) for s in shape)
        self.dtype = _np_dtype(dtype)
        self.device = device
        self.array = array
        self._plan = [None]  # resolved copy plan, see _copy_to_device
        # How the last receive landed: "device" (array handoff -- inproc or
        # PJRT pull) or "staged" (bytes streamed through host staging).
        self.last_transport = None

    @classmethod
    def like(cls, array, device=None) -> "DeviceBuffer":
        """A sink shaped like ``array``, targeting ``device`` (default: the
        device ``array`` lives on)."""
        dev = device
        if dev is None:
            devs = getattr(array, "devices", None)
            if callable(devs):
                ds = devs()
                dev = next(iter(ds)) if ds else None
        return cls(array.shape, array.dtype, device=dev)

    @property
    def nbytes(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n * self.dtype.itemsize


class DevicePayload:
    """Send-side wrapper: a jax.Array plus a lazily-created host view."""

    __slots__ = ("array", "nbytes", "_host_view")

    def __init__(self, array):
        self.array = array
        self.nbytes = int(array.nbytes)
        self._host_view: Optional[memoryview] = None

    def as_host_view(self) -> memoryview:
        if self._host_view is None:
            import numpy as np

            host = np.ascontiguousarray(np.asarray(self.array))
            self._host_view = memoryview(host).cast("B")
        return self._host_view


class DeviceRecvSink:
    """Receive-side adapter bridging the byte matcher to a DeviceBuffer."""

    __slots__ = ("devbuf", "_staging", "_staging_view")

    def __init__(self, devbuf: DeviceBuffer):
        self.devbuf = devbuf
        self._staging = None
        self._staging_view: Optional[memoryview] = None

    @property
    def nbytes(self) -> int:
        return self.devbuf.nbytes

    def host_staging(self) -> memoryview:
        """Host bounce buffer for streamed (TCP) payloads."""
        if self._staging_view is None:
            import numpy as np

            self._staging = np.empty(self.nbytes, dtype=np.uint8)
            self._staging_view = memoryview(self._staging).cast("B")
        return self._staging_view

    def finalize_from_host(self, length: int) -> None:
        """Staged bytes fully arrived: view as dtype/shape, place on device."""
        import numpy as np

        self._place(np.asarray(self._staging[:length]), length)
        self._staging = None
        self._staging_view = None

    def accept_host(self, view, length: int) -> None:
        """Complete host bytes already in hand (in-process delivery, or an
        owned unexpected-queue spill): device_put straight from the source
        view, eliding the staging memcpy, where that is safe.

        It is NOT safe on CPU targets: jax zero-copies aligned host numpy
        buffers onto the CPU device, which would alias the SENDER's buffer
        — and send completion explicitly licenses the sender to reuse it
        (pinned by tests/test_device.py::test_host_to_device_inline_
        snapshots, which fails loudly if a jax release changes either
        behavior).  Accelerator targets always copy host->HBM, so the
        elision stands there."""
        import numpy as np
        import jax

        raw = np.frombuffer(view, dtype=np.uint8, count=length)
        dev = self.devbuf.device
        platform = dev.platform if dev is not None else jax.local_devices()[0].platform
        if platform == "cpu":
            raw = raw.copy()  # private snapshot; aliasing it is then fine
            self._place(raw, length)
        else:
            # H2D device_put is async: the DMA reads the source view after
            # the call returns, and completion licenses the sender to reuse
            # that buffer.  Block until the data is resident (the same
            # recv-complete semantics accept_device enforces).
            self._place(raw, length)
            self.devbuf.array.block_until_ready()

    def _place(self, raw, length: int) -> None:
        import jax

        arr = raw.view(self.devbuf.dtype)
        if length == self.nbytes:
            arr = arr.reshape(self.devbuf.shape)
        self.devbuf.array = (
            jax.device_put(arr, self.devbuf.device)
            if self.devbuf.device is not None
            else jax.device_put(arr)
        )
        self.devbuf.last_transport = "staged"

    def accept_device(self, array) -> None:
        """Direct device handoff (in-process path): HBM -> HBM over ICI when
        source and target devices differ, reference handoff when they match."""
        import jax

        self.devbuf.last_transport = "device"
        target = self.devbuf.device
        if target is not None:
            src_devs = array.devices() if hasattr(array, "devices") else set()
            if src_devs == {target}:
                self.devbuf.array = array
                return
            self.devbuf.array = _copy_to_device(array, target, self.devbuf._plan)
            # Make completion mean "data resident on target", matching the
            # reference's recv-complete semantics.
            self.devbuf.array.block_until_ready()
        else:
            self.devbuf.array = array


# ------------------------------------------------------ cross-process pull
#
# The reference's whole value is zero-copy RDMA directly into the receiver's
# buffer (reference: src/bindings/main.cpp:370,1172).  The TPU equivalent
# for device payloads crossing processes is the PJRT transfer server
# (jax.experimental.transfer, the DCN cross-slice transfer machinery):
# the sender registers the array for pull, a tiny descriptor rides the
# framed stream for tag matching, and the receiver pulls the buffer
# device-to-device over the PJRT data socket -- pinned staging and
# streaming overlap live inside PJRT, not in Python, and the framework
# never materialises the payload on the host.  Negotiated per connection
# ("devpull" in HELLO/HELLO_ACK); peers without it (the C++ engine, or no
# jax) fall back to staged DATA frames.


def devpull_supported() -> bool:
    """Capability probe (no server started): jax live + API available + a
    backend the transfer server is known-good on.

    MUST NOT initialise a backend: this runs during the TCP handshake, and
    backend bring-up can block for seconds (or forever, behind a dead
    accelerator tunnel).  A process whose jax backend is not up yet simply
    negotiates no devpull -- device payloads fall back to staging for that
    connection, which is always correct."""
    import sys

    from . import config

    if not config.devpull_enabled():
        return False
    jax = sys.modules.get("jax")
    if jax is None:
        return False
    try:
        from jax._src import xla_bridge
        from jax._src.lib import xla_client as xc

        if not hasattr(xc._xla, "start_transfer_server"):
            return False
        # _default_backend is assigned only when backend bring-up has fully
        # completed (checking the _backends dict instead would race: it is
        # populated entry-by-entry while another thread still holds the
        # init lock, and the default_backend() call below would then block
        # on that lock -- the handshake hang this guard exists to prevent).
        if getattr(xla_bridge, "_default_backend", None) is None:
            return False
        if jax.default_backend() not in ("cpu", "tpu", "gpu", "cuda", "rocm"):
            return False
        # Tunneled/proxied backends present as "tpu" but run the transfer
        # server against a remote PJRT endpoint where it wedges; the plugin
        # name only shows in platform_version.
        version = getattr(jax.local_devices()[0].client, "platform_version", "")
        return "axon" not in version
    except Exception:
        return False


class TransferManager:
    """Per-worker PJRT transfer server wrapper.

    Owned by a Worker; dropped at worker close so unpulled sends die with
    the worker (the close-cancels-in-flight contract).  Server creation and
    peer connections are lazy; completion waits run on one daemon thread so
    the engine loop never blocks on a transfer.
    """

    def __init__(self, host: str):
        import itertools
        import queue
        import threading

        self._host = host
        self._server = None
        self._failed = False
        self._conns: dict = {}  # address -> TransferConnection
        self._uuid = itertools.count(1)
        self._lock = threading.Lock()
        self._q: "queue.Queue" = queue.Queue()
        self._thread = None
        self._closed = False

    # ------------------------------------------------------------- server
    def _ensure_server(self):
        with self._lock:
            if self._server is None and not self._failed and not self._closed:
                try:
                    import jax
                    from jax.experimental import transfer

                    # local_devices, not devices: under jax.distributed the
                    # global list leads with process 0's devices, which are
                    # non-addressable from other members.
                    client = jax.local_devices()[0].client
                    # Explicit transport addresses: without them the
                    # same-host "local bulk transport" path aborts (probed
                    # on this jax version).
                    self._server = transfer.start_transfer_server(
                        client, f"{self._host}:0", [f"{self._host}:0"])
                except Exception:
                    logger.warning("PJRT transfer server unavailable; "
                                   "device payloads fall back to host "
                                   "staging", exc_info=True)
                    self._failed = True
            return self._server

    # -------------------------------------------------------------- sender
    def offer(self, array):
        """Register ``array`` for remote pull; returns the descriptor dict
        (or None when the server cannot start -- caller falls back)."""
        srv = self._ensure_server()
        if srv is None:
            return None
        uid = next(self._uuid)
        srv.await_pull(uid, [array])
        return {
            "u": uid,
            "a": srv.address(),
            "n": int(array.nbytes),
            "s": list(array.shape),
            "d": str(array.dtype),
        }

    # ------------------------------------------------------------ receiver
    def pull(self, desc: dict, device, on_done, on_fail) -> None:
        """Pull ``desc`` onto ``device`` (None = default), asynchronously.

        Everything that can block (server start, peer connect, the transfer
        itself) runs on the manager's completion thread -- the caller is
        typically the engine thread and must never stall.  Exactly one of
        the callbacks fires, on that thread.
        """
        self._submit(lambda: self._do_pull(desc, device, on_done, on_fail))

    def _do_pull(self, desc: dict, device, on_done, on_fail):
        try:
            srv = self._ensure_server()
            if srv is None:
                on_fail("transfer server unavailable")
                return
            import jax
            import numpy as np
            from jax.sharding import SingleDeviceSharding

            with self._lock:
                conn = self._conns.get(desc["a"])
            if conn is None:
                conn = srv.connect(desc["a"])
                with self._lock:
                    conn = self._conns.setdefault(desc["a"], conn)
            # Default to a LOCAL device: under jax.distributed, devices()[0]
            # is global device 0 -- non-addressable on every other member,
            # and a pull spec'd onto it yields an array whose value this
            # process cannot even read.
            dev = device if device is not None else jax.local_devices()[0]
            try:
                dt = np.dtype(desc["d"])
            except TypeError:
                import ml_dtypes  # bfloat16 etc. are extension dtypes

                dt = np.dtype(getattr(ml_dtypes, desc["d"]))
            spec = jax.ShapeDtypeStruct(
                tuple(desc["s"]), dt, sharding=SingleDeviceSharding(dev))
            (arr,) = conn.pull(int(desc["u"]), [spec])
            arr.block_until_ready()
        except Exception as exc:
            on_fail(str(exc))
            return
        on_done(arr)

    def _submit(self, thunk) -> None:
        import threading

        with self._lock:
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="starway-devpull", daemon=True)
                self._thread.start()
        self._q.put(thunk)

    def _run(self):
        while True:
            thunk = self._q.get()
            if thunk is None:
                return
            try:
                thunk()
            except Exception:
                logger.exception("devpull completion callback failed")

    def close(self) -> None:
        """Drop the server: unpulled offers die (close-cancel contract)."""
        with self._lock:
            self._closed = True
            self._server = None
            self._conns.clear()
        self._q.put(None)


class PulledPayload:
    """Duck-typed payload for a pulled array (matcher contract)."""

    __slots__ = ("array", "nbytes", "_host_view")

    def __init__(self, array):
        self.array = array
        self.nbytes = int(array.nbytes)
        self._host_view = None

    def as_host_view(self) -> memoryview:
        if self._host_view is None:
            import numpy as np

            host = np.ascontiguousarray(np.asarray(self.array))
            self._host_view = memoryview(host).cast("B")
        return self._host_view


class RemoteMsg:
    """Receiver-side handle for one DEVPULL descriptor.

    Owned by the conn that received it (flush accounting) and referenced by
    the matcher's InboundMsg (``msg.remote``).  ``start(msg)`` is invoked by
    matcher fire thunks -- after the worker lock is released -- once the
    message is claimed by a receive (or force-started by a FLUSH barrier).
    """

    __slots__ = ("desc", "conn", "manager", "started")

    def __init__(self, desc: dict, conn, manager: TransferManager):
        self.desc = desc
        self.conn = conn
        self.manager = manager
        self.started = False

    @property
    def nbytes(self) -> int:
        return int(self.desc["n"])

    def start(self, msg) -> None:
        worker = self.conn.worker
        # Start thunks can be queued from two paths concurrently (a
        # post_recv claim and a FLUSH force-start): the check-and-set must
        # be atomic or the uuid gets pulled twice.
        with worker.lock:
            if self.started:
                return
            self.started = True
            pr = msg.posted
        device = None
        if pr is not None and not isinstance(pr.buf, memoryview):
            device = pr.buf.devbuf.device if isinstance(pr.buf, DeviceRecvSink) else None
        self.manager.pull(
            self.desc, device,
            lambda arr, m=msg: worker._on_pull_done(m, PulledPayload(arr), None),
            lambda err, m=msg: worker._on_pull_done(m, None, err),
        )


def send_device(worker, conn, buffer, tag, done, fail):
    """Route a device payload: direct array handoff in-process, PJRT pull
    when the peer negotiated it, host staging otherwise."""
    from . import config

    if isinstance(buffer, DeviceBuffer):
        if buffer.array is None:
            raise ValueError("DeviceBuffer has no array to send")
        payload = DevicePayload(buffer.array)
    else:
        payload = DevicePayload(buffer)
    if conn is not None and conn.kind == "inproc":
        worker.submit_send(conn, payload, tag, done, fail, payload)
        return
    if (conn is not None and getattr(conn, "devpull_ok", False)
            and payload.nbytes >= config.devpull_threshold()):
        mgr = worker.transfer_manager()
        desc = mgr.offer(payload.array) if mgr is not None else None
        if desc is not None:
            worker.submit_devpull(conn, desc, tag, done, fail, payload)
            return
    view = payload.as_host_view()
    worker.submit_send(conn, view, tag, done, fail, payload)


def post_device_recv(worker, buffer, tag, mask, done, fail):
    if not isinstance(buffer, DeviceBuffer):
        raise TypeError("device receives require a DeviceBuffer sink")
    sink = DeviceRecvSink(buffer)
    worker.post_recv(sink, tag, mask, done, fail, owner=sink)
