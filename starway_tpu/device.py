"""Device data plane: jax.Array payloads over the fabric.

Placeholder hooks for the device plane (SURVEY.md section 7, stage 3); the
full implementation lands with the mesh/ICI layer.  The host byte path never
imports jax, keeping cold-start light for pure host users.
"""

from __future__ import annotations

import sys


def is_device_payload(buffer) -> bool:
    jax = sys.modules.get("jax")
    if jax is None:
        return False
    if isinstance(buffer, DeviceBuffer):
        return True
    try:
        return isinstance(buffer, jax.Array)
    except Exception:
        return False


class DeviceBuffer:
    """Mutable holder for a receive target living in device HBM.

    jax.Arrays are immutable, so "receive into a preallocated device buffer"
    means: the framework materialises the received payload as a jax.Array on
    ``device`` and swaps it into ``.array`` (donating the previous one when
    possible).  Created empty via shape/dtype or wrapping an existing array.
    """

    def __init__(self, shape, dtype, device=None, array=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.device = device
        self.array = array

    def __len__(self) -> int:
        import numpy as np

        return int(np.prod(self.shape)) * np.dtype(self.dtype).itemsize


def send_device(worker, conn, buffer, tag, done, fail):
    raise NotImplementedError("device plane lands in the mesh/ICI milestone")


def post_device_recv(worker, buffer, tag, mask, done, fail):
    raise NotImplementedError("device plane lands in the mesh/ICI milestone")
