"""Benchmark scenario registry (reference: src/starway/benchmarks/__init__.py)."""

from __future__ import annotations

from .scenarios import SCENARIOS, ScenarioDefinition, ScenarioResult

__all__ = [
    "SCENARIOS",
    "ScenarioDefinition",
    "ScenarioResult",
    "list_scenarios",
    "get_scenario",
]


def list_scenarios() -> list[str]:
    return list(SCENARIOS.keys())


def get_scenario(name: str) -> ScenarioDefinition:
    try:
        return SCENARIOS[name]
    except KeyError as exc:
        raise ValueError(f"Unknown benchmark scenario '{name}'") from exc
