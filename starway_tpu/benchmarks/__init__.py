"""Benchmark package: the scenario registry lives in `scenarios.py`.

The import path mirrors the reference layout (src/starway/benchmarks/) so
bench-driving code ports over unchanged, but everything of substance —
Scenario subclasses, the SCENARIOS table, control-plane tags — is defined
in one module and re-exported here.
"""

from __future__ import annotations

from .scenarios import SCENARIOS, Scenario, ScenarioDefinition, ScenarioResult


def list_scenarios() -> list[str]:
    """Names of all registered scenarios, in registry order."""
    return [*SCENARIOS]


def get_scenario(name: str) -> Scenario:
    """Registry lookup with the available names in the error message."""
    if name not in SCENARIOS:
        raise ValueError(
            f"Unknown benchmark scenario {name!r}; available: {', '.join(SCENARIOS)}")
    return SCENARIOS[name]


__all__ = ["SCENARIOS", "Scenario", "ScenarioDefinition", "ScenarioResult",
           "get_scenario", "list_scenarios"]
