"""Benchmark scenarios.

Re-implements the reference's four-scenario suite (reference:
src/starway/benchmarks/scenarios.py, benchmark.md:48-102) with the same
names, default configs, and metric keys so results are comparable:

* ``large-array``     -- one-way bandwidth, single large buffer
* ``small-messages``  -- many small concurrent messages
* ``pingpong-flag``   -- 1-byte round-trip latency
* ``streaming-duplex``-- bidirectional medium-chunk streaming

Design differs from the reference (paired free functions) by making each
scenario a class with ``run_client`` / ``run_server`` coroutines; payloads may
be host numpy arrays (default) or device jax.Arrays (``payload="device"``),
which is the TPU-native headline path.

Tag space (compatible with the reference constants):
control 0x1AA0-0x1AA2, data 0x2B00-0x2B31.
"""

from __future__ import annotations

import asyncio
import json
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping

import numpy as np


def _encode_ctl(payload: Mapping[str, Any]) -> np.ndarray:
    raw = json.dumps(payload, separators=(",", ":"), sort_keys=True).encode()
    return np.frombuffer(raw, dtype=np.uint8).copy()


def _decode_ctl(buffer: np.ndarray, length: int) -> dict:
    return json.loads(bytes(memoryview(buffer)[:length]).decode())

TAG_MASK: int = (1 << 64) - 1

CONTROL_TAG = 0x1AA0
READY_TAG = 0x1AA1
DONE_TAG = 0x1AA2

LARGE_DATA_TAG = 0x2B00
SMALL_DATA_TAG = 0x2B10
SMALL_ACK_TAG = 0x2B11
FLAG_PING_TAG = 0x2B20
FLAG_PONG_TAG = 0x2B21
STREAM_UP_TAG = 0x2B30
STREAM_DOWN_TAG = 0x2B31
STRIPED_DATA_TAG = 0x2B40
FLOOD_DATA_TAG = 0x2B50
FLOOD_STATS_TAG = 0x2B51
RESHARD_STATS_TAG = 0x2B60
#: swshard schedules address their transfers inside the reserved
#: 0xE5<<56 namespace (reshard/tags.py); the scenario pins lease slot 11
#: on both roles -- the shared-coordinate contract.
RESHARD_LEASE_SLOT = 11


@dataclass
class ScenarioResult:
    """Metrics + optional per-iteration samples for one scenario run
    (reference: ScenarioResult, src/starway/benchmarks/scenarios.py:42-57)."""

    name: str
    metrics: Dict[str, float]
    samples: Dict[str, List[float]] = field(default_factory=dict)
    config: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self, include_samples: bool = True) -> Dict[str, Any]:
        out: Dict[str, Any] = {"name": self.name, "metrics": self.metrics, "config": self.config}
        if include_samples:
            out["samples"] = self.samples
        return out


def _pct(values_us: np.ndarray, q: float) -> float:
    return float(np.percentile(values_us, q)) if len(values_us) else 0.0


def _make_payload(size: int, fill: int, kind: str):
    """Host numpy buffer or a device jax.Array (the TPU-native path)."""
    if kind == "device":
        import jax.numpy as jnp

        return jnp.full((size,), fill % 256, dtype=jnp.uint8)
    return np.full(size, fill % 256, dtype=np.uint8)


def _make_sink(size: int, kind: str):
    if kind == "device":
        from ..device import DeviceBuffer

        return DeviceBuffer((size,), np.uint8)
    return np.empty(size, dtype=np.uint8)


class Scenario:
    """Base: a named scenario with defaults; subclasses implement the client
    (measuring) and server (echo/sink) coroutines."""

    name: str = ""
    description: str = ""
    defaults: Dict[str, Any] = {}

    def config(self, overrides: Mapping[str, Any]) -> Dict[str, Any]:
        cfg = dict(self.defaults)
        cfg.update({k: v for k, v in overrides.items() if v is not None})
        return cfg

    async def run_client(self, ctx, overrides: Mapping[str, Any]) -> ScenarioResult:
        raise NotImplementedError

    async def run_server(self, ctx, overrides: Mapping[str, Any]) -> None:
        raise NotImplementedError


class LargeArray(Scenario):
    name = "large-array"
    description = "Measure one-way bandwidth by transferring a single large buffer."
    defaults = {"message_bytes": 1 << 30, "warmup": 1, "iterations": 3, "payload": "host"}

    async def run_client(self, ctx, overrides) -> ScenarioResult:
        cfg = self.config(overrides)
        size, warmup, iters = int(cfg["message_bytes"]), int(cfg["warmup"]), int(cfg["iterations"])
        payload = _make_payload(size, 0x5A, cfg.get("payload", "host"))
        secs: list[float] = []
        gbps: list[float] = []
        for i in range(warmup + iters):
            t0 = time.perf_counter()
            await ctx.client.asend(payload, LARGE_DATA_TAG)
            await ctx.flush()
            dt = time.perf_counter() - t0
            if i >= warmup:
                secs.append(dt)
                if dt > 0:
                    gbps.append(size / dt / 1e9)
        total = sum(secs)
        return ScenarioResult(
            name=self.name,
            metrics={
                "total_seconds": total,
                "avg_seconds_per_iter": total / iters if iters else 0.0,
                "avg_gbps": (size * iters / total / 1e9) if total > 0 else 0.0,
                "best_gbps": max(gbps) if gbps else 0.0,
                "worst_gbps": min(gbps) if gbps else 0.0,
            },
            samples={"duration_seconds": secs, "per_iter_gbps": gbps},
            config=cfg,
        )

    async def run_server(self, ctx, overrides) -> None:
        cfg = self.config(overrides)
        size, total = int(cfg["message_bytes"]), int(cfg["warmup"]) + int(cfg["iterations"])
        sink = _make_sink(size, cfg.get("payload", "host"))
        await ctx.signal_ready()
        for _ in range(total):
            await ctx.server.arecv(sink, LARGE_DATA_TAG, ctx.tag_mask)
        await ctx.flush_endpoint()


class SmallMessages(Scenario):
    name = "small-messages"
    description = "Stress many small messages with configurable concurrency."
    defaults = {"message_bytes": 1024, "warmup_batches": 2, "iterations": 10, "concurrency": 64}

    async def run_client(self, ctx, overrides) -> ScenarioResult:
        cfg = self.config(overrides)
        size = int(cfg["message_bytes"])
        warmup, iters = int(cfg["warmup_batches"]), int(cfg["iterations"])
        conc = int(cfg["concurrency"])
        payloads = [np.full(size, i % 251, dtype=np.uint8) for i in range(conc)]
        batch_secs: list[float] = []
        per_msg: list[float] = []
        for b in range(warmup + iters):
            t0 = time.perf_counter()
            await asyncio.gather(*(ctx.client.asend(p, SMALL_DATA_TAG) for p in payloads))
            await ctx.flush()
            dt = time.perf_counter() - t0
            if b >= warmup:
                batch_secs.append(dt)
                if conc:
                    per_msg.append(dt / conc)
        total = sum(batch_secs)
        nmsg = iters * conc
        lat_us = np.asarray(per_msg) * 1e6
        return ScenarioResult(
            name=self.name,
            metrics={
                "total_seconds": total,
                "messages_per_second": nmsg / total if total > 0 else 0.0,
                "bandwidth_gbps": size * nmsg / total / 1e9 if total > 0 else 0.0,
                "latency_p50_us": _pct(lat_us, 50),
                "latency_p95_us": _pct(lat_us, 95),
            },
            samples={"batch_duration_seconds": batch_secs, "avg_latency_seconds": per_msg},
            config=cfg,
        )

    async def run_server(self, ctx, overrides) -> None:
        cfg = self.config(overrides)
        size = int(cfg["message_bytes"])
        batches = int(cfg["warmup_batches"]) + int(cfg["iterations"])
        conc = int(cfg["concurrency"])
        sinks = [np.empty(size, dtype=np.uint8) for _ in range(conc)]
        await ctx.signal_ready()
        for _ in range(batches):
            await asyncio.gather(*(ctx.server.arecv(s, SMALL_DATA_TAG, ctx.tag_mask) for s in sinks))
        await ctx.flush_endpoint()


class PingpongFlag(Scenario):
    name = "pingpong-flag"
    description = "Round-trip a single-byte control flag to capture latency."
    defaults = {"warmup": 100, "iterations": 1000}

    async def run_client(self, ctx, overrides) -> ScenarioResult:
        cfg = self.config(overrides)
        warmup, iters = int(cfg["warmup"]), int(cfg["iterations"])
        ping = np.ones(1, dtype=np.uint8)
        pong = np.zeros(1, dtype=np.uint8)
        rtts: list[float] = []
        for i in range(warmup + iters):
            pong_fut = ctx.client.arecv(pong, FLAG_PONG_TAG, ctx.tag_mask)
            t0 = time.perf_counter()
            await ctx.client.asend(ping, FLAG_PING_TAG)
            await pong_fut
            if i >= warmup:
                rtts.append(time.perf_counter() - t0)
        await ctx.flush()
        us = np.asarray(rtts) * 1e6
        avg = float(np.mean(us)) if len(us) else 0.0
        return ScenarioResult(
            name=self.name,
            metrics={
                "avg_rtt_us": avg,
                "median_rtt_us": float(np.median(us)) if len(us) else 0.0,
                "min_rtt_us": float(np.min(us)) if len(us) else 0.0,
                "max_rtt_us": float(np.max(us)) if len(us) else 0.0,
                "avg_one_way_us": avg / 2.0,
            },
            samples={"rtt_seconds": rtts},
            config=cfg,
        )

    async def run_server(self, ctx, overrides) -> None:
        cfg = self.config(overrides)
        total = int(cfg["warmup"]) + int(cfg["iterations"])
        sink = np.zeros(1, dtype=np.uint8)
        ack = np.ones(1, dtype=np.uint8)
        await ctx.signal_ready()
        for _ in range(total):
            await ctx.server.arecv(sink, FLAG_PING_TAG, ctx.tag_mask)
            await ctx.server.asend(ctx.endpoint, ack, FLAG_PONG_TAG)
        await ctx.flush_endpoint()


class StreamingDuplex(Scenario):
    name = "streaming-duplex"
    description = "Bidirectional medium-sized streaming in both directions."
    defaults = {"message_bytes": 4 * 1024 * 1024, "warmup": 8, "iterations": 64, "payload": "host"}

    async def run_client(self, ctx, overrides) -> ScenarioResult:
        cfg = self.config(overrides)
        size = int(cfg["message_bytes"])
        warmup, iters = int(cfg["warmup"]), int(cfg["iterations"])
        up = _make_payload(size, 0x7B, cfg.get("payload", "host"))
        down = _make_sink(size, cfg.get("payload", "host"))
        secs: list[float] = []
        for i in range(warmup + iters):
            down_fut = ctx.client.arecv(down, STREAM_DOWN_TAG, ctx.tag_mask)
            t0 = time.perf_counter()
            await asyncio.gather(ctx.client.asend(up, STREAM_UP_TAG), down_fut)
            dt = time.perf_counter() - t0
            if i >= warmup:
                secs.append(dt)
        await ctx.flush()
        total = sum(secs)
        one_way = size * iters
        per_dir = one_way / total / 1e9 if total > 0 else 0.0
        return ScenarioResult(
            name=self.name,
            metrics={
                "total_seconds": total,
                "avg_seconds_per_iter": total / iters if iters else 0.0,
                "client_to_server_gbps": per_dir,
                "server_to_client_gbps": per_dir,
                "aggregate_gbps": 2 * per_dir,
            },
            samples={"iteration_seconds": secs},
            config=cfg,
        )

    async def run_server(self, ctx, overrides) -> None:
        cfg = self.config(overrides)
        size = int(cfg["message_bytes"])
        total = int(cfg["warmup"]) + int(cfg["iterations"])
        down = _make_payload(size, 0x3C, cfg.get("payload", "host"))
        up = _make_sink(size, cfg.get("payload", "host"))
        await ctx.signal_ready()
        for _ in range(total):
            await asyncio.gather(
                ctx.server.arecv(up, STREAM_UP_TAG, ctx.tag_mask),
                ctx.server.asend(ctx.endpoint, down, STREAM_DOWN_TAG),
            )
        await ctx.flush_endpoint()


class Striped(Scenario):
    """Multi-rail striped throughput (DESIGN.md §17): one-way transfer of
    large messages with the stripe scheduler armed (``--rails N`` sets
    ``STARWAY_RAILS`` before the workers are built; the conn then carries
    N lanes).  ``paired=True`` is the built-in paired-ratio mode: every
    iteration measures a striping-OFF baseline and a striping-ON transfer
    back to back over the SAME connection (``STARWAY_STRIPE_THRESHOLD``
    is read per send, so the toggle is one env flip), which cancels the
    1.5-6 GB/s box noise that otherwise needs hand-run interleaving
    (BENCHMARK.md)."""

    name = "striped"
    description = "Striped large-message throughput across the rail set (optionally HEAD/new paired)."
    defaults = {"message_bytes": 8 << 20, "warmup": 2, "iterations": 10,
                "payload": "host", "paired": False}

    @staticmethod
    def _thr_env():
        import os

        return os.environ.get("STARWAY_STRIPE_THRESHOLD", "")

    @staticmethod
    def _set_thr(val: str) -> None:
        import os

        if val:
            os.environ["STARWAY_STRIPE_THRESHOLD"] = val
        else:
            os.environ.pop("STARWAY_STRIPE_THRESHOLD", None)

    async def run_client(self, ctx, overrides) -> ScenarioResult:
        cfg = self.config(overrides)
        size = int(cfg["message_bytes"])
        warmup, iters = int(cfg["warmup"]), int(cfg["iterations"])
        paired = bool(cfg.get("paired"))
        payload = _make_payload(size, 0x5B, cfg.get("payload", "host"))
        armed = self._thr_env() or str(1 << 20)

        async def one(thr: str) -> float:
            self._set_thr(thr)
            try:
                t0 = time.perf_counter()
                await ctx.client.asend(payload, STRIPED_DATA_TAG)
                await ctx.flush()
                return time.perf_counter() - t0
            finally:
                self._set_thr(armed)

        striped: list[float] = []
        base: list[float] = []
        for i in range(warmup + iters):
            if paired:
                b = await one("0")       # HEAD config: single lane
                s = await one(armed)     # new config: striped
                if i >= warmup:
                    base.append(b)
                    striped.append(s)
            else:
                s = await one(armed)
                if i >= warmup:
                    striped.append(s)
        gbps = [size / dt / 1e9 for dt in striped if dt > 0]
        metrics = {
            "striped_gbps_p50": float(np.median(gbps)) if gbps else 0.0,
            "striped_seconds_total": sum(striped),
        }
        samples = {"striped_seconds": striped}
        if paired:
            base_gbps = [size / dt / 1e9 for dt in base if dt > 0]
            ratios = [b / s for b, s in zip(base, striped) if s > 0]
            metrics.update(
                baseline_gbps_p50=(float(np.median(base_gbps))
                                   if base_gbps else 0.0),
                paired_ratio_p50=float(np.median(ratios)) if ratios else 0.0,
                paired_ratio_min=min(ratios) if ratios else 0.0,
                paired_ratio_max=max(ratios) if ratios else 0.0,
            )
            samples["baseline_seconds"] = base
            samples["paired_ratios"] = ratios
        return ScenarioResult(name=self.name, metrics=metrics,
                              samples=samples, config=cfg)

    async def run_server(self, ctx, overrides) -> None:
        cfg = self.config(overrides)
        size = int(cfg["message_bytes"])
        total = int(cfg["warmup"]) + int(cfg["iterations"])
        if bool(cfg.get("paired")):
            total *= 2
        sink = _make_sink(size, cfg.get("payload", "host"))
        await ctx.signal_ready()
        for _ in range(total):
            await ctx.server.arecv(sink, STRIPED_DATA_TAG, ctx.tag_mask)
        await ctx.flush_endpoint()


class Flooded(Scenario):
    """Overload robustness (DESIGN.md §18): a burst of unmatched eager
    sends against a peer that posts its receives LATE.  With
    ``STARWAY_FC_WINDOW`` set the receiver's unexpected-queue residency
    stays bounded by the window (``peak_unexp_bytes``, sampled live on
    the receiving worker while the flood is in flight) and the sender
    parks (``sends_parked``); with it unset the queue grows with the
    whole burst -- run the CLI once with and once without the env to see
    bounded-vs-unbounded receiver memory.  ``paired=True``
    (``--paired-baseline``) interleaves a MATCHED phase (receives posted
    before the burst) with every flood iteration over the same conn, so
    one run also shows that flow control adds no measurable cost to the
    matched-recv fast path (``matched_msgs_per_s`` with fc on vs a run
    with it off)."""

    name = "flooded"
    description = "Unmatched-send overload: bounded receiver memory + matched fast-path cost (DESIGN.md §18)."
    defaults = {"message_bytes": 16 << 10, "messages": 96, "warmup": 1,
                "iterations": 4, "hold_s": 0.4, "paired": False}

    async def run_client(self, ctx, overrides) -> ScenarioResult:
        cfg = self.config(overrides)
        size, nmsg = int(cfg["message_bytes"]), int(cfg["messages"])
        warmup, iters = int(cfg["warmup"]), int(cfg["iterations"])
        paired = bool(cfg.get("paired"))
        payloads = [np.full(size, i % 251, dtype=np.uint8)
                    for i in range(nmsg)]
        stats_buf = np.zeros(4096, dtype=np.uint8)
        flood_secs: list[float] = []
        matched_secs: list[float] = []
        peaks: list[int] = []
        for it in range(warmup + iters):
            stats_fut = ctx.client.arecv(stats_buf, FLOOD_STATS_TAG,
                                         ctx.tag_mask)
            t0 = time.perf_counter()
            await asyncio.gather(
                *(ctx.client.asend(p, FLOOD_DATA_TAG) for p in payloads))
            _, ln = await stats_fut
            await ctx.flush()
            dt = time.perf_counter() - t0
            stats = _decode_ctl(stats_buf, ln)
            if it >= warmup:
                flood_secs.append(dt)
                peaks.append(int(stats.get("peak", 0)))
            if paired:
                # Matched phase: the server posts first and GOes us.
                _, ln = await ctx.client.arecv(stats_buf, FLOOD_STATS_TAG,
                                               ctx.tag_mask)
                t0 = time.perf_counter()
                await asyncio.gather(
                    *(ctx.client.asend(p, FLOOD_DATA_TAG) for p in payloads))
                await ctx.flush()
                if it >= warmup:
                    matched_secs.append(time.perf_counter() - t0)
        metrics = {
            "peak_unexp_bytes": max(peaks) if peaks else 0,
            "flood_seconds_p50": float(np.median(flood_secs))
            if flood_secs else 0.0,
            "flood_msgs_per_s": (nmsg / float(np.median(flood_secs)))
            if flood_secs else 0.0,
        }
        samples = {"flood_seconds": flood_secs,
                   "peak_unexp_bytes": [float(p) for p in peaks]}
        if paired:
            metrics["matched_seconds_p50"] = (float(np.median(matched_secs))
                                              if matched_secs else 0.0)
            metrics["matched_msgs_per_s"] = (
                nmsg / float(np.median(matched_secs)) if matched_secs else 0.0)
            samples["matched_seconds"] = matched_secs
        return ScenarioResult(name=self.name, metrics=metrics,
                              samples=samples, config=cfg)

    async def run_server(self, ctx, overrides) -> None:
        cfg = self.config(overrides)
        size, nmsg = int(cfg["message_bytes"]), int(cfg["messages"])
        total = int(cfg["warmup"]) + int(cfg["iterations"])
        hold = float(cfg["hold_s"])
        paired = bool(cfg.get("paired"))
        sinks = [np.empty(size, dtype=np.uint8) for _ in range(nmsg)]
        worker = ctx.server._server

        def unexp_now() -> int:
            g = worker.gauges_snapshot()
            return sum(int(c.get("unexp_bytes", 0))
                       for c in g.get("conns", {}).values())

        await ctx.signal_ready()
        for _ in range(total):
            # Flood phase: hold the receives back and sample residency.
            peak = 0
            deadline = time.perf_counter() + hold
            while time.perf_counter() < deadline:
                peak = max(peak, unexp_now())
                await asyncio.sleep(0.02)
            recvs = [ctx.server.arecv(s, FLOOD_DATA_TAG, ctx.tag_mask)
                     for s in sinks]
            await ctx.server.asend(ctx.endpoint, _encode_ctl({"peak": peak}),
                                   FLOOD_STATS_TAG)
            await asyncio.gather(*recvs)
            if paired:
                # Matched phase: receives first, then GO.
                recvs = [ctx.server.arecv(s, FLOOD_DATA_TAG, ctx.tag_mask)
                         for s in sinks]
                await ctx.server.asend(ctx.endpoint, _encode_ctl({"go": 1}),
                                       FLOOD_STATS_TAG)
                await asyncio.gather(*recvs)
        await ctx.flush_endpoint()


class Reshard(Scenario):
    """swshard array redistribution (DESIGN.md §20): the measuring side
    (rank 0) owns an N-byte array row-sharded into ``blocks`` shards,
    the sink side (rank 1) wants it column-sharded -- the transposed-
    ownership retile every piece of the array must cross for.  The
    planner compiles the block intersections into rounds of <=budget
    transfers and the executor drives them with flush barriers between
    rounds, so peak staging per role stays O(shard) = O(N/blocks), not
    O(N) -- ``peak_staging_bytes`` (the live reshard_staging gauge) vs
    ``staging_bound_bytes`` in the metrics shows the §20 memory bound
    holding at full bandwidth.  Host numpy path: the schedule machinery
    itself is jax-free; jax arrays enter via reshard.redistribute()."""

    name = "reshard"
    description = "Sharding->sharding redistribution: GB/s under the O(shard) staging bound (DESIGN.md §20)."
    defaults = {"message_bytes": 256 << 20, "blocks": 8, "warmup": 1,
                "iterations": 3}

    @staticmethod
    def _specs(size: int, blocks: int):
        from ..reshard import Block, ShardSpec

        rows = int(blocks)
        cols = max(rows, int(size) // rows)
        shape = (rows, cols)  # one row per source shard
        src = ShardSpec(shape, 1, [
            Block(0, ((r, r + 1), (0, cols))) for r in range(rows)])
        step = cols // rows
        edges = [c * step for c in range(rows)] + [cols]
        dst = ShardSpec(shape, 1, [
            Block(1, ((0, rows), (edges[c], edges[c + 1])))
            for c in range(rows)])
        return shape, src, dst

    @staticmethod
    def _lease():
        from ..reshard import tags

        # Direct construction (no registry acquire): both roles -- which
        # share one process in loopback -- coordinate on the same slot.
        return tags.TagLease(RESHARD_LEASE_SLOT)

    async def run_client(self, ctx, overrides) -> ScenarioResult:
        from ..reshard import build_plan, executor

        cfg = self.config(overrides)
        size, blocks = int(cfg["message_bytes"]), int(cfg["blocks"])
        warmup, iters = int(cfg["warmup"]), int(cfg["iterations"])
        shape, src, dst = self._specs(size, blocks)
        plan = build_plan(src, dst)
        lease = self._lease()
        # Tiled 0..250 pattern with no multi-GiB uint64 temporaries (the
        # scenario's selling point is bounded staging; its own setup
        # must not allocate O(8 x array)).
        data = np.resize(np.arange(251, dtype=np.uint8),
                         shape[0] * shape[1]).reshape(shape)

        def read_box(box):
            (r0, r1), (c0, c1) = box
            return np.ascontiguousarray(data[r0:r1, c0:c1]).reshape(-1)

        def write_box(box, view):  # rank 0 is a pure sender
            raise AssertionError("unexpected receive on the source rank")

        stats_buf = np.zeros(4096, dtype=np.uint8)
        secs: list[float] = []
        peaks: list[int] = []
        rounds = 0
        for i in range(warmup + iters):
            stats_fut = ctx.client.arecv(stats_buf, RESHARD_STATS_TAG,
                                         ctx.tag_mask)
            t0 = time.perf_counter()
            st = await executor.execute(
                plan, 0, {1: ctx.client}, read_box, write_box,
                tag_of=lambda t: lease.data_tag(t.tag_off))
            _, ln = await stats_fut
            dt = time.perf_counter() - t0
            peer = _decode_ctl(stats_buf, ln)
            if i >= warmup:
                secs.append(dt)
                rounds = st["rounds"]
                # Worst single ROLE's own high-water: per-invocation
                # peaks, not the process-global gauge -- in loopback
                # both roles share one process and would double-count.
                peaks.append(max(int(st["peak_staging"]),
                                 int(peer.get("peak", 0))))
        await ctx.flush()
        total = sum(secs)
        moved = plan.total_wire_nbytes()
        return ScenarioResult(
            name=self.name,
            metrics={
                "total_seconds": total,
                "avg_seconds_per_iter": total / iters if iters else 0.0,
                "avg_gbps": (moved * iters / total / 1e9) if total > 0 else 0.0,
                "rounds": rounds,
                "transfers": len(plan.transfers),
                "wire_bytes_per_iter": moved,
                "peak_staging_bytes": max(peaks) if peaks else 0,
                "staging_bound_bytes": 2 * plan.budget,
            },
            samples={"duration_seconds": secs,
                     "peak_staging_bytes": [float(p) for p in peaks]},
            config=cfg,
        )

    class _SinkPort:
        """Endpoint-bound server port (dp_exchange.ServerPort's shape,
        local so this module stays importable without jax)."""

        def __init__(self, server, endpoint):
            self._s = server
            self._ep = endpoint

        def asend(self, buf, tag):
            return self._s.asend(self._ep, buf, tag)

        def arecv(self, buf, tag, mask):
            return self._s.arecv(buf, tag, mask)

        def aflush(self):
            return self._s.aflush_ep(self._ep)

    async def run_server(self, ctx, overrides) -> None:
        from ..reshard import build_plan, executor

        cfg = self.config(overrides)
        size, blocks = int(cfg["message_bytes"]), int(cfg["blocks"])
        total = int(cfg["warmup"]) + int(cfg["iterations"])
        shape, src, dst = self._specs(size, blocks)
        plan = build_plan(src, dst)
        lease = self._lease()
        out = np.empty(shape, dtype=np.uint8)

        def read_box(box):  # rank 1 is a pure receiver
            raise AssertionError("unexpected send from the sink rank")

        def write_box(box, view):
            (r0, r1), (c0, c1) = box
            out[r0:r1, c0:c1] = np.frombuffer(view, dtype=np.uint8).reshape(
                (r1 - r0, c1 - c0))

        port = self._SinkPort(ctx.server, ctx.endpoint)
        await ctx.signal_ready()
        for _ in range(total):
            st = await executor.execute(
                plan, 1, {0: port}, read_box, write_box,
                tag_of=lambda t: lease.data_tag(t.tag_off))
            await ctx.server.asend(
                ctx.endpoint, _encode_ctl({"peak": int(st["peak_staging"])}),
                RESHARD_STATS_TAG)
        # Cheap correctness pin: the received retile is the source pattern.
        want = np.resize(np.arange(251, dtype=np.uint8),
                         shape[0] * shape[1]).reshape(shape)
        if not np.array_equal(out, want):
            raise AssertionError("reshard scenario: received retile corrupt")
        await ctx.flush_endpoint()


# Back-compat aliases matching the reference's registry surface.
ScenarioDefinition = Scenario

SCENARIOS: Dict[str, Scenario] = {
    s.name: s for s in (LargeArray(), SmallMessages(), PingpongFlag(),
                        StreamingDuplex(), Striped(), Flooded(), Reshard())
}

__all__ = [
    "SCENARIOS",
    "Scenario",
    "ScenarioDefinition",
    "ScenarioResult",
    "CONTROL_TAG",
    "READY_TAG",
    "DONE_TAG",
    "TAG_MASK",
]
