"""Process-local fabric registry for the in-process fast path.

The reference's loopback tests run two UCX workers in one process and UCX
negotiates a shared-memory transport between them (SURVEY.md section 4).  The
TPU build makes that path explicit: servers register their listen coordinates
here, and a client connecting to a registered address attaches directly --
messages then move with a single memcpy (host buffers) or a device-to-device
ICI transfer (jax.Array buffers) with no socket in between.

Disable with ``STARWAY_TLS`` not containing ``inproc`` to force the real TCP
path even within one process (useful for transport tests).
"""

from __future__ import annotations

import threading
import weakref

_lock = threading.Lock()
_by_sockaddr: dict[tuple[str, int], "weakref.ReferenceType"] = {}
_by_worker_id: dict[str, "weakref.ReferenceType"] = {}

_WILDCARDS = ("0.0.0.0", "::", "")


def register(worker, addr: str, port: int) -> None:
    ref = weakref.ref(worker)
    with _lock:
        _by_worker_id[worker.worker_id] = ref
        if port:
            _by_sockaddr[(addr, port)] = ref
            if addr in _WILDCARDS:
                _by_sockaddr[("127.0.0.1", port)] = ref


def register_worker(worker) -> None:
    with _lock:
        _by_worker_id[worker.worker_id] = weakref.ref(worker)


def unregister(worker) -> None:
    with _lock:
        _by_worker_id.pop(worker.worker_id, None)
        dead = [k for k, ref in _by_sockaddr.items() if ref() is worker or ref() is None]
        for k in dead:
            _by_sockaddr.pop(k, None)


def lookup_sockaddr(addr: str, port: int):
    with _lock:
        ref = _by_sockaddr.get((addr, port))
        if ref is None and addr == "localhost":
            ref = _by_sockaddr.get(("127.0.0.1", port))
        return ref() if ref is not None else None


def lookup_worker_id(worker_id: str):
    with _lock:
        ref = _by_worker_id.get(worker_id)
        return ref() if ref is not None else None
