"""swrefine runtime conformance monitor (DESIGN.md §22).

The thin in-process half of the swrefine plane: with ``STARWAY_MONITOR=1``
every traced worker's protocol-event channel (swtrace ``EV_PROTO``;
emitted identically by both engines) is replayed through the protocol
monitor automaton that ``analysis/refine.py`` compiles from the engines'
own extracted state machines -- the same automaton the static gate runs
against the checked-in event corpus.  A divergence here means the running
engine and the verified model disagree: it is recorded, the §13 flight
recorder dumps, and ``assert_clean()`` fails the run hard (the chaos
soaks call it every run; ``swtrace.retire`` checks each worker
automatically at close).

This module is deliberately tiny: the automaton, the event grammar, and
the replay semantics live in ``starway_tpu.analysis.refine`` (stdlib-only,
imported lazily and only when the monitor is armed) so the gate and the
runtime can never drift apart -- one monitor, two drivers.  Off path
(env unset): nothing here is ever imported by the data plane.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from .. import config

logger = logging.getLogger("starway_tpu")

_lock = threading.Lock()
_violations: list = []
_seen_keys: set = set()  # dedup: retire-checked rings reappear in check_all
_witnessed: set = set()
_monitor = None  # compiled refine.Monitor, or False once compile failed


def active() -> bool:
    return config.monitor_enabled()


def _compiled():
    """The compiled monitor automaton (one per process; the model is
    static).  Compile failure disables checking for the process -- the
    monitor must never take a soak down with a tooling error -- but is
    loudly logged (a silent None would be a vacuous pass)."""
    global _monitor
    with _lock:
        if _monitor is None:
            try:
                from ..analysis import refine

                mon, problems = refine.compile_monitor(runtime=True)
                for p in problems:
                    logger.warning("starway: monitor compile: %s", p)
                _monitor = mon if mon is not None else False
            except Exception as e:  # pragma: no cover - tooling failure
                logger.error("starway: protocol monitor unavailable: %s", e)
                _monitor = False
        return _monitor or None


def check_events(events, label: str = "") -> list:
    """Replay one ring's events (swtrace 7-tuples) through the monitor;
    record and return any violations.  Safe on non-proto rings (no
    EV_PROTO events = nothing to check)."""
    mon = _compiled()
    if mon is None:
        return []
    viols, seen = mon.replay(events, label=label)
    fresh = []
    with _lock:
        _witnessed.update(seen)
        for v in viols:
            # One divergence, one record: a ring checked at worker
            # retirement shows up again in check_all()'s dump_all sweep.
            key = (v.label, v.conn, v.index, v.cls, v.message)
            if key not in _seen_keys:
                _seen_keys.add(key)
                _violations.append(v)
                fresh.append(v)
    for v in fresh:
        logger.error("starway: protocol monitor violation: %s", v.render())
    return fresh


def check_worker(worker, events=None) -> list:
    """Replay one worker's ring; on violation, dump the §13 flight
    recorder so the divergence ships with its surrounding evidence."""
    if not active():
        return []
    if events is None:
        try:
            events = worker.trace_events()
        except Exception:
            return []
    label = getattr(worker, "trace_label", "worker")
    viols = check_events(events, label=label)
    if viols:
        from . import swtrace

        worker._faulted = True
        swtrace.flight_dump("monitor-violation", worker, viols[0].render())
    return viols


def check_all() -> list:
    """Replay every traced ring this process has seen (live + retired) --
    the chaos soaks' per-run conformance checkpoint."""
    if not active():
        return []
    from . import swtrace

    out = []
    for dump in swtrace.dump_all():
        out.extend(check_events(dump["events"], label=dump["worker"]))
    return out


def violations() -> list:
    with _lock:
        return list(_violations)


def witnessed() -> set:
    """Model transitions witnessed by every ring checked so far (the
    runtime side of refine's transition-coverage accounting)."""
    with _lock:
        return set(_witnessed)


def assert_clean() -> None:
    """Fail hard on any recorded violation (soaks call this last)."""
    viols = violations()
    if viols:
        raise AssertionError(
            "protocol monitor violations:\n"
            + "\n".join(v.render() for v in viols))


def reset() -> None:
    """Drop recorded state (test isolation).  The compiled automaton is
    kept -- the model does not change within a process."""
    with _lock:
        _violations.clear()
        _seen_keys.clear()
        _witnessed.clear()
