"""Resilient-session state: sequence numbers, replay journal, resume.

The seed failure contract is "a dropped conn cancels every in-flight op"
(tests/test_basic.py).  ``STARWAY_SESSION=1`` (config.py) opts a
Client<->Server pair into riding through transient peer loss instead --
the way portable collective layers assume a reliable substrate
(arXiv:2112.01075) and multi-path transfer stacks re-issue work after a
path failure.  One :class:`SessionState` hangs off each session-enabled
``TcpConn`` (core/conn.py) and carries everything that must survive a
connection incarnation:

* **TX**: the next sequence number, and the bounded replay **journal** --
  the tx items (TxData/TxCtl/TxDevpull) of every sequenced frame, kept
  until the peer's cumulative ACK covers them.  Eager payloads are copied
  at framing time (the user may legally reuse the buffer once ``done``
  fires); rendezvous/chunked payloads are held by reference (delivery is
  only promised after a flush, and the journal pins the payload object
  until acked -- DESIGN.md §14 documents the stability requirement).
  When journaled-but-unacked bytes reach ``STARWAY_SESSION_JOURNAL_BYTES``
  new frames park in ``waiting`` unframed: the send *blocks* (completes
  late) rather than growing the journal without bound.
* **RX**: the cumulative in-order sequence received (``rx_cum``), the last
  cumulative ACK sent (``acked_sent``), and dedup bookkeeping -- a frame
  whose seq is already covered by ``rx_cum`` is drained and dropped
  (``dup_frames_dropped``), which is what makes replay exactly-once.
* **Lifecycle**: ``suspended`` (transport gone, resumable), ``expired``
  (grace elapsed or epoch mismatch: the terminal state), the resume
  deadline, and the client's redial backoff counter.

The wire protocol half lives in core/frames.py (T_SEQ/T_ACK and the
``sess``/``sess_id``/``sess_epoch``/``sess_ack`` handshake keys); the C++
engine implements the identical machine in native/sw_engine.cpp
(``Session``), and the two interoperate in mixed-engine pairs.
"""

from __future__ import annotations

import time
from collections import deque

from .. import config


class SessionState:
    """Per-conn session bookkeeping (both directions)."""

    __slots__ = (
        "sid", "epoch", "journal_cap", "grace",
        "tx_seq", "journal", "journal_bytes", "waiting", "peer_acked",
        "rx_cum", "acked_sent",
        "suspended", "expired", "deadline", "redial_attempt",
    )

    def __init__(self, sid: str, epoch: str):
        self.sid = sid
        self.epoch = epoch
        self.journal_cap = config.session_journal_bytes()
        self.grace = config.session_grace()
        # -- tx side
        self.tx_seq = 0            # last sequence number assigned
        self.journal: deque = deque()   # framed, unacked tx items (seq order)
        self.journal_bytes = 0
        self.waiting: deque = deque()   # unframed items parked by backpressure
        self.peer_acked = 0        # highest cumulative ACK received
        # -- rx side
        self.rx_cum = 0            # highest in-order seq fully processed
        self.acked_sent = 0        # last cumulative ACK we put on the wire
        # -- lifecycle
        self.suspended = False
        self.expired = False
        self.deadline = 0.0        # monotonic resume deadline while suspended
        self.redial_attempt = 0

    # ------------------------------------------------------------------ tx
    def next_seq(self) -> int:
        self.tx_seq += 1
        return self.tx_seq

    def has_room(self, nbytes: int) -> bool:
        """May a frame of ``nbytes`` be journaled now?  An empty journal
        always admits one frame (a single payload above the cap must not
        deadlock); parked items keep FIFO order, so nothing may be framed
        while ``waiting`` is non-empty."""
        if self.waiting:
            return False
        if not self.journal:
            return True
        return self.journal_bytes + nbytes <= self.journal_cap

    def journal_add(self, item, nbytes: int) -> None:
        self.journal.append(item)
        self.journal_bytes += nbytes

    def journal_trim(self, cum_ack: int) -> list:
        """Drop journal entries covered by the peer's cumulative ACK.
        Returns the dropped items (the caller releases any deferred
        payload pins)."""
        if cum_ack > self.peer_acked:
            self.peer_acked = cum_ack
        dropped = []
        while self.journal and self.journal[0].sess_seq <= cum_ack:
            item = self.journal.popleft()
            self.journal_bytes -= item.sess_nbytes
            dropped.append(item)
        if not self.journal:
            self.journal_bytes = 0
        return dropped

    # ----------------------------------------------------------- lifecycle
    def suspend(self) -> None:
        self.suspended = True
        self.deadline = time.monotonic() + self.grace

    def resume(self) -> None:
        self.suspended = False
        self.redial_attempt = 0

    def redial_delay(self) -> float:
        """Exponential backoff for the next redial attempt (the PR-1
        backoff shape: doubling base, capped; the caller adds jitter)."""
        self.redial_attempt += 1
        return min(1.0, 0.05 * (2 ** min(self.redial_attempt - 1, 5)))
