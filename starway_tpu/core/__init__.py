"""Host runtime core: tag matching, connections, worker engines.

Layer L2 of the build (SURVEY.md section 1) -- the TPU-native replacement for
the reference's C++ binding core (src/bindings/).  A C++ implementation of
this engine lives in ``native/`` and is preferred when built
(``STARWAY_NATIVE=1``); this Python implementation is the portable fallback
and the behavioural specification.
"""

from .endpoint import ServerEndpoint
from .engine import ClientWorker, ServerWorker

__all__ = ["ServerEndpoint", "ClientWorker", "ServerWorker"]
