"""swscope live telemetry plane: per-conn gauges + a periodic sampler.

The counter registry (core/swtrace.py) answers "what happened so far";
this module answers "what is happening NOW" (DESIGN.md §15).  Three
pieces:

* **Gauge vocabulary** -- the fixed per-conn ``GAUGE_NAMES`` below,
  implemented identically by the Python engine (``Worker.gauges_snapshot``
  computes them from live conn state under the GIL) and the C++ engine
  (rendered ON the engine thread and surfaced through the ``sw_gauges``
  ABI call, so no lock-free shadow state is needed).  Like the counter
  vocabulary it is cross-engine contract surface: swcheck's
  ``contract-trace`` pass diffs ``GAUGE_NAMES`` against ``kGaugeNames[]``.
  Two worker-level gauges ride alongside the per-conn dict:
  ``posted_recvs`` (receives queued in the matcher) and
  ``staging_pool_bytes`` (process-global device staging-pool occupancy,
  overlaid by this module the way the global counters are).

* **Sampler** -- off by default; armed by ``STARWAY_METRICS_INTERVAL``
  (or implicitly by ``STARWAY_METRICS_PATH`` / ``STARWAY_METRICS_ADDR``).
  A daemon thread snapshots every registered worker's counters + gauges
  into a bounded ring of timestamped samples (monotonic ``mono`` for
  ordering, wall ``t`` for humans), optionally appending each sample as a
  JSONL line and pushing it to connected live viewers (``python -m
  starway_tpu.metrics``).  The per-op hot path never touches this module:
  workers register once at construction (and only when the sampler is
  armed), so metrics-off adds zero per-op work -- pinned by
  tests/test_telemetry.py's overhead guard next to the swtrace one.

* **Surfacing** -- ``evaluate_perf_detail()["telemetry"]`` carries the
  worker's current gauges + the recent sample window, and flight-recorder
  dumps embed the last samples so a post-mortem shows the queue/journal
  *trend* into the failure (core/swtrace.py flight_dump).
"""

from __future__ import annotations

import json
import logging
import socket
import threading
import time
import weakref
from collections import deque
from typing import Optional

from .. import config
from . import swtrace

logger = logging.getLogger("starway_tpu")

# ------------------------------------------------------- gauge vocabulary
#
# One name list, two implementations (Worker.gauges_snapshot in
# core/engine.py and the kGaugeNames/sw_gauges pair in sw_engine.cpp);
# machine-checked by `python -m starway_tpu.analysis` (contract-trace).
# All are instantaneous per-conn values that drain to ZERO on an idle,
# flushed connection -- the invariant tests/test_telemetry.py pins.

GAUGE_NAMES = (
    "tx_queue_depth",   # items queued on the conn (incl. session-parked)
    "tx_queue_bytes",   # unwritten wire bytes across those items
    "inflight_sends",   # data items submitted but not yet fully on the wire
    "inflight_recvs",   # inbound payload streaming in + unresolved pulls
    "journal_bytes",    # session replay-journal residency (DESIGN.md §14)
    "journal_frames",   # journaled-but-unacked frames
    "stripe_pending",   # striped chunks assigned to this lane but not yet
    #                     fully written (primary rows add undisbursed
    #                     chunks; DESIGN.md §17 rail balance)
    "unexp_bytes",      # receiver-side unexpected-queue bytes this conn
    #                     has spilled and not yet granted back (§18;
    #                     populated only with fc or the cap armed -- the
    #                     seed path carries no accounting)
    "credits_avail",    # sender-side §18 credit remaining toward the peer
    #                     (0 when flow control is off or exhausted)
    "retx_pending",     # §19 NACK-requeued striped chunks not yet
    #                     rewritten (drains to 0 once every retransmit
    #                     is back on a lane; primary rows only)
    "zc_pending",       # §24 MSG_ZEROCOPY sends awaiting the kernel's
    #                     errqueue completion (native-only lever; this
    #                     engine declares the name and reports 0)
)


def _item_remaining(item) -> int:
    try:
        return int(item.remaining)
    except Exception:
        return 0


def _item_total(item) -> int:
    try:
        return int(item.total)
    except Exception:
        return len(getattr(item, "data", b""))


def conn_gauges(conn) -> dict:
    """GAUGE_NAMES snapshot for one Python-engine conn.  Reads live
    engine-thread state: every container is snapshotted via ``list()``
    (GIL-atomic for deques) and a torn read only skews one sample --
    telemetry tolerates that, the engine never does."""
    gauges = dict.fromkeys(GAUGE_NAMES, 0)
    tx = getattr(conn, "tx", None)
    if tx is None:  # inproc conns deliver synchronously: nothing queues
        return gauges
    from .conn import TxCtl  # local: telemetry must not import at module load

    try:
        items = list(tx)
        sess = getattr(conn, "sess", None)
        waiting = list(sess.waiting) if sess is not None else []
        waiting += list(getattr(conn, "fc_waiting", ()))  # §18 parked sends
        gauges["tx_queue_depth"] = len(items) + len(waiting)
        gauges["tx_queue_bytes"] = (
            sum(_item_remaining(i) for i in items)
            + sum(_item_total(i) for i in waiting))
        gauges["inflight_sends"] = (
            sum(1 for i in items
                if not isinstance(i, TxCtl) and _item_remaining(i) > 0)
            + sum(1 for i in waiting if not isinstance(i, TxCtl)))
        gauges["inflight_recvs"] = (
            (1 if getattr(conn, "_rx_msg", None) is not None else 0)
            + len(getattr(conn, "_remote_msgs", ())))
        if sess is not None:
            gauges["journal_bytes"] = int(sess.journal_bytes)
            gauges["journal_frames"] = len(sess.journal)
        from .lane import StripeFeeder  # local, like TxCtl above

        pending = sum(1 for i in items
                      if isinstance(i, StripeFeeder) and i.src is not None)
        grp = getattr(conn, "stripe", None)
        if grp is not None:
            pending += sum(len(s.pending) for s in grp.by_id.values()
                           if not s.sacked and not s.failed)
        gauges["stripe_pending"] = pending
        gauges["unexp_bytes"] = int(getattr(conn, "fc_unexp", 0))
        credits = int(getattr(conn, "fc_credits", 0))
        gauges["credits_avail"] = credits if credits > 0 else 0
        gauges["retx_pending"] = len(getattr(conn, "retx_offs", ()) or ())
    except Exception:
        pass  # a conn torn down mid-snapshot yields a partial sample
    return gauges


def staging_pool_bytes() -> int:
    """Process-global device staging-pool occupancy (device.py), overlaid
    onto every worker snapshot like the global counters are.  0 when the
    device layer has never loaded (no jax import from core/)."""
    import sys

    dev = sys.modules.get("starway_tpu.device")
    if dev is None:
        return 0
    pool = getattr(dev, "_staging_pool", None)
    return int(getattr(pool, "_held", 0)) if pool is not None else 0


def _reshard_staging() -> dict:
    """Process-global swshard transfer-staging occupancy + high-water
    mark (reshard/executor.py; DESIGN.md §20's asserted memory bound).
    Zeros when the reshard layer has never loaded -- core/ must not
    import it (layering-reshard, the jax-rule twin)."""
    import sys

    ex = sys.modules.get("starway_tpu.reshard.executor")
    if ex is None:
        return {"now": 0, "peak": 0}
    try:
        return ex.staging_snapshot()
    except Exception:
        return {"now": 0, "peak": 0}


def merge_global_gauges(snap: dict) -> dict:
    """Overlay the process-global gauges onto a worker snapshot (the
    native engine reports 0 for them, like its counter twin)."""
    snap["staging_pool_bytes"] = staging_pool_bytes()
    st = _reshard_staging()
    snap["reshard_staging_bytes"] = st["now"]
    snap["reshard_staging_peak"] = st["peak"]
    return snap


# --------------------------------------------------------------- sampler


def armed() -> bool:
    """Sampler armed for new workers?  Checked once per WORKER (at
    construction) -- never per op, so the off path is env-lookup-free on
    the data path (the PR-4 armed-state caching discipline)."""
    return (config.metrics_interval() > 0 or bool(config.metrics_path())
            or bool(config.metrics_addr()) or config.stall_ms() > 0)


def interval() -> float:
    """Effective sampling period: the env knob, or 1 s when only a
    path/addr (or the §25 stall sentinel) armed the sampler.  An armed
    sentinel caps the period at half its threshold so a wedge is
    detected within ~1.5x the configured STARWAY_STALL_MS."""
    iv = config.metrics_interval()
    iv = iv if iv > 0 else 1.0
    stall = config.stall_ms()
    if stall > 0:
        iv = min(iv, max(stall / 2e3, 0.01))
    return iv


_lock = threading.Lock()
# Serializes whole samples (stamp + ring append + emit): the daemon
# thread and explicit sample_now() callers (bench teardown, chaos
# scripts, tests) may overlap, and an unserialized pair could land in
# the ring/JSONL out of mono order -- the monotonicity consumers assert.
_sample_lock = threading.Lock()
_workers: list = []          # weakref.ref(worker), registration order
_samples: Optional[deque] = None   # bounded sample ring (armed runs only)
_thread: Optional[threading.Thread] = None
_stop = threading.Event()    # the CURRENT thread's stop flag (see _run)
_feed_clients: list = []     # sockets of live viewers
_feed_listener: Optional[socket.socket] = None


def register_worker(worker) -> None:
    """Called once per worker at construction (both engines).  No-op when
    the sampler is not armed -- the default path carries no registry."""
    if not armed():
        return
    global _samples
    with _lock:
        if _samples is None:
            _samples = deque(maxlen=config.metrics_ring_size())
        _workers.append(weakref.ref(worker))
        _workers[:] = [r for r in _workers if r() is not None]
    _ensure_thread()


def _live_workers() -> list:
    with _lock:
        refs = list(_workers)
    return [w for w in (r() for r in refs) if w is not None]


def sample_now() -> dict:
    """Take one sample across every registered worker, append it to the
    ring, and emit it (JSONL / live feed).  Also the test hook: samplers
    in tests call this directly instead of racing the thread -- the
    sample lock keeps the ring and the JSONL stream mono-ordered when
    they do overlap."""
    with _sample_lock:
        workers = {}
        for w in _live_workers():
            try:
                workers[w.trace_label] = {
                    "counters": w.counters_snapshot(),
                    "gauges": w.gauges_snapshot(),
                    # §25 swpulse: the compact percentile view, not the
                    # raw buckets -- samples stay JSONL-sized.
                    "hists": swtrace.hist_summary(w.hists_snapshot()),
                }
            except Exception:
                continue  # a worker mid-close yields no sample this tick
        sample = {"t": time.time(), "mono": time.perf_counter(),
                  "workers": workers}
        with _lock:
            if _samples is not None:
                _samples.append(sample)
        _emit(sample)
    return sample


def recent_samples(limit: int = 32) -> list:
    """The last ``limit`` samples (newest last); [] when the sampler was
    never armed.  Flight-recorder dumps embed this trend."""
    with _lock:
        if _samples is None:
            return []
        return list(_samples)[-limit:]


def detail_for(worker) -> dict:
    """The ``evaluate_perf_detail()["telemetry"]`` payload for one
    worker: its live gauges plus the recent sample window."""
    try:
        gauges = worker.gauges_snapshot()
    except Exception:
        gauges = {}
    return {
        "armed": armed(),
        "interval": interval() if armed() else 0.0,
        "gauges": gauges,
        "samples": recent_samples(),
    }


def reset() -> None:
    """Drop sampler state (test isolation).  The thread, if running,
    exits on its next tick."""
    global _samples, _thread, _feed_listener
    _stop.set()
    with _lock:
        _workers.clear()
        _samples = None
        _thread = None
        _stall_reports.clear()
        _stall_state.clear()
        listener, _feed_listener = _feed_listener, None
        clients = list(_feed_clients)
        _feed_clients.clear()
    for s in ([listener] if listener else []) + clients:
        try:
            s.close()
        except OSError:
            pass


# ------------------------------------------------------ §25 stall sentinel
#
# Armed only by STARWAY_STALL_MS (stall_ms() > 0): the sampler thread,
# once per tick, checks every registered worker for no-progress
# conditions.  Python workers expose the scan itself (Worker.stall_scan:
# flush barriers, credit parks, stripe pins, unexpected growth -- it
# bumps stall_alerts and records EV_STALL); the native engine
# self-detects inside its progress loop, so here its stall_alerts DELTA
# is what surfaces the report.  Either way the unified answer is a
# structured report (+ last ring events) in `_stall_reports`, a warning
# log line, and a §13 flight-recorder dump with the `stall` trigger.

_stall_reports: deque = deque(maxlen=64)
_stall_state = weakref.WeakKeyDictionary()  # worker -> (progress_sum, alerts)


def stall_reports(limit: int = 64) -> list:
    """The most recent stall-sentinel reports (newest last); [] unless
    STARWAY_STALL_MS armed the sentinel and a wedge was flagged."""
    with _lock:
        return list(_stall_reports)[-limit:]


def _progress_sum(counters: dict) -> int:
    """Monotone work signal: any counter moving between two ticks means
    the worker is progressing, not wedged.  stall_alerts itself is
    excluded (an alert must not read as progress)."""
    return sum(v for k, v in counters.items()
               if k != "stall_alerts" and isinstance(v, int))


def _stall_tick(threshold_s: float) -> None:
    for w in _live_workers():
        try:
            ctr = w.counters_snapshot()
        except Exception:
            continue
        sum_now = _progress_sum(ctr)
        alerts_now = int(ctr.get("stall_alerts", 0))
        prev = _stall_state.get(w)
        try:
            _stall_state[w] = (sum_now, alerts_now)
        except TypeError:
            continue  # un-weakrefable duck: no baseline, no scan
        if prev is None:
            continue  # first sight establishes the baseline only
        progressed = sum_now != prev[0]
        reports: list = []
        scan = getattr(w, "stall_scan", None)
        if scan is not None:
            try:
                reports = scan(threshold_s, progressed)
            except Exception:
                logger.debug("starway stall scan failed", exc_info=True)
        elif alerts_now > prev[1]:
            # Native worker: its run() loop already bumped stall_alerts
            # and recorded EV_STALL into the engine ring -- reshape the
            # ring records into the unified report.
            try:
                evs = [e for e in w.trace_events()
                       if e[1] == swtrace.EV_STALL]
            except Exception:
                evs = []
            for e in evs[-(alerts_now - prev[1]):]:
                reports.append({"worker": w.trace_label, "reason": e[5],
                                "conn": int(e[3]), "age_ms": int(e[4]),
                                "detail": "native stall sentinel"})
            if not reports:  # ring unarmed/wrapped: delta is the report
                reports.append({"worker": w.trace_label,
                                "reason": swtrace.STALL_REASONS[0],
                                "conn": 0, "age_ms": 0,
                                "detail": "native stall sentinel "
                                          "(ring unavailable)"})
        if not reports:
            continue
        try:
            tail = [list(e) for e in w.trace_events()[-8:]]
        except Exception:
            tail = []
        for r in reports:
            r.setdefault("worker", w.trace_label)
            r["events"] = tail  # last protocol/trace events from the ring
            with _lock:
                _stall_reports.append(r)
            logger.warning(
                "starway stall sentinel: %s on %s conn %s after %dms (%s)",
                r["reason"], r["worker"], r["conn"], r["age_ms"],
                r["detail"])
        swtrace.flight_dump("stall", w, reports[-1]["reason"])


# ---------------------------------------------------------- emit channels


def _emit(sample: dict) -> None:
    line = json.dumps(sample, separators=(",", ":")) + "\n"
    path = config.metrics_path()
    if path:
        try:
            with open(path, "a") as f:
                f.write(line)
        except OSError:
            logger.debug("starway telemetry: JSONL append failed", exc_info=True)
    with _lock:
        clients = list(_feed_clients)
    dead = []
    for s in clients:
        try:
            # Sockets are non-blocking: a viewer whose buffer is full is
            # dropped on the spot -- one stalled reader must never stall
            # the sampler (this runs under _sample_lock).
            s.sendall(line.encode())
        except (BlockingIOError, OSError):
            dead.append(s)
    if dead:
        with _lock:
            for s in dead:
                if s in _feed_clients:
                    _feed_clients.remove(s)
        for s in dead:
            try:
                s.close()
            except OSError:
                pass


def _ensure_thread() -> None:
    global _thread, _stop, _feed_listener
    with _lock:
        if _thread is not None and _thread.is_alive():
            return
        # Fresh stop event PER thread: reset() sets the old one and an
        # old thread mid-tick keeps its own (already-set) event, so a
        # re-arm can never revive it -- exactly one sampler runs.
        stop = threading.Event()
        _stop = stop
        addr = config.metrics_addr()
        if addr and _feed_listener is None:
            try:
                host, _, port = addr.rpartition(":")
                listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                listener.bind((host or "127.0.0.1", int(port)))
                listener.listen(8)
                # Non-blocking: _accept_feed_clients polls each tick; a
                # blocking accept would stretch the sampling period.
                listener.setblocking(False)
                _feed_listener = listener
            except (OSError, ValueError):
                logger.warning("starway telemetry: cannot listen on %s", addr)
        _thread = threading.Thread(target=_run, args=(stop,),
                                   name="starway-telemetry", daemon=True)
        _thread.start()


def _run(stop: threading.Event) -> None:
    # swcheck: allow(blocking-call): sampler daemon thread, never the engine thread
    while not stop.wait(interval()):
        try:
            if not _live_workers():
                continue  # every worker gone: idle tick, ring unchanged
            _accept_feed_clients()
            sample_now()
            stall = config.stall_ms()
            if stall > 0:
                _stall_tick(stall / 1e3)
        except Exception:
            logger.debug("starway telemetry tick failed", exc_info=True)


def _accept_feed_clients() -> None:
    listener = _feed_listener
    if listener is None:
        return
    while True:
        try:
            s, _ = listener.accept()
        except (socket.timeout, OSError):
            return
        s.setblocking(False)  # a stalled viewer is dropped, never waited on
        with _lock:
            _feed_clients.append(s)


# ---------------------------------------------------------- report helper


def summarize(samples: list) -> dict:
    """Time-series summary for the bench JSON report (--metrics): peaks
    and means of the load-bearing gauges across a run's samples."""
    n = 0
    peak_depth = peak_journal = peak_qbytes = 0
    sum_depth = 0
    for sample in samples:
        for wk in sample.get("workers", {}).values():
            for g in wk.get("gauges", {}).get("conns", {}).values():
                n += 1
                depth = int(g.get("tx_queue_depth", 0))
                sum_depth += depth
                peak_depth = max(peak_depth, depth)
                peak_qbytes = max(peak_qbytes, int(g.get("tx_queue_bytes", 0)))
                peak_journal = max(peak_journal, int(g.get("journal_bytes", 0)))
    return {
        "samples": len(samples),
        "conn_samples": n,
        "peak_tx_queue_depth": peak_depth,
        "mean_tx_queue_depth": (sum_depth / n) if n else 0.0,
        "peak_tx_queue_bytes": peak_qbytes,
        "peak_journal_bytes": peak_journal,
    }
