"""ctypes bridge to the C++ native engine (native/sw_engine.cpp).

The C ABI this module mirrors is declared authoritatively in
``native/sw_engine.h`` — the analogue of the reference's hand-written type
stub (src/starway/_bindings.pyi), documenting every function, callback
signature, and buffer-lifetime rule crossing the language boundary.  Keep
``load()``'s argtypes in lockstep with that header.

Presents the same worker protocol as the pure-Python engine
(core/engine.py): ``NativeClientWorker`` / ``NativeServerWorker`` with
``submit_send`` / ``post_recv`` / ``submit_flush`` / ``close`` / endpoint
introspection, so the api layer swaps engines transparently.  The native
engine covers the host paths -- TCP and the negotiated same-host
shared-memory rings (``sm``, core/shmring.py) -- speaking the same wire
protocol as the Python engine, so mixed-engine processes interoperate over
either.  The in-process fast path stays in Python, which is why native
selection requires inproc-free mode (``STARWAY_TLS=tcp`` or ``tcp,sm``,
plus ``STARWAY_NATIVE=1``).  Cross-process device payloads ride the
negotiated PJRT pull extension: ALL matching lives in the engine
(descriptor records share its FIFO unexpected stream with staged DATA, so
same-tag ordering matches the Python engine); the engine surfaces
descriptors and claim events through ``sw_set_devpull``'s two callbacks
and this wrapper runs the pulls (the engine cannot -- they need a live
JAX runtime), releasing deferred flush barriers via
``sw_devpull_resolved`` (see sw_engine.h "devpull" and DESIGN.md §7).

Lifetime/GIL notes: callbacks cross from the engine thread through ctypes
trampolines, which acquire the GIL.  Each pending op holds its Python buffer
and callbacks in a registry keyed by an integer handle passed through the
C ``ctx`` pointer, so nothing is garbage-collected mid-flight.
"""

from __future__ import annotations

import ctypes
import itertools
import json
import threading
import uuid
import weakref
from typing import Optional

from .. import config, perf
from ..errors import StarwayStateError
from . import state, swtrace, telemetry
from .engine import logger

_lib = None
_lib_err: Optional[str] = None

_DONE_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p)
_FAIL_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_char_p)
_RECV_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64)
_ACCEPT_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_uint64)
_STATUS_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_char_p)
_DEVPULL_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_uint64,
                               ctypes.c_uint64, ctypes.POINTER(ctypes.c_char),
                               ctypes.c_uint64, ctypes.c_uint64,
                               ctypes.c_int, ctypes.c_uint64)
_DEVPULL_CLAIM_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_uint64,
                                     ctypes.c_uint64, ctypes.c_int)
_EVENT_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_char_p,
                             ctypes.c_uint64)


def load() -> Optional[ctypes.CDLL]:
    """Load (building on first use) the native engine; None if unavailable."""
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    try:
        from .. import native_build

        path = native_build.ensure_built()
        lib = ctypes.CDLL(str(path))
        lib.sw_version.restype = ctypes.c_char_p
        lib.sw_client_new.restype = ctypes.c_void_p
        lib.sw_client_new.argtypes = [ctypes.c_char_p]
        lib.sw_server_new.restype = ctypes.c_void_p
        lib.sw_server_new.argtypes = [ctypes.c_char_p]
        lib.sw_client_connect.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
            _STATUS_CB, ctypes.c_void_p,
        ]
        lib.sw_server_listen.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
        lib.sw_server_set_accept_cb.argtypes = [ctypes.c_void_p, _ACCEPT_CB, ctypes.c_void_p]
        lib.sw_send.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64,
            ctypes.c_uint64, _DONE_CB, _FAIL_CB, ctypes.c_void_p,
            _DONE_CB, ctypes.c_void_p, ctypes.c_double,
        ]
        lib.sw_recv.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_uint64, _RECV_CB, _FAIL_CB, ctypes.c_void_p,
            ctypes.c_double,
        ]
        lib.sw_flush.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int, _DONE_CB, _FAIL_CB,
            ctypes.c_void_p, ctypes.c_double,
        ]
        lib.sw_close.argtypes = [ctypes.c_void_p, _DONE_CB, ctypes.c_void_p]
        lib.sw_status.argtypes = [ctypes.c_void_p]
        lib.sw_primary_conn.argtypes = [ctypes.c_void_p]
        lib.sw_primary_conn.restype = ctypes.c_uint64
        lib.sw_list_conns.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64), ctypes.c_int
        ]
        lib.sw_conn_info.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_int
        ]
        lib.sw_counters.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int
        ]
        lib.sw_trace.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int
        ]
        lib.sw_gauges.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int
        ]
        lib.sw_hists.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int
        ]
        lib.sw_free.argtypes = [ctypes.c_void_p]
        lib.sw_set_devpull.argtypes = [
            ctypes.c_void_p, ctypes.c_int, _DEVPULL_CB, _DEVPULL_CLAIM_CB,
            ctypes.c_void_p,
        ]
        lib.sw_devpull_resolved.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int
        ]
        lib.sw_devpull_purge.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.sw_send_devpull.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_char_p,
            ctypes.c_uint64, _DONE_CB, _FAIL_CB, ctypes.c_void_p,
        ]
        lib.sw_set_event_cb.argtypes = [
            ctypes.c_void_p, _EVENT_CB, ctypes.c_void_p
        ]
        # Optional (older .so builds lack them): portable sm cursor atomics
        # for the Python engine on non-TSO architectures (core/shmring.py).
        if hasattr(lib, "sw_atomic_load_u64"):
            lib.sw_atomic_load_u64.argtypes = [ctypes.c_void_p]
            lib.sw_atomic_load_u64.restype = ctypes.c_uint64
            lib.sw_atomic_store_u64.argtypes = [ctypes.c_void_p,
                                                ctypes.c_uint64]
        # Optional: hardware CRC32C for the §19 integrity plane -- the
        # Python engine checksums through the same export the C++ engine
        # uses internally, so mixed pairs agree bit-for-bit
        # (core/frames.py crc32c).
        if hasattr(lib, "sw_crc32c"):
            lib.sw_crc32c.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                      ctypes.c_uint32]
            lib.sw_crc32c.restype = ctypes.c_uint32
        # Optional: the §21 swcompose differential decode harness -- a
        # pure structural decoder the wirefuzz analysis pass diffs
        # against frames.decode_stream byte-for-byte.
        if hasattr(lib, "sw_wire_decode"):
            lib.sw_wire_decode.argtypes = [
                ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int,
                ctypes.c_char_p, ctypes.c_int
            ]
        # Optional: the §24 swfast capability probe (bit0 io_uring, bit1
        # MSG_ZEROCOPY, bit2 busy-poll) -- which opt-in hot-path levers
        # this build+kernel can actually engage (tests/test_fast.py and
        # the CI capability check consume it).
        if hasattr(lib, "sw_fast_probe"):
            lib.sw_fast_probe.argtypes = []
            lib.sw_fast_probe.restype = ctypes.c_uint64
        _lib = lib
    except Exception as e:  # toolchain/build failure => Python engine
        _lib_err = str(e)
        logger.debug("starway native engine unavailable: %s", e)
    return _lib


def available() -> bool:
    return load() is not None


def fast_probe() -> int:
    """§24 swfast capability bitmask: bit0 io_uring (runtime probe OK),
    bit1 MSG_ZEROCOPY, bit2 bounded busy-poll.  0 when the native lib is
    absent or predates the probe."""
    lib = load()
    if lib is None or not hasattr(lib, "sw_fast_probe"):
        return 0
    return int(lib.sw_fast_probe())


def atomics(build: bool = True) -> Optional[tuple]:
    """(load_acquire_u64, store_release_u64) ctypes fns, or None (no
    native lib, or an old build without them).  Used by core/shmring.py to
    carry sm on non-x86 hosts.

    ``build=False``: only use an ALREADY-BUILT artifact — never compile.
    The sm capability probe runs on the connection-setup path, where a
    synchronous g++ build (or a slow failed one) would stall the first
    connect of every fresh process."""
    global _lib
    if _lib is None and _lib_err is None and not build:
        from .. import native_build

        if native_build.prebuilt() is None:
            return None
    lib = load()
    if lib is None or not hasattr(lib, "sw_atomic_load_u64"):
        return None
    return lib.sw_atomic_load_u64, lib.sw_atomic_store_u64


def crc32c_fn(build: bool = True):
    """The native ``sw_crc32c`` ctypes fn (hardware CRC32C with software
    fallback inside the engine), or None.  ``build=False`` mirrors
    :func:`atomics`: only an already-built artifact -- the first checksum
    computes on the connection path, where a synchronous g++ build would
    stall the handshake (core/frames.py falls back to its pure-Python
    table)."""
    global _lib
    if _lib is None and _lib_err is None and not build:
        from .. import native_build

        if native_build.prebuilt() is None:
            return None
    lib = load()
    if lib is None or not hasattr(lib, "sw_crc32c"):
        return None
    return lib.sw_crc32c


# ----------------------------------------------------------- op registry

_op_ids = itertools.count(1)
_ops: dict[int, tuple] = {}
_ops_lock = threading.Lock()


def _register(*payload) -> int:
    key = next(_op_ids)
    with _ops_lock:
        _ops[key] = payload
    return key


def _take(key: int):
    with _ops_lock:
        return _ops.pop(key, None)


def _peek(key: int):
    with _ops_lock:
        return _ops.get(key)


@_DONE_CB
def _on_done(ctx):
    rec = _take(ctx)
    if rec and rec[0] is not None:
        try:
            rec[0]()
        except Exception:
            logger.exception("starway native done callback raised")


@_FAIL_CB
def _on_fail(ctx, reason):
    rec = _take(ctx)
    if rec and rec[1] is not None:
        try:
            rec[1]((reason or b"").decode())
        except Exception:
            logger.exception("starway native fail callback raised")


@_RECV_CB
def _on_recv(ctx, sender_tag, length):
    rec = _take(ctx)
    if rec and rec[0] is not None:
        try:
            rec[0](int(sender_tag), int(length))
        except Exception:
            logger.exception("starway native recv callback raised")


@_DONE_CB
def _on_release(ctx):
    # Buffer-keepalive release: the engine is finished with the payload
    # (fully written or cancelled).  Fired separately from the op's done
    # callback because rendezvous sends complete locally at header-write
    # while the payload keeps streaming.
    _take(ctx)


@_STATUS_CB
def _on_status(ctx, status):
    rec = _take(ctx)
    if rec and rec[0] is not None:
        try:
            rec[0]((status or b"").decode())
        except Exception:
            logger.exception("starway native status callback raised")


@_ACCEPT_CB
def _on_accept(ctx, conn_id):
    rec = _peek(ctx)  # persistent registration: not popped
    if rec and rec[0] is not None:
        try:
            rec[0](int(conn_id))
        except Exception:
            logger.exception("starway native accept callback raised")


@_DEVPULL_CB
def _on_devpull(ctx, conn_id, tag, body, length, msg_id, rc, recv_ctx):
    rec = _peek(ctx)  # persistent registration: not popped
    if rec and rec[0] is not None:
        try:
            rec[0](int(conn_id), int(tag),
                   ctypes.string_at(body, int(length)), int(msg_id),
                   int(rc), int(recv_ctx))
        except Exception:
            logger.exception("starway native devpull callback raised")


@_DEVPULL_CLAIM_CB
def _on_devpull_claim(ctx, remote_id, recv_ctx, flags):
    rec = _peek(ctx)  # persistent registration: not popped
    if rec and rec[1] is not None:
        try:
            rec[1](int(remote_id), int(recv_ctx), int(flags))
        except Exception:
            logger.exception("starway native devpull claim callback raised")


@_EVENT_CB
def _on_event(ctx, event, conn_id):
    rec = _peek(ctx)  # persistent registration: not popped
    if rec and rec[0] is not None:
        try:
            rec[0]((event or b"").decode(), int(conn_id))
        except Exception:
            logger.exception("starway native event callback raised")


def _is_device_sink(obj) -> bool:
    return obj is not None and hasattr(obj, "devbuf") and hasattr(obj, "accept_device")


def _timeout_s(timeout) -> float:
    """Map an optional per-op deadline to the C ABI sentinel (<= 0 = no
    deadline).  A caller-passed 0/negative timeout means "already expired"
    on the Python engine, so it becomes a minimal positive deadline here
    instead of silently disabling the clock (two engines, one contract)."""
    if timeout is None:
        return 0.0
    t = float(timeout)
    return t if t > 0 else 1e-9


# ------------------------------------------------------------- endpoints


class NativeConn:
    """Lightweight stand-in for the Python engine's conn objects: carries
    the native conn id plus lazily-fetched metadata."""

    kind = "tcp"

    def __init__(self, worker: "NativeWorkerBase", conn_id: int):
        self.worker = worker
        self.conn_id = conn_id
        self._transports: Optional[list[tuple[str, str]]] = None
        self._devpull: Optional[bool] = None

    def _info(self) -> dict:
        lib = load()
        buf = ctypes.create_string_buffer(512)
        n = lib.sw_conn_info(self.worker._h, self.conn_id, buf, 512)
        if n <= 0:
            return {}
        return json.loads(buf.value.decode())

    @property
    def peer_name(self) -> str:
        return self._info().get("name", "")

    @property
    def alive(self) -> bool:
        return bool(self._info().get("alive", 0))

    @property
    def mode(self) -> str:
        return self._info().get("mode", "socket")

    @property
    def local_addr(self) -> str:
        return self._info().get("local_addr", "")

    @property
    def local_port(self) -> int:
        return int(self._info().get("local_port", 0))

    @property
    def remote_addr(self) -> str:
        return self._info().get("remote_addr", "")

    @property
    def remote_port(self) -> int:
        return int(self._info().get("remote_port", 0))

    def transports(self) -> list[tuple[str, str]]:
        # The transport is fixed at handshake time: memoize so per-message
        # callers (evaluate_perf) pay the FFI round-trip once.
        if self._transports is None:
            if self._info().get("transport") == "sm":
                self._transports = [("shm", "sm")]
            else:
                dev = "lo" if self.remote_addr.startswith("127.") else "eth0"
                self._transports = [(dev, "tcp+native")]
        return self._transports

    @property
    def devpull_ok(self) -> bool:
        # Handshake-fixed, like the transport: memoize the FFI round-trip.
        if self._devpull is None:
            self._devpull = bool(self._info().get("devpull", 0))
        return self._devpull

    @property
    def rail_count(self) -> int:
        """Secondary lanes attached to this (primary) conn (DESIGN.md
        §17); live value, not memoized -- rails can die and re-attach."""
        return int(self._info().get("rails", 0))


# --------------------------------------------------------------- workers


class _PendingPull:
    """Receiver-side record for one surfaced DEVPULL descriptor (native
    engine analogue of the Python engine's matcher-held remote msgs)."""

    __slots__ = ("desc", "conn_id", "msg_id", "tag", "nbytes", "claimed",
                 "array", "failed", "discard", "resolved")

    def __init__(self, desc: dict, conn_id: int, msg_id: int, tag: int):
        self.desc = desc
        self.conn_id = conn_id
        self.msg_id = msg_id
        self.tag = tag
        self.nbytes = int(desc["n"])
        self.claimed = None  # (user_done, fail, mv_or_None, sink_or_None)
        self.array = None    # pulled payload (complete, unclaimed)
        self.failed = False
        self.discard = False
        # The claimed receive's terminal outcome fired (done at pull
        # completion, or cancel at close) -- whoever sets it first wins,
        # under _devpull_lock, so a pull landing during close cannot
        # double-resolve the future.
        self.resolved = False


class NativeWorkerBase:
    kind = "worker"

    def __init__(self):
        lib = load()
        if lib is None:
            raise RuntimeError(f"native engine unavailable: {_lib_err}")
        self._lib = lib
        self.worker_id = uuid.uuid4().hex
        self._h = None
        self._address_blob: Optional[bytes] = None
        self._conn_cache: dict[int, NativeConn] = {}
        # devpull extension state (sw_engine.h "devpull"): the engine owns
        # the wire + matching; this wrapper owns the pulls.
        self._devpull_key: Optional[int] = None
        self._xfer_mgr = None
        # msg_id -> entry for every surfaced descriptor.  Matching lives in
        # the ENGINE (descriptor records share its FIFO unexpected stream);
        # this wrapper only runs pulls and completes claimed receives.
        self._devpull_entries: dict[int, _PendingPull] = {}
        self._devpull_claimed: list[_PendingPull] = []
        self._devpull_lock = threading.Lock()
        # swtrace observability (DESIGN.md §13): lifecycle events and the
        # counter registry live in the ENGINE (TraceRing / Counters in
        # sw_engine.cpp, pulled through sw_trace / sw_counters); the
        # wrapper adds the per-worker stage scope (device placement runs
        # in Python) and the flight-recorder fault triggers.
        self._faulted = False
        # Armed-state cached at construction, like the Python engine's
        # self._trace: the off path must stay env-lookup-free per op.
        self._swtrace_on = swtrace.active()
        self.stage_scope = perf.StageScope()
        self._event_key: Optional[int] = None
        swtrace.register_worker(self)
        telemetry.register_worker(self)

    # ------------------------------------------------------ session events
    def _install_events(self) -> None:
        """Register the engine-event callback (sw_set_event_cb): session
        resume / expiry are flight-recorder dump triggers (DESIGN.md §14)
        and the resume events recorded in the engine's trace ring must
        reach the post-mortem dump.  Armed only when swtrace is active --
        the default path takes no per-event trampoline."""
        if not self._swtrace_on or not config.session_enabled():
            return
        wself = weakref.ref(self)

        def dispatch(event: str, conn_id: int) -> None:
            s = wself()
            if s is None:
                return
            if event == "session-expired":
                s._faulted = True
            swtrace.flight_dump(event, s)

        self._event_key = _register(dispatch, None)
        self._lib.sw_set_event_cb(self._h, _on_event, self._event_key)

    # --------------------------------------------------------- observability
    @property
    def trace_label(self) -> str:
        return f"{self.kind}-{self.worker_id[:8]}"

    def trace_events(self) -> list:
        """The engine-side swtrace ring, pulled through ``sw_trace`` and
        reshaped to the Python ring's event tuples ([] when tracing off
        or the handle is gone)."""
        if self._h is None:
            return []
        cap = 256 + 224 * config.trace_ring_size()
        buf = ctypes.create_string_buffer(cap)
        n = self._lib.sw_trace(self._h, buf, cap)
        if n <= 0:
            return []
        try:
            raw = json.loads(buf.value.decode(errors="replace"))
        except ValueError:
            return []
        return [(e.get("t", 0.0), e.get("ev", ""), int(e.get("tag", 0)),
                 int(e.get("conn", 0)), int(e.get("n", 0)),
                 e.get("reason", ""), 0.0) for e in raw]

    def counters_snapshot(self) -> dict:
        """The engine's counter registry (``sw_counters``) in the shared
        COUNTER_NAMES vocabulary, with the process-global counters
        (staging pool, reconnects) overlaid -- same shape as the Python
        engine's ``Worker.counters_snapshot``."""
        snap = {name: 0 for name in swtrace.COUNTER_NAMES}
        if self._h is not None:
            buf = ctypes.create_string_buffer(2048)
            n = self._lib.sw_counters(self._h, buf, 2048)
            if n > 0:
                try:
                    for key, val in json.loads(buf.value.decode()).items():
                        if key in snap:
                            snap[key] = int(val)
                except ValueError:
                    pass
        return swtrace.merge_global_counters(snap)

    def hists_snapshot(self) -> dict:
        """swpulse (DESIGN.md §25): the engine's log-bucket histograms
        (``sw_hists``) in the shared HIST_NAMES vocabulary -- same shape
        as the Python engine's ``Worker.hists_snapshot`` (name -> 64
        bucket counts)."""
        snap = {name: [0] * swtrace.HIST_BUCKETS
                for name in swtrace.HIST_NAMES}
        if self._h is not None:
            cap = 16384
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.sw_hists(self._h, buf, cap)
            if n > 0:
                try:
                    for key, row in json.loads(buf.value.decode()).items():
                        if key in snap and len(row) == swtrace.HIST_BUCKETS:
                            snap[key] = [int(v) for v in row]
                except (ValueError, TypeError):
                    pass
        return snap

    def gauges_snapshot(self) -> dict:
        """The engine's live per-conn gauges (``sw_gauges``; rendered on
        the engine thread) with the process-global staging-pool occupancy
        overlaid -- same shape as the Python engine's
        ``Worker.gauges_snapshot`` (DESIGN.md §15)."""
        snap: dict = {"conns": {}, "posted_recvs": 0, "uring_depth": 0}
        if self._h is not None:
            cap = 65536
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.sw_gauges(self._h, buf, cap)
            if n < -1:
                # Snapshot outgrew the buffer (-n = needed bytes); retry
                # sized with headroom for conns added meanwhile.
                cap = -n + 4096
                buf = ctypes.create_string_buffer(cap)
                n = self._lib.sw_gauges(self._h, buf, cap)
            if n > 0:
                try:
                    raw = json.loads(buf.value.decode())
                    snap["posted_recvs"] = int(raw.get("posted_recvs", 0))
                    # §24: submission-ring depth, 0 when the uring core
                    # is dark (seed parity) or the build predates it.
                    snap["uring_depth"] = int(raw.get("uring_depth", 0))
                    snap["conns"] = {
                        int(cid): {k: int(v) for k, v in g.items()}
                        for cid, g in raw.get("conns", {}).items()
                    }
                except (ValueError, TypeError):
                    pass
        return telemetry.merge_global_gauges(snap)

    def _flight_fail(self, fail):
        """Wrap an op's fail callback with the flight-recorder trigger
        (first non-cancel failure dumps).  Identity when tracing/flight
        are off -- no per-op closure on the default path."""
        if not self._swtrace_on:
            return fail
        wself = weakref.ref(self)

        def traced_fail(reason: str):
            s = wself()
            if s is not None and "cancel" not in reason.lower():
                s._faulted = True
                swtrace.flight_dump("op-failed", s, reason)
            if fail is not None:
                fail(reason)

        return traced_fail

    @property
    def status(self) -> int:
        if self._h is None:
            return state.VOID
        return int(self._lib.sw_status(self._h))

    def _require_running(self) -> None:
        if self.status != state.RUNNING:
            raise StarwayStateError(
                f"starway {self.kind} is not in a running state "
                f"(status={state.NAMES.get(self.status, self.status)})"
            )

    def _conn(self, conn_id: int) -> NativeConn:
        c = self._conn_cache.get(conn_id)
        if c is None:
            c = self._conn_cache[conn_id] = NativeConn(self, conn_id)
        return c

    # ------------------------------------------------------------- ops
    @staticmethod
    def _mv_pointer(mv: memoryview):
        """(address, keepalive) for a flat memoryview.  Writable views are
        zero-copy; readonly payloads (bytes) take one copy."""
        if len(mv) == 0:
            return 0, None
        if not mv.readonly:
            keep = ctypes.c_char.from_buffer(mv)
            return ctypes.addressof(keep), keep
        keep = (ctypes.c_char * len(mv)).from_buffer_copy(mv)
        return ctypes.addressof(keep), keep

    # ---------------------------------------------------------- devpull
    def _install_devpull(self) -> None:
        """Register the descriptor callback + advertise capability; called
        before listen/connect (the handshake carries the negotiation).
        Advertised only when the jax backend is already up -- same
        semantics as the Python engine's handshake probe."""
        from .. import device as _device

        if not _device.devpull_supported():
            return
        wself = weakref.ref(self)

        def dispatch(conn_id, tag, body, msg_id, rc, recv_ctx):
            s = wself()
            if s is not None:
                s._on_devpull_native(conn_id, tag, body, msg_id, rc, recv_ctx)

        def dispatch_claim(remote_id, recv_ctx, flags):
            s = wself()
            if s is not None:
                s._on_devpull_claim_native(remote_id, recv_ctx, flags)

        self._devpull_key = _register(dispatch, dispatch_claim)
        self._lib.sw_set_devpull(self._h, 1, _on_devpull, _on_devpull_claim,
                                 self._devpull_key)

    def transfer_manager(self):
        from .. import device as _device

        with self._devpull_lock:
            if self._xfer_mgr is None:
                if not _device.devpull_supported():
                    return None
                self._xfer_mgr = _device.TransferManager(config.advertised_host())
            return self._xfer_mgr

    @staticmethod
    def _claim_from_rec(entry: _PendingPull, rec) -> None:
        # rec = (done_wrapped, fail, mv, owner, keep, user_done)
        user_done = rec[5] if len(rec) > 5 else rec[0]
        owner = rec[3]
        sink = owner if _is_device_sink(owner) else None
        entry.claimed = (user_done, rec[1], None if sink else rec[2], sink)

    def _on_devpull_native(self, conn_id: int, tag: int, body: bytes,
                           msg_id: int, rc: int, recv_ctx: int) -> None:
        """Engine-thread callback: a descriptor arrived and the ENGINE
        already matched it (rc 1 claimed / -1 truncated / 0 queued in its
        FIFO unexpected stream).  Pull EAGERLY whatever the outcome -- the
        sender's buffer must be released and a flush barrier behind the
        descriptor must be able to complete (the engine withholds the
        FLUSH_ACK until sw_devpull_resolved)."""
        fail_trunc = None
        try:
            desc = json.loads(body.decode())
            entry = _PendingPull(desc, conn_id, msg_id, tag)
            with self._devpull_lock:
                self._devpull_entries[msg_id] = entry
            if rc != 0:
                rec = _take(recv_ctx)
                if rc == -1:
                    entry.discard = True  # drain pull releases the sender
                    fail_trunc = rec[1] if rec is not None else None
                elif rec is not None:
                    with self._devpull_lock:
                        self._claim_from_rec(entry, rec)
                        self._devpull_claimed.append(entry)
        except Exception:
            logger.exception("starway devpull descriptor handling failed")
            # The engine may have queued a record for this descriptor; it
            # has no wrapper entry, so it must not eat a future receive.
            self._lib.sw_devpull_purge(self._h, msg_id)
            self._lib.sw_devpull_resolved(self._h, conn_id, msg_id, 0)
            return
        if fail_trunc is not None:
            from ..errors import REASON_TRUNCATED

            try:
                fail_trunc(REASON_TRUNCATED)
            except Exception:
                logger.exception("starway devpull truncation callback raised")
        self._start_pull(entry)

    def _on_devpull_claim_native(self, remote_id: int, recv_ctx: int,
                                 flags: int) -> None:
        """A later receive claimed a queued descriptor record inside the
        engine's matcher (or was failed there for truncation, flags=1)."""
        complete_now = None
        with self._devpull_lock:
            entry = self._devpull_entries.get(remote_id)
        if entry is None:
            # Stale claim (record outlived its wrapper entry -- descriptor
            # handling failed, or the worker is closing): cancel the
            # receive rather than orphan it.
            rec = _take(recv_ctx) if recv_ctx else None
            if rec is not None and rec[1] is not None:
                from ..errors import REASON_CANCELLED

                try:
                    rec[1](REASON_CANCELLED)
                except Exception:
                    logger.exception("starway devpull cancel callback raised")
            return
        if flags == 1:
            # Engine fired the receive's truncation failure and consumed
            # the record; no claim will ever arrive for this entry.
            with self._devpull_lock:
                entry.discard = True
                self._devpull_entries.pop(entry.msg_id, None)
            return
        rec = _take(recv_ctx)
        if rec is None:
            return
        with self._devpull_lock:
            self._claim_from_rec(entry, rec)
            if entry.array is not None and not entry.resolved:
                entry.resolved = True
                complete_now = entry.array
            else:
                # Pull outstanding -- or failed, in which case the receive
                # stays pending (peer-death semantics) until the close
                # sweep cancels it.
                self._devpull_claimed.append(entry)
        if complete_now is not None:
            self._finish_entry(entry, complete_now)

    def _start_pull(self, entry: _PendingPull) -> None:
        mgr = self.transfer_manager()
        if mgr is None:
            self._pull_failed(entry, "transfer server unavailable")
            return
        device = None
        if entry.claimed is not None and entry.claimed[3] is not None:
            device = entry.claimed[3].devbuf.device
        mgr.pull(entry.desc, device,
                 lambda arr, e=entry: self._pull_done(e, arr),
                 lambda err, e=entry: self._pull_failed(e, err))

    def _pull_done(self, entry: _PendingPull, arr) -> None:
        try:
            with self._devpull_lock:
                entry.array = arr
                deliver = entry.claimed is not None and not entry.resolved \
                    and not entry.discard
                if deliver:
                    entry.resolved = True
                if entry.discard:
                    self._devpull_entries.pop(entry.msg_id, None)
            if deliver:
                self._finish_entry(entry, arr)
            # Unclaimed entries keep the array; the engine's matcher still
            # holds the record and a later receive claims it.
        finally:
            self._lib.sw_devpull_resolved(self._h, entry.conn_id,
                                          entry.msg_id, 1)

    def _finish_entry(self, entry: _PendingPull, arr) -> None:
        """Deliver a pulled payload into its claimed receive.  Never called
        under _devpull_lock (user callbacks re-enter the API)."""
        import numpy as np

        try:
            user_done, _fail, mv, sink = entry.claimed
            if sink is not None:
                sink.accept_device(arr)
            elif mv is not None:
                host = np.asarray(arr).view(np.uint8).reshape(-1)
                mv[: entry.nbytes] = memoryview(host)[: entry.nbytes]
            with self._devpull_lock:
                if entry in self._devpull_claimed:
                    self._devpull_claimed.remove(entry)
                self._devpull_entries.pop(entry.msg_id, None)
            if user_done is not None:
                user_done(entry.tag, entry.nbytes)
        except Exception:
            logger.exception("starway devpull completion failed")

    def _pull_failed(self, entry: _PendingPull, err: str) -> None:
        logger.warning("starway devpull pull failed: %s", err)
        purge = False
        with self._devpull_lock:
            entry.failed = True
            purge = entry.claimed is None
        if purge:
            # Remove the engine matcher's queued record so it cannot eat
            # future receives.  The wrapper entry stays in the dict: a
            # claim racing the purge then finds a failed entry and its
            # receive goes pending (peer-death semantics) instead of being
            # silently dropped; the dict entry is reclaimed at close.
            self._lib.sw_devpull_purge(self._h, entry.msg_id)
        # A claimed receive stays pending (peer-death semantics) until the
        # close sweep cancels it (_drop_devpull).
        self._lib.sw_devpull_resolved(self._h, entry.conn_id, entry.msg_id, 0)

    def submit_devpull(self, conn, desc: dict, tag: int, done, fail,
                       owner=None) -> None:
        self._require_running()
        conn_id = conn.conn_id if isinstance(conn, NativeConn) else 0
        body = json.dumps(desc, separators=(",", ":")).encode()
        key = _register(done, self._flight_fail(fail), owner)
        rc = self._lib.sw_send_devpull(self._h, conn_id, tag, body, len(body),
                                       _on_done, _on_fail, key)
        if rc != 0:
            _take(key)
            raise StarwayStateError("starway native send rejected (not running)")

    def submit_send(self, conn, view, tag: int, done, fail, owner=None,
                    timeout=None) -> None:
        self._require_running()
        conn_id = conn.conn_id if isinstance(conn, NativeConn) else 0
        mv = memoryview(view)
        addr, keep = self._mv_pointer(mv)
        key = _register(done, self._flight_fail(fail))
        # The payload must outlive the op past local completion (rndv sends
        # stream after `done` fires); the engine's release callback is the
        # only thing allowed to drop this reference.
        rel_key = _register(None, None, mv, owner, keep)
        rc = self._lib.sw_send(self._h, conn_id, addr, len(mv), tag,
                               _on_done, _on_fail, key, _on_release, rel_key,
                               _timeout_s(timeout))
        if rc != 0:
            _take(key)
            _take(rel_key)
            raise StarwayStateError("starway native send rejected (not running)")

    def post_recv(self, buf, tag: int, mask: int, done, fail, owner=None,
                  timeout=None) -> None:
        self._require_running()
        user_done = done
        if isinstance(buf, memoryview):
            mv = buf
        else:
            mv = buf.host_staging()  # DeviceRecvSink
            inner_done = done

            def done(st, ln, _sink=buf, _cb=inner_done):
                _sink.finalize_from_host(ln)
                _cb(st, ln)

        if mv.readonly:
            raise TypeError("receive buffer must be writable")
        addr, keep = self._mv_pointer(mv)
        # Slot 5 (user_done) lets a devpull claim complete the receive via
        # the device path instead of the staging-wrapped `done`.
        key = _register(done, self._flight_fail(fail), mv, owner, keep,
                        user_done)
        rc = self._lib.sw_recv(self._h, addr, len(mv), tag, mask, _on_recv,
                               _on_fail, key, _timeout_s(timeout))
        if rc != 0:
            _take(key)
            raise StarwayStateError("starway native recv rejected (not running)")

    def submit_flush(self, done, fail, conns=None, timeout=None) -> None:
        self._require_running()
        key = _register(done, self._flight_fail(fail))
        t = _timeout_s(timeout)
        if conns:
            conn_id = conns[0].conn_id if isinstance(conns[0], NativeConn) else 0
            rc = self._lib.sw_flush(self._h, conn_id, 1, _on_done, _on_fail, key, t)
        else:
            rc = self._lib.sw_flush(self._h, 0, 0, _on_done, _on_fail, key, t)
        if rc != 0:
            _take(key)
            raise StarwayStateError("starway native flush rejected (not running)")

    def close(self, cb) -> None:
        self._require_running()
        if self._faulted:
            # Post-mortem snapshot before teardown (DESIGN.md §13).
            swtrace.flight_dump("close-after-fault", self)

        def cb_devpull_cleanup(_cb=cb):
            # Park the engine ring's final contents for post-close
            # consumers; the handle stays valid until sw_free.
            swtrace.retire(self)
            self._drop_devpull()
            if _cb is not None:
                _cb()

        key = _register(cb_devpull_cleanup, None)
        rc = self._lib.sw_close(self._h, _on_done, key)
        if rc != 0:
            _take(key)
            raise StarwayStateError(
                f"starway {self.kind} is not in a running state (native close rejected)"
            )

    def _drop_devpull(self) -> None:
        if self._event_key is not None:
            _take(self._event_key)
            self._event_key = None
        if self._devpull_key is not None:
            _take(self._devpull_key)
            self._devpull_key = None
        with self._devpull_lock:
            mgr, self._xfer_mgr = self._xfer_mgr, None
            self._devpull_entries.clear()
            cancelled = [e for e in self._devpull_claimed if not e.resolved]
            for e in cancelled:
                e.resolved = True
            self._devpull_claimed.clear()
        # Claimed receives whose pull never landed get the standard close
        # cancel (they were removed from the C++ matcher, so its own
        # cancel sweep cannot reach them).
        if cancelled:
            from ..errors import REASON_CANCELLED

            for e in cancelled:
                fail = e.claimed[1]
                if fail is not None:
                    try:
                        fail(REASON_CANCELLED)
                    except Exception:
                        logger.exception("starway devpull cancel callback raised")
        if mgr is not None:
            # Dropping the transfer server cancels unpulled offers (the
            # close-cancels-in-flight contract for device sends).
            mgr.close()

    def force_close(self) -> None:
        pass  # sw_free in __del__ handles signalling

    def get_worker_address(self) -> bytes:
        if self._address_blob is None:
            self._address_blob = json.dumps(
                {"worker_id": self.worker_id, "host": config.advertised_host(),
                 "port": 0, "fabric": "starway-tpu"}
            ).encode()
        return self._address_blob

    def _perf_transport(self, conn) -> str:
        self._require_running()
        if isinstance(conn, NativeConn) and conn.transports() == [("shm", "sm")]:
            return "sm"
        return "tcp"

    def evaluate_perf(self, conn, msg_size: int) -> float:
        # Per-endpoint first (live-calibrated, perf.autocalibrate[_ep]),
        # transport-class model otherwise.
        return perf.conn_estimate(conn, self._perf_transport(conn), msg_size)

    def evaluate_perf_detail(self, conn, msg_size: int) -> dict:
        detail = perf.conn_estimate_detail(conn, self._perf_transport(conn),
                                           msg_size, scope=self.stage_scope)
        detail["counters"] = self.counters_snapshot()
        detail["telemetry"] = telemetry.detail_for(self)
        return detail

    def __del__(self):
        try:
            swtrace.retire(self)
        except Exception:
            pass
        try:
            self._drop_devpull()
        except Exception:
            pass
        try:
            if self._h is not None:
                self._lib.sw_free(self._h)
                self._h = None
        except Exception:
            pass


class NativeClientWorker(NativeWorkerBase):
    kind = "client"

    def __init__(self):
        super().__init__()
        self._h = self._lib.sw_client_new(self.worker_id.encode())
        self._connected = False

    @property
    def primary_conn(self) -> Optional[NativeConn]:
        cid = int(self._lib.sw_primary_conn(self._h))
        return self._conn(cid) if cid else None

    def _do_connect(self, host: str, port: int, mode: str, cb) -> None:
        if self.status != state.VOID:
            raise StarwayStateError(
                "starway client supports a single connect "
                f"(status={state.NAMES.get(self.status, self.status)})"
            )
        self._install_devpull()
        self._install_events()
        key = _register(cb, None)
        rc = self._lib.sw_client_connect(
            self._h, host.encode(), port, mode.encode(), _on_status, key
        )
        if rc != 0:
            _take(key)
            raise StarwayStateError("starway client supports a single connect")

    def connect(self, addr: str, port: int, cb, timeout=None) -> None:
        # Per-call timeout override rides the env knob on the native engine
        # (the C engine samples STARWAY_CONNECT_TIMEOUT at connect); the api
        # layer additionally bounds the attempt with asyncio.wait_for.
        del timeout
        self._do_connect(addr, port, "socket", cb)

    def connect_address(self, blob: bytes, cb, timeout=None) -> None:
        del timeout
        from . import frames

        info = frames.unpack_json_body(blob)
        self._do_connect(info.get("host", "127.0.0.1"), int(info.get("port", 0)),
                         "address", cb)


class NativeServerWorker(NativeWorkerBase):
    kind = "server"

    def __init__(self):
        super().__init__()
        self._h = self._lib.sw_server_new(self.worker_id.encode())
        self._accept_key: Optional[int] = None
        self._eps: dict[int, object] = {}
        self._eps_lock = threading.Lock()
        self._user_accept_cb = None

    def set_accept_cb(self, cb) -> None:
        self._user_accept_cb = cb

    def _on_native_accept(self, conn_id: int) -> None:
        from .endpoint import ServerEndpoint

        ep = ServerEndpoint(self._conn(conn_id))
        with self._eps_lock:
            self._eps[conn_id] = ep
        if self._user_accept_cb is not None:
            self._user_accept_cb(ep)

    def _install_accept(self) -> None:
        # Weakref dispatch: the persistent registry entry must not keep the
        # worker alive (it would never be GC'd and sw_free never called).
        wself = weakref.ref(self)

        def dispatch(conn_id: int) -> None:
            s = wself()
            if s is not None:
                s._on_native_accept(conn_id)

        self._accept_key = _register(dispatch, None)
        self._lib.sw_server_set_accept_cb(self._h, _on_accept, self._accept_key)

    def _drop_accept(self) -> None:
        if self._accept_key is not None:
            _take(self._accept_key)
            self._accept_key = None

    def close(self, cb) -> None:
        def cb_and_cleanup():
            self._drop_accept()
            if cb is not None:
                cb()

        super().close(cb_and_cleanup)

    def __del__(self):
        try:
            self._drop_accept()
        except Exception:
            pass
        try:
            super().__del__()
        except Exception:
            pass

    def listen(self, addr: str, port: int) -> None:
        if self.status != state.VOID:
            raise StarwayStateError("starway server already listening or closed")
        self._install_accept()
        self._install_devpull()
        self._install_events()
        rc = int(self._lib.sw_server_listen(self._h, addr.encode(), port))
        if rc <= 0:
            raise OSError(-rc, f"native listen failed on {addr}:{port}")
        self._address_blob = json.dumps(
            {"worker_id": self.worker_id,
             "host": addr if addr not in ("0.0.0.0", "") else config.advertised_host(),
             "port": rc, "fabric": "starway-tpu"}
        ).encode()

    def listen_address(self) -> bytes:
        if self.status != state.VOID:
            raise StarwayStateError("starway server already listening or closed")
        self._install_accept()
        self._install_devpull()
        self._install_events()
        rc = int(self._lib.sw_server_listen(self._h, b"0.0.0.0", 0))
        if rc <= 0:
            raise OSError(-rc, "native listen_address failed")
        self._address_blob = json.dumps(
            {"worker_id": self.worker_id, "host": config.advertised_host(),
             "port": rc, "fabric": "starway-tpu"}
        ).encode()
        return self._address_blob

    def list_clients(self) -> set:
        with self._eps_lock:
            return set(self._eps.values())
