"""ctypes bridge to the C++ native engine (native/sw_engine.cpp).

The C ABI this module mirrors is declared authoritatively in
``native/sw_engine.h`` — the analogue of the reference's hand-written type
stub (src/starway/_bindings.pyi), documenting every function, callback
signature, and buffer-lifetime rule crossing the language boundary.  Keep
``load()``'s argtypes in lockstep with that header.

Presents the same worker protocol as the pure-Python engine
(core/engine.py): ``NativeClientWorker`` / ``NativeServerWorker`` with
``submit_send`` / ``post_recv`` / ``submit_flush`` / ``close`` / endpoint
introspection, so the api layer swaps engines transparently.  The native
engine covers the host paths -- TCP and the negotiated same-host
shared-memory rings (``sm``, core/shmring.py) -- speaking the same wire
protocol as the Python engine, so mixed-engine processes interoperate over
either.  The in-process fast path and device plane stay in Python, which
is why native selection requires inproc-free mode (``STARWAY_TLS=tcp`` or
``tcp,sm``, plus ``STARWAY_NATIVE=1``).

Lifetime/GIL notes: callbacks cross from the engine thread through ctypes
trampolines, which acquire the GIL.  Each pending op holds its Python buffer
and callbacks in a registry keyed by an integer handle passed through the
C ``ctx`` pointer, so nothing is garbage-collected mid-flight.
"""

from __future__ import annotations

import ctypes
import itertools
import json
import threading
import uuid
import weakref
from typing import Optional

from .. import config
from ..errors import StarwayStateError
from . import state
from .engine import logger

_lib = None
_lib_err: Optional[str] = None

_DONE_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p)
_FAIL_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_char_p)
_RECV_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64)
_ACCEPT_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_uint64)
_STATUS_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_char_p)


def load() -> Optional[ctypes.CDLL]:
    """Load (building on first use) the native engine; None if unavailable."""
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    try:
        from .. import native_build

        path = native_build.ensure_built()
        lib = ctypes.CDLL(str(path))
        lib.sw_version.restype = ctypes.c_char_p
        lib.sw_client_new.restype = ctypes.c_void_p
        lib.sw_client_new.argtypes = [ctypes.c_char_p]
        lib.sw_server_new.restype = ctypes.c_void_p
        lib.sw_server_new.argtypes = [ctypes.c_char_p]
        lib.sw_client_connect.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
            _STATUS_CB, ctypes.c_void_p,
        ]
        lib.sw_server_listen.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
        lib.sw_server_set_accept_cb.argtypes = [ctypes.c_void_p, _ACCEPT_CB, ctypes.c_void_p]
        lib.sw_send.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64,
            ctypes.c_uint64, _DONE_CB, _FAIL_CB, ctypes.c_void_p,
            _DONE_CB, ctypes.c_void_p,
        ]
        lib.sw_recv.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_uint64, _RECV_CB, _FAIL_CB, ctypes.c_void_p,
        ]
        lib.sw_flush.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int, _DONE_CB, _FAIL_CB,
            ctypes.c_void_p,
        ]
        lib.sw_close.argtypes = [ctypes.c_void_p, _DONE_CB, ctypes.c_void_p]
        lib.sw_status.argtypes = [ctypes.c_void_p]
        lib.sw_primary_conn.argtypes = [ctypes.c_void_p]
        lib.sw_primary_conn.restype = ctypes.c_uint64
        lib.sw_list_conns.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64), ctypes.c_int
        ]
        lib.sw_conn_info.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_int
        ]
        lib.sw_free.argtypes = [ctypes.c_void_p]
        _lib = lib
    except Exception as e:  # toolchain/build failure => Python engine
        _lib_err = str(e)
        logger.debug("starway native engine unavailable: %s", e)
    return _lib


def available() -> bool:
    return load() is not None


# ----------------------------------------------------------- op registry

_op_ids = itertools.count(1)
_ops: dict[int, tuple] = {}
_ops_lock = threading.Lock()


def _register(*payload) -> int:
    key = next(_op_ids)
    with _ops_lock:
        _ops[key] = payload
    return key


def _take(key: int):
    with _ops_lock:
        return _ops.pop(key, None)


def _peek(key: int):
    with _ops_lock:
        return _ops.get(key)


@_DONE_CB
def _on_done(ctx):
    rec = _take(ctx)
    if rec and rec[0] is not None:
        try:
            rec[0]()
        except Exception:
            logger.exception("starway native done callback raised")


@_FAIL_CB
def _on_fail(ctx, reason):
    rec = _take(ctx)
    if rec and rec[1] is not None:
        try:
            rec[1]((reason or b"").decode())
        except Exception:
            logger.exception("starway native fail callback raised")


@_RECV_CB
def _on_recv(ctx, sender_tag, length):
    rec = _take(ctx)
    if rec and rec[0] is not None:
        try:
            rec[0](int(sender_tag), int(length))
        except Exception:
            logger.exception("starway native recv callback raised")


@_DONE_CB
def _on_release(ctx):
    # Buffer-keepalive release: the engine is finished with the payload
    # (fully written or cancelled).  Fired separately from the op's done
    # callback because rendezvous sends complete locally at header-write
    # while the payload keeps streaming.
    _take(ctx)


@_STATUS_CB
def _on_status(ctx, status):
    rec = _take(ctx)
    if rec and rec[0] is not None:
        try:
            rec[0]((status or b"").decode())
        except Exception:
            logger.exception("starway native status callback raised")


@_ACCEPT_CB
def _on_accept(ctx, conn_id):
    rec = _peek(ctx)  # persistent registration: not popped
    if rec and rec[0] is not None:
        try:
            rec[0](int(conn_id))
        except Exception:
            logger.exception("starway native accept callback raised")


# ------------------------------------------------------------- endpoints


class NativeConn:
    """Lightweight stand-in for the Python engine's conn objects: carries
    the native conn id plus lazily-fetched metadata."""

    kind = "tcp"

    def __init__(self, worker: "NativeWorkerBase", conn_id: int):
        self.worker = worker
        self.conn_id = conn_id
        self._transports: Optional[list[tuple[str, str]]] = None

    def _info(self) -> dict:
        lib = load()
        buf = ctypes.create_string_buffer(512)
        n = lib.sw_conn_info(self.worker._h, self.conn_id, buf, 512)
        if n <= 0:
            return {}
        return json.loads(buf.value.decode())

    @property
    def peer_name(self) -> str:
        return self._info().get("name", "")

    @property
    def alive(self) -> bool:
        return bool(self._info().get("alive", 0))

    @property
    def mode(self) -> str:
        return self._info().get("mode", "socket")

    @property
    def local_addr(self) -> str:
        return self._info().get("local_addr", "")

    @property
    def local_port(self) -> int:
        return int(self._info().get("local_port", 0))

    @property
    def remote_addr(self) -> str:
        return self._info().get("remote_addr", "")

    @property
    def remote_port(self) -> int:
        return int(self._info().get("remote_port", 0))

    def transports(self) -> list[tuple[str, str]]:
        # The transport is fixed at handshake time: memoize so per-message
        # callers (evaluate_perf) pay the FFI round-trip once.
        if self._transports is None:
            if self._info().get("transport") == "sm":
                self._transports = [("shm", "sm")]
            else:
                dev = "lo" if self.remote_addr.startswith("127.") else "eth0"
                self._transports = [(dev, "tcp+native")]
        return self._transports


# --------------------------------------------------------------- workers


class NativeWorkerBase:
    kind = "worker"

    def __init__(self):
        lib = load()
        if lib is None:
            raise RuntimeError(f"native engine unavailable: {_lib_err}")
        self._lib = lib
        self.worker_id = uuid.uuid4().hex
        self._h = None
        self._address_blob: Optional[bytes] = None
        self._conn_cache: dict[int, NativeConn] = {}

    @property
    def status(self) -> int:
        if self._h is None:
            return state.VOID
        return int(self._lib.sw_status(self._h))

    def _require_running(self) -> None:
        if self.status != state.RUNNING:
            raise StarwayStateError(
                f"starway {self.kind} is not in a running state "
                f"(status={state.NAMES.get(self.status, self.status)})"
            )

    def _conn(self, conn_id: int) -> NativeConn:
        c = self._conn_cache.get(conn_id)
        if c is None:
            c = self._conn_cache[conn_id] = NativeConn(self, conn_id)
        return c

    # ------------------------------------------------------------- ops
    @staticmethod
    def _mv_pointer(mv: memoryview):
        """(address, keepalive) for a flat memoryview.  Writable views are
        zero-copy; readonly payloads (bytes) take one copy."""
        if len(mv) == 0:
            return 0, None
        if not mv.readonly:
            keep = ctypes.c_char.from_buffer(mv)
            return ctypes.addressof(keep), keep
        keep = (ctypes.c_char * len(mv)).from_buffer_copy(mv)
        return ctypes.addressof(keep), keep

    def submit_send(self, conn, view, tag: int, done, fail, owner=None) -> None:
        self._require_running()
        conn_id = conn.conn_id if isinstance(conn, NativeConn) else 0
        mv = memoryview(view)
        addr, keep = self._mv_pointer(mv)
        key = _register(done, fail)
        # The payload must outlive the op past local completion (rndv sends
        # stream after `done` fires); the engine's release callback is the
        # only thing allowed to drop this reference.
        rel_key = _register(None, None, mv, owner, keep)
        rc = self._lib.sw_send(self._h, conn_id, addr, len(mv), tag,
                               _on_done, _on_fail, key, _on_release, rel_key)
        if rc != 0:
            _take(key)
            _take(rel_key)
            raise StarwayStateError("starway native send rejected (not running)")

    def post_recv(self, buf, tag: int, mask: int, done, fail, owner=None) -> None:
        self._require_running()
        if isinstance(buf, memoryview):
            mv = buf
        else:
            mv = buf.host_staging()  # DeviceRecvSink
            inner_done = done

            def done(st, ln, _sink=buf, _cb=inner_done):
                _sink.finalize_from_host(ln)
                _cb(st, ln)

        if mv.readonly:
            raise TypeError("receive buffer must be writable")
        addr, keep = self._mv_pointer(mv)
        key = _register(done, fail, mv, owner, keep)
        rc = self._lib.sw_recv(self._h, addr, len(mv), tag, mask, _on_recv, _on_fail, key)
        if rc != 0:
            _take(key)
            raise StarwayStateError("starway native recv rejected (not running)")

    def submit_flush(self, done, fail, conns=None) -> None:
        self._require_running()
        key = _register(done, fail)
        if conns:
            conn_id = conns[0].conn_id if isinstance(conns[0], NativeConn) else 0
            rc = self._lib.sw_flush(self._h, conn_id, 1, _on_done, _on_fail, key)
        else:
            rc = self._lib.sw_flush(self._h, 0, 0, _on_done, _on_fail, key)
        if rc != 0:
            _take(key)
            raise StarwayStateError("starway native flush rejected (not running)")

    def close(self, cb) -> None:
        self._require_running()
        key = _register(cb, None)
        rc = self._lib.sw_close(self._h, _on_done, key)
        if rc != 0:
            _take(key)
            raise StarwayStateError(
                f"starway {self.kind} is not in a running state (native close rejected)"
            )

    def force_close(self) -> None:
        pass  # sw_free in __del__ handles signalling

    def get_worker_address(self) -> bytes:
        if self._address_blob is None:
            self._address_blob = json.dumps(
                {"worker_id": self.worker_id, "host": config.advertised_host(),
                 "port": 0, "fabric": "starway-tpu"}
            ).encode()
        return self._address_blob

    def evaluate_perf(self, conn, msg_size: int) -> float:
        from .. import perf

        self._require_running()
        transport = "tcp"
        if isinstance(conn, NativeConn) and conn.transports() == [("shm", "sm")]:
            transport = "sm"
        return perf.estimate(transport, msg_size)

    def __del__(self):
        try:
            if self._h is not None:
                self._lib.sw_free(self._h)
                self._h = None
        except Exception:
            pass


class NativeClientWorker(NativeWorkerBase):
    kind = "client"

    def __init__(self):
        super().__init__()
        self._h = self._lib.sw_client_new(self.worker_id.encode())
        self._connected = False

    @property
    def primary_conn(self) -> Optional[NativeConn]:
        cid = int(self._lib.sw_primary_conn(self._h))
        return self._conn(cid) if cid else None

    def _do_connect(self, host: str, port: int, mode: str, cb) -> None:
        if self.status != state.VOID:
            raise StarwayStateError(
                "starway client supports a single connect "
                f"(status={state.NAMES.get(self.status, self.status)})"
            )
        key = _register(cb, None)
        rc = self._lib.sw_client_connect(
            self._h, host.encode(), port, mode.encode(), _on_status, key
        )
        if rc != 0:
            _take(key)
            raise StarwayStateError("starway client supports a single connect")

    def connect(self, addr: str, port: int, cb) -> None:
        self._do_connect(addr, port, "socket", cb)

    def connect_address(self, blob: bytes, cb) -> None:
        info = json.loads(bytes(blob).decode())
        self._do_connect(info.get("host", "127.0.0.1"), int(info.get("port", 0)),
                         "address", cb)


class NativeServerWorker(NativeWorkerBase):
    kind = "server"

    def __init__(self):
        super().__init__()
        self._h = self._lib.sw_server_new(self.worker_id.encode())
        self._accept_key: Optional[int] = None
        self._eps: dict[int, object] = {}
        self._eps_lock = threading.Lock()
        self._user_accept_cb = None

    def set_accept_cb(self, cb) -> None:
        self._user_accept_cb = cb

    def _on_native_accept(self, conn_id: int) -> None:
        from .endpoint import ServerEndpoint

        ep = ServerEndpoint(self._conn(conn_id))
        with self._eps_lock:
            self._eps[conn_id] = ep
        if self._user_accept_cb is not None:
            self._user_accept_cb(ep)

    def _install_accept(self) -> None:
        # Weakref dispatch: the persistent registry entry must not keep the
        # worker alive (it would never be GC'd and sw_free never called).
        wself = weakref.ref(self)

        def dispatch(conn_id: int) -> None:
            s = wself()
            if s is not None:
                s._on_native_accept(conn_id)

        self._accept_key = _register(dispatch, None)
        self._lib.sw_server_set_accept_cb(self._h, _on_accept, self._accept_key)

    def _drop_accept(self) -> None:
        if self._accept_key is not None:
            _take(self._accept_key)
            self._accept_key = None

    def close(self, cb) -> None:
        def cb_and_cleanup():
            self._drop_accept()
            if cb is not None:
                cb()

        super().close(cb_and_cleanup)

    def __del__(self):
        try:
            self._drop_accept()
        except Exception:
            pass
        try:
            super().__del__()
        except Exception:
            pass

    def listen(self, addr: str, port: int) -> None:
        if self.status != state.VOID:
            raise StarwayStateError("starway server already listening or closed")
        self._install_accept()
        rc = int(self._lib.sw_server_listen(self._h, addr.encode(), port))
        if rc <= 0:
            raise OSError(-rc, f"native listen failed on {addr}:{port}")
        self._address_blob = json.dumps(
            {"worker_id": self.worker_id,
             "host": addr if addr not in ("0.0.0.0", "") else config.advertised_host(),
             "port": rc, "fabric": "starway-tpu"}
        ).encode()

    def listen_address(self) -> bytes:
        if self.status != state.VOID:
            raise StarwayStateError("starway server already listening or closed")
        self._install_accept()
        rc = int(self._lib.sw_server_listen(self._h, b"0.0.0.0", 0))
        if rc <= 0:
            raise OSError(-rc, "native listen_address failed")
        self._address_blob = json.dumps(
            {"worker_id": self.worker_id, "host": config.advertised_host(),
             "port": rc, "fabric": "starway-tpu"}
        ).encode()
        return self._address_blob

    def list_clients(self) -> set:
        with self._eps_lock:
            return set(self._eps.values())
