"""Worker lifecycle states.

Mirrors the reference's 5-state lifecycle atomic (``0 void, 1 init,
2 running, 3 to-close, 4 closed``; reference: src/bindings/main.hpp:306-376,
SURVEY.md section 2 #5/#6).  Connect/listen are once-only transitions and a
second close raises (tests/test_basic.py:485-511).
"""

VOID = 0
INIT = 1
RUNNING = 2
CLOSING = 3
CLOSED = 4

NAMES = {VOID: "VOID", INIT: "INIT", RUNNING: "RUNNING", CLOSING: "CLOSING", CLOSED: "CLOSED"}
